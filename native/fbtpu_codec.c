/* fbtpu_codec — CPython C-API msgpack event decoder.
 *
 * The decode path (codec/events.decode_events → pure-Python Unpacker)
 * costs ~30µs/record and caps every non-raw filter stage near 50k
 * lines/s; this extension decodes the same log-event subset straight
 * into Python objects (~10x). Byte-for-byte semantic twin of
 * codec/msgpack.Unpacker + codec/events._to_event:
 *   - strings decode UTF-8 with errors="replace"
 *   - unhashable map keys degrade to repr()
 *   - ext type 0 (len 8) → EventTime(sec, nsec)
 *   - any OTHER ext type raises FallbackError: the caller reruns the
 *     pure-Python decoder (ExtType construction is not worth porting)
 *   - V2 [[ts, meta], body] and legacy [ts, body] records both map to
 *     LogEvent(timestamp, body, metadata, raw-span)
 *
 * Reference precedent: the hot decode loop is C in fluent-bit too
 * (lib/msgpack-c via flb_log_event_decoder, src/flb_log_event_decoder.c).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

static PyObject *g_logevent = NULL;   /* codec.events.LogEvent */
static PyObject *g_eventtime = NULL;  /* codec.msgpack.EventTime */
static PyObject *g_fallback = NULL;   /* fbtpu_codec.FallbackError */
static PyObject *g_truncated = NULL;  /* internal: torn trailing record */

/* nesting bound: the pure-Python decoder dies with a recoverable
 * RecursionError around CPython's ~1000-frame limit; unbounded C
 * recursion would overflow the REAL stack and segfault the process on
 * a hostile buffer (b"\x91" * N). 512 covers any sane log event. */
#define MAX_DEPTH 512

typedef struct {
    const uint8_t *p;
    const uint8_t *end;
    int depth;
} rd;

static int need(rd *r, Py_ssize_t n) {
    if (r->end - r->p < n) {
        /* the Python Unpacker treats a torn tail as end-of-stream
         * (OutOfData stops iteration, the decoded prefix is returned);
         * decode_events must mirror that, so truncation gets its own
         * exception type the loop can swallow */
        PyErr_SetString(g_truncated, "truncated msgpack");
        return -1;
    }
    return 0;
}

/* every caller need()s before calling — hoisting the check here would
 * double it on the hottest decode path
 * fbtpu-lint: allow(codec-bounds) */
static uint64_t rd_be(rd *r, int n) {
    uint64_t v = 0;
    for (int i = 0; i < n; i++) v = (v << 8) | r->p[i];
    r->p += n;
    return v;
}

static PyObject *decode_obj(rd *r);

static PyObject *decode_str(rd *r, Py_ssize_t n) {
    if (need(r, n) < 0) return NULL;
    PyObject *s = PyUnicode_DecodeUTF8((const char *)r->p, n, "replace");
    r->p += n;
    return s;
}

static PyObject *decode_bin(rd *r, Py_ssize_t n) {
    if (need(r, n) < 0) return NULL;
    PyObject *b = PyBytes_FromStringAndSize((const char *)r->p, n);
    r->p += n;
    return b;
}

static PyObject *decode_ext(rd *r, int code, Py_ssize_t n) {
    if (need(r, n) < 0) return NULL;
    if (code == 0 && n == 8) {
        uint32_t sec = ((uint32_t)r->p[0] << 24) | ((uint32_t)r->p[1] << 16)
                     | ((uint32_t)r->p[2] << 8) | r->p[3];
        uint32_t nsec = ((uint32_t)r->p[4] << 24) | ((uint32_t)r->p[5] << 16)
                      | ((uint32_t)r->p[6] << 8) | r->p[7];
        r->p += 8;
        return PyObject_CallFunction(g_eventtime, "kk",
                                     (unsigned long)sec,
                                     (unsigned long)nsec);
    }
    /* non-EventTime ext: the Python decoder builds ExtType — punt */
    PyErr_SetString(g_fallback, "non-EventTime ext type");
    return NULL;
}

static PyObject *decode_array(rd *r, Py_ssize_t n) {
    PyObject *lst = PyList_New(n);
    if (!lst) return NULL;
    r->depth++;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = decode_obj(r);
        if (!it) { r->depth--; Py_DECREF(lst); return NULL; }
        PyList_SET_ITEM(lst, i, it);
    }
    r->depth--;
    return lst;
}

static PyObject *decode_map(rd *r, Py_ssize_t n) {
    PyObject *d = PyDict_New();
    if (!d) return NULL;
    r->depth++;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *k = decode_obj(r);
        if (!k) { r->depth--; Py_DECREF(d); return NULL; }
        if (PyDict_Check(k) || PyList_Check(k)) {
            /* unhashable keys degrade to repr() (msgpack.py parity) */
            PyObject *rep = PyObject_Repr(k);
            Py_DECREF(k);
            if (!rep) { Py_DECREF(d); return NULL; }
            k = rep;
        }
        PyObject *v = decode_obj(r);
        if (!v) { r->depth--; Py_DECREF(k); Py_DECREF(d); return NULL; }
        if (PyDict_SetItem(d, k, v) < 0) {
            r->depth--;
            Py_DECREF(k); Py_DECREF(v); Py_DECREF(d);
            return NULL;
        }
        Py_DECREF(k);
        Py_DECREF(v);
    }
    r->depth--;
    return d;
}

static PyObject *decode_obj(rd *r) {
    if (r->depth >= MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "msgpack nesting too deep");
        return NULL;
    }
    if (need(r, 1) < 0) return NULL;
    uint8_t b = *r->p++;
    if (b < 0x80) return PyLong_FromLong(b);
    if (b >= 0xE0) return PyLong_FromLong((long)b - 0x100);
    if (b <= 0x8F) return decode_map(r, b & 0x0F);
    if (b <= 0x9F) return decode_array(r, b & 0x0F);
    if (b <= 0xBF) return decode_str(r, b & 0x1F);
    switch (b) {
    case 0xC0: Py_RETURN_NONE;
    case 0xC2: Py_RETURN_FALSE;
    case 0xC3: Py_RETURN_TRUE;
    case 0xC4: if (need(r, 1) < 0) return NULL;
        return decode_bin(r, (Py_ssize_t)rd_be(r, 1));
    case 0xC5: if (need(r, 2) < 0) return NULL;
        return decode_bin(r, (Py_ssize_t)rd_be(r, 2));
    case 0xC6: if (need(r, 4) < 0) return NULL;
        return decode_bin(r, (Py_ssize_t)rd_be(r, 4));
    case 0xC7: {
        if (need(r, 2) < 0) return NULL;
        Py_ssize_t n = (Py_ssize_t)rd_be(r, 1);
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xC8: {
        if (need(r, 3) < 0) return NULL;
        Py_ssize_t n = (Py_ssize_t)rd_be(r, 2);
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xC9: {
        if (need(r, 5) < 0) return NULL;
        Py_ssize_t n = (Py_ssize_t)rd_be(r, 4);
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xCA: {
        if (need(r, 4) < 0) return NULL;
        union { uint32_t u; float f; } c;
        c.u = (uint32_t)rd_be(r, 4);
        return PyFloat_FromDouble((double)c.f);
    }
    case 0xCB: {
        if (need(r, 8) < 0) return NULL;
        union { uint64_t u; double d; } c;
        c.u = rd_be(r, 8);
        return PyFloat_FromDouble(c.d);
    }
    case 0xCC: if (need(r, 1) < 0) return NULL;
        return PyLong_FromUnsignedLong((unsigned long)rd_be(r, 1));
    case 0xCD: if (need(r, 2) < 0) return NULL;
        return PyLong_FromUnsignedLong((unsigned long)rd_be(r, 2));
    case 0xCE: if (need(r, 4) < 0) return NULL;
        return PyLong_FromUnsignedLong((unsigned long)rd_be(r, 4));
    case 0xCF: if (need(r, 8) < 0) return NULL;
        return PyLong_FromUnsignedLongLong(
            (unsigned long long)rd_be(r, 8));
    case 0xD0: if (need(r, 1) < 0) return NULL;
        return PyLong_FromLong((int8_t)rd_be(r, 1));
    case 0xD1: if (need(r, 2) < 0) return NULL;
        return PyLong_FromLong((int16_t)rd_be(r, 2));
    case 0xD2: if (need(r, 4) < 0) return NULL;
        return PyLong_FromLong((int32_t)rd_be(r, 4));
    case 0xD3: if (need(r, 8) < 0) return NULL;
        return PyLong_FromLongLong((int64_t)rd_be(r, 8));
    case 0xD4: case 0xD5: case 0xD6: case 0xD7: case 0xD8: {
        Py_ssize_t n = (Py_ssize_t)1 << (b - 0xD4);
        if (need(r, 1) < 0) return NULL;
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xD9: if (need(r, 1) < 0) return NULL;
        return decode_str(r, (Py_ssize_t)rd_be(r, 1));
    case 0xDA: if (need(r, 2) < 0) return NULL;
        return decode_str(r, (Py_ssize_t)rd_be(r, 2));
    case 0xDB: if (need(r, 4) < 0) return NULL;
        return decode_str(r, (Py_ssize_t)rd_be(r, 4));
    case 0xDC: if (need(r, 2) < 0) return NULL;
        return decode_array(r, (Py_ssize_t)rd_be(r, 2));
    case 0xDD: if (need(r, 4) < 0) return NULL;
        return decode_array(r, (Py_ssize_t)rd_be(r, 4));
    case 0xDE: if (need(r, 2) < 0) return NULL;
        return decode_map(r, (Py_ssize_t)rd_be(r, 2));
    case 0xDF: if (need(r, 4) < 0) return NULL;
        return decode_map(r, (Py_ssize_t)rd_be(r, 4));
    default:
        PyErr_Format(PyExc_ValueError, "invalid msgpack byte 0x%02x", b);
        return NULL;
    }
}

/* obj (the decoded outer list) + raw span → LogEvent
 * (codec/events._to_event parity) */
static PyObject *to_event(PyObject *obj, PyObject *raw) {
    if (!PyList_Check(obj) || PyList_GET_SIZE(obj) == 0) {
        PyObject *rep = PyObject_Repr(obj);
        PyErr_Format(PyExc_ValueError, "invalid log event: %U",
                     rep ? rep : PyUnicode_FromString("?"));
        Py_XDECREF(rep);
        return NULL;
    }
    PyObject *header = PyList_GET_ITEM(obj, 0);  /* borrowed */
    PyObject *ts, *meta, *body;
    if (PyList_Check(header)) {
        ts = PyList_GET_SIZE(header) > 0
            ? PyList_GET_ITEM(header, 0) : NULL;
        if (ts == NULL) {
            ts = PyLong_FromLong(0);
        } else {
            Py_INCREF(ts);
        }
        meta = PyList_GET_SIZE(header) > 1
            && PyDict_Check(PyList_GET_ITEM(header, 1))
            ? PyList_GET_ITEM(header, 1) : NULL;
        body = PyList_GET_SIZE(obj) > 1
            && PyDict_Check(PyList_GET_ITEM(obj, 1))
            ? PyList_GET_ITEM(obj, 1) : NULL;
    } else {
        ts = header;
        Py_INCREF(ts);
        meta = NULL;
        body = PyList_GET_SIZE(obj) > 1
            && PyDict_Check(PyList_GET_ITEM(obj, 1))
            ? PyList_GET_ITEM(obj, 1) : NULL;
    }
    if (body == NULL) {
        body = PyDict_New();
        if (!body) { Py_DECREF(ts); return NULL; }
    } else {
        Py_INCREF(body);
    }
    if (meta == NULL) {
        meta = PyDict_New();
        if (!meta) { Py_DECREF(ts); Py_DECREF(body); return NULL; }
    } else {
        Py_INCREF(meta);
    }
    PyObject *ev = PyObject_CallFunctionObjArgs(
        g_logevent, ts, body, meta, raw, NULL);
    Py_DECREF(ts);
    Py_DECREF(body);
    Py_DECREF(meta);
    return ev;
}

/* ------------------------------------------------------------------ */
/* Packing — byte-exact twin of codec/msgpack._pack (exact-type
 * dispatch; anything outside the known set raises FallbackError and
 * the caller reruns the Python packer). */

typedef struct {
    uint8_t *buf;
    Py_ssize_t len, cap;
    int depth;
} wr;

static int wr_reserve(wr *w, Py_ssize_t extra) {
    if (w->len + extra <= w->cap) return 0;
    Py_ssize_t ncap = w->cap ? w->cap : 256;
    while (ncap < w->len + extra) ncap *= 2;
    uint8_t *nb = PyMem_Realloc(w->buf, ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    w->buf = nb;
    w->cap = ncap;
    return 0;
}

static int wr_bytes(wr *w, const void *p, Py_ssize_t n) {
    if (wr_reserve(w, n) < 0) return -1;
    memcpy(w->buf + w->len, p, n);
    w->len += n;
    return 0;
}

static int wr_u8(wr *w, uint8_t b) { return wr_bytes(w, &b, 1); }

static int wr_be(wr *w, uint64_t v, int n) {
    uint8_t tmp[8];
    for (int i = n - 1; i >= 0; i--) { tmp[i] = v & 0xff; v >>= 8; }
    return wr_bytes(w, tmp, n);
}

static int pack_obj(wr *w, PyObject *obj);

static int pack_header(wr *w, Py_ssize_t n, uint8_t fixbase,
                       uint8_t b16, uint8_t b32, int fixmax) {
    if (n < fixmax) return wr_u8(w, (uint8_t)(fixbase | n));
    if (n <= 0xFFFF) {
        if (wr_u8(w, b16) < 0) return -1;
        return wr_be(w, (uint64_t)n, 2);
    }
    if (wr_u8(w, b32) < 0) return -1;
    return wr_be(w, (uint64_t)n, 4);
}

static int pack_obj(wr *w, PyObject *obj) {
    if (w->depth >= MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "msgpack nesting too deep");
        return -1;
    }
    if (obj == Py_None) return wr_u8(w, 0xC0);
    PyTypeObject *t = Py_TYPE(obj);
    if (obj == Py_True) return wr_u8(w, 0xC3);
    if (obj == Py_False) return wr_u8(w, 0xC2);
    if (t == &PyLong_Type) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
        if (overflow > 0) {  /* > i64 max: may still fit u64 */
            unsigned long long u = PyLong_AsUnsignedLongLong(obj);
            if (PyErr_Occurred()) {
                PyErr_Clear();
                PyErr_SetString(PyExc_OverflowError,
                                "int too large for msgpack");
                return -1;
            }
            if (wr_u8(w, 0xCF) < 0) return -1;
            return wr_be(w, (uint64_t)u, 8);
        }
        if (overflow < 0) {
            PyErr_SetString(PyExc_OverflowError,
                            "int too small for msgpack");
            return -1;
        }
        if (v >= 0) {
            if (v < 0x80) return wr_u8(w, (uint8_t)v);
            if (v <= 0xFF) {
                if (wr_u8(w, 0xCC) < 0) return -1;
                return wr_u8(w, (uint8_t)v);
            }
            if (v <= 0xFFFF) {
                if (wr_u8(w, 0xCD) < 0) return -1;
                return wr_be(w, (uint64_t)v, 2);
            }
            if (v <= 0xFFFFFFFFLL) {
                if (wr_u8(w, 0xCE) < 0) return -1;
                return wr_be(w, (uint64_t)v, 4);
            }
            if (wr_u8(w, 0xCF) < 0) return -1;
            return wr_be(w, (uint64_t)v, 8);
        }
        if (v >= -32) return wr_u8(w, (uint8_t)(int8_t)v);
        if (v >= -128) {
            if (wr_u8(w, 0xD0) < 0) return -1;
            return wr_u8(w, (uint8_t)(int8_t)v);
        }
        if (v >= -32768) {
            if (wr_u8(w, 0xD1) < 0) return -1;
            return wr_be(w, (uint64_t)(uint16_t)(int16_t)v, 2);
        }
        if (v >= -2147483648LL) {
            if (wr_u8(w, 0xD2) < 0) return -1;
            return wr_be(w, (uint64_t)(uint32_t)(int32_t)v, 4);
        }
        if (wr_u8(w, 0xD3) < 0) return -1;
        return wr_be(w, (uint64_t)v, 8);
    }
    if (t == &PyFloat_Type) {
        union { double d; uint64_t u; } c;
        c.d = PyFloat_AS_DOUBLE(obj);
        if (wr_u8(w, 0xCB) < 0) return -1;
        return wr_be(w, c.u, 8);
    }
    if (t == &PyUnicode_Type) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!s) return -1;
        if (n < 32) {
            if (wr_u8(w, (uint8_t)(0xA0 | n)) < 0) return -1;
        } else if (n <= 0xFF) {
            if (wr_u8(w, 0xD9) < 0 || wr_u8(w, (uint8_t)n) < 0)
                return -1;
        } else if (n <= 0xFFFF) {
            if (wr_u8(w, 0xDA) < 0 || wr_be(w, (uint64_t)n, 2) < 0)
                return -1;
        } else {
            if (wr_u8(w, 0xDB) < 0 || wr_be(w, (uint64_t)n, 4) < 0)
                return -1;
        }
        return wr_bytes(w, s, n);
    }
    if (t == &PyBytes_Type || t == &PyByteArray_Type
            || t == &PyMemoryView_Type) {
        PyObject *b = PyBytes_FromObject(obj);
        if (!b) return -1;
        Py_ssize_t n = PyBytes_GET_SIZE(b);
        int rc;
        if (n <= 0xFF)
            rc = wr_u8(w, 0xC4) < 0 ? -1 : wr_u8(w, (uint8_t)n);
        else if (n <= 0xFFFF)
            rc = wr_u8(w, 0xC5) < 0 ? -1 : wr_be(w, (uint64_t)n, 2);
        else
            rc = wr_u8(w, 0xC6) < 0 ? -1 : wr_be(w, (uint64_t)n, 4);
        if (rc == 0) rc = wr_bytes(w, PyBytes_AS_STRING(b), n);
        Py_DECREF(b);
        return rc;
    }
    if (t == &PyList_Type || t == &PyTuple_Type) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        if (pack_header(w, n, 0x90, 0xDC, 0xDD, 16) < 0) return -1;
        PyObject **items = PySequence_Fast_ITEMS(obj);
        w->depth++;
        for (Py_ssize_t i = 0; i < n; i++)
            if (pack_obj(w, items[i]) < 0) { w->depth--; return -1; }
        w->depth--;
        return 0;
    }
    if (t == &PyDict_Type) {
        Py_ssize_t n = PyDict_GET_SIZE(obj);
        if (pack_header(w, n, 0x80, 0xDE, 0xDF, 16) < 0) return -1;
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        w->depth++;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (pack_obj(w, k) < 0) { w->depth--; return -1; }
            if (pack_obj(w, v) < 0) { w->depth--; return -1; }
        }
        w->depth--;
        return 0;
    }
    if ((PyObject *)t == g_eventtime) {
        PyObject *sec = PyObject_GetAttrString(obj, "sec");
        PyObject *nsec = PyObject_GetAttrString(obj, "nsec");
        if (!sec || !nsec) { Py_XDECREF(sec); Py_XDECREF(nsec); return -1; }
        uint32_t s = (uint32_t)PyLong_AsUnsignedLongLongMask(sec);
        uint32_t ns = (uint32_t)PyLong_AsUnsignedLongLongMask(nsec);
        Py_DECREF(sec);
        Py_DECREF(nsec);
        if (wr_u8(w, 0xD7) < 0 || wr_u8(w, 0x00) < 0) return -1;
        if (wr_be(w, s, 4) < 0) return -1;
        return wr_be(w, ns, 4);
    }
    /* ExtType, subclasses, exotic types: let the Python packer decide */
    PyErr_SetString(g_fallback, "type outside the fast-pack set");
    return -1;
}

static PyObject *py_pack_event(PyObject *self, PyObject *args) {
    PyObject *ts, *meta, *body;
    if (!PyArg_ParseTuple(args, "OOO", &ts, &meta, &body)) return NULL;
    wr w = {NULL, 0, 0, 0};
    /* [[ts, meta], body] */
    if (wr_u8(&w, 0x92) < 0 || wr_u8(&w, 0x92) < 0
            || pack_obj(&w, ts) < 0 || pack_obj(&w, meta) < 0
            || pack_obj(&w, body) < 0) {
        PyMem_Free(w.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

static PyObject *py_decode_events(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    rd r = {(const uint8_t *)view.buf,
            (const uint8_t *)view.buf + view.len, 0};
    PyObject *events = PyList_New(0);
    if (!events) { PyBuffer_Release(&view); return NULL; }
    while (r.p < r.end) {
        const uint8_t *start = r.p;
        PyObject *obj = decode_obj(&r);
        if (!obj) {
            if (PyErr_ExceptionMatches(g_truncated)) {
                /* torn trailing record: Python-parity — keep prefix */
                PyErr_Clear();
                break;
            }
            goto fail;
        }
        PyObject *raw = PyBytes_FromStringAndSize(
            (const char *)start, r.p - start);
        if (!raw) { Py_DECREF(obj); goto fail; }
        PyObject *ev = to_event(obj, raw);
        Py_DECREF(obj);
        Py_DECREF(raw);
        if (!ev) goto fail;
        int rc = PyList_Append(events, ev);
        Py_DECREF(ev);
        if (rc < 0) goto fail;
    }
    PyBuffer_Release(&view);
    return events;
fail:
    Py_DECREF(events);
    PyBuffer_Release(&view);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* filter_parser JSON fast path — whole-chunk JSON→msgpack transcode.
 *
 * parser_json_batch(buf, key) walks the concatenated V2 log-event
 * buffer once and, for every record whose top-level string field `key`
 * holds a JSON object, rewrites the record as
 * ``[[ts, meta], <parsed object>]`` — byte-exactly what the per-record
 * path (json.loads → dict → pack_event) produces:
 *
 *   - JSON object keys keep first-position/last-value duplicate
 *     semantics (Python dict insertion behavior);
 *   - ints pack with pack_obj's minimal-width rules, floats as f64,
 *     NaN/Infinity with CPython's exact bit patterns;
 *   - strings unescape (incl. surrogate pairs) to UTF-8;
 *   - parse failures / non-object documents / missing or non-string
 *     field values leave the record verbatim (the per-record path
 *     re-emits ev.raw for those).
 *
 * Anything the C path cannot reproduce bit-exactly raises
 * FallbackError and the caller runs the per-record path for the whole
 * chunk: legacy (V1) records, non-canonical msgpack in a parsed
 * record's [ts, meta] header (re-encode would change bytes), bin-typed
 * field values (decoded with errors="replace" upstream), invalid UTF-8
 * in the JSON text, ints beyond u64/i64, lone surrogate escapes, torn
 * trailing records, pathological nesting. */

#define JT_SYNTAX   (-1)  /* json.loads would fail → record verbatim */
#define JT_FALLBACK (-2)  /* bit-exactness not guaranteed → chunk decline */
#define JT_NOMEM    (-3)
#define JT_MAX_DEPTH 64
#define JT_MAX_ENTRIES 128

/* ---- span-level msgpack walking (no PyObject) ---- */

static const uint8_t *mp_skip_span(const uint8_t *p, const uint8_t *end,
                                   int depth);

static const uint8_t *mp_skip_n(const uint8_t *p, const uint8_t *end,
                                long long n, int depth) {
    for (long long i = 0; i < n; i++) {
        p = mp_skip_span(p, end, depth);
        if (!p) return NULL;
    }
    return p;
}

static const uint8_t *mp_skip_span(const uint8_t *p, const uint8_t *end,
                                   int depth) {
    if (depth > MAX_DEPTH || p >= end) return NULL;
    uint8_t b = *p++;
    long long n;
    if (b < 0x80 || b >= 0xE0) return p;              /* fixint */
    if (b <= 0x8F) return mp_skip_n(p, end, 2LL * (b & 0x0F), depth + 1);
    if (b <= 0x9F) return mp_skip_n(p, end, b & 0x0F, depth + 1);
    if (b <= 0xBF) { n = b & 0x1F; return (end - p >= n) ? p + n : NULL; }
    switch (b) {
    case 0xC0: case 0xC2: case 0xC3: return p;
    case 0xC4: case 0xD9:
        if (end - p < 1) return NULL;
        n = p[0]; p += 1; return (end - p >= n) ? p + n : NULL;
    case 0xC5: case 0xDA:
        if (end - p < 2) return NULL;
        n = ((long long)p[0] << 8) | p[1]; p += 2;
        return (end - p >= n) ? p + n : NULL;
    case 0xC6: case 0xDB:
        if (end - p < 4) return NULL;
        n = ((long long)p[0] << 24) | ((long long)p[1] << 16)
          | ((long long)p[2] << 8) | p[3];
        p += 4; return (end - p >= n) ? p + n : NULL;
    case 0xC7:
        if (end - p < 2) return NULL;
        n = p[0]; p += 2; return (end - p >= n) ? p + n : NULL;
    case 0xC8:
        if (end - p < 3) return NULL;
        n = ((long long)p[0] << 8) | p[1]; p += 3;
        return (end - p >= n) ? p + n : NULL;
    case 0xC9:
        if (end - p < 5) return NULL;
        n = ((long long)p[0] << 24) | ((long long)p[1] << 16)
          | ((long long)p[2] << 8) | p[3];
        p += 5; return (end - p >= n) ? p + n : NULL;
    case 0xCA: return (end - p >= 4) ? p + 4 : NULL;
    case 0xCB: return (end - p >= 8) ? p + 8 : NULL;
    case 0xCC: case 0xD0: return (end - p >= 1) ? p + 1 : NULL;
    case 0xCD: case 0xD1: return (end - p >= 2) ? p + 2 : NULL;
    case 0xCE: case 0xD2: return (end - p >= 4) ? p + 4 : NULL;
    case 0xCF: case 0xD3: return (end - p >= 8) ? p + 8 : NULL;
    case 0xD4: case 0xD5: case 0xD6: case 0xD7: case 0xD8:
        n = 1 + ((long long)1 << (b - 0xD4));
        return (end - p >= n) ? p + n : NULL;
    case 0xDC:
        if (end - p < 2) return NULL;
        n = ((long long)p[0] << 8) | p[1];
        return mp_skip_n(p + 2, end, n, depth + 1);
    case 0xDD:
        if (end - p < 4) return NULL;
        n = ((long long)p[0] << 24) | ((long long)p[1] << 16)
          | ((long long)p[2] << 8) | p[3];
        return mp_skip_n(p + 4, end, n, depth + 1);
    case 0xDE:
        if (end - p < 2) return NULL;
        n = ((long long)p[0] << 8) | p[1];
        return mp_skip_n(p + 2, end, 2 * n, depth + 1);
    case 0xDF:
        if (end - p < 4) return NULL;
        n = ((long long)p[0] << 24) | ((long long)p[1] << 16)
          | ((long long)p[2] << 8) | p[3];
        return mp_skip_n(p + 4, end, 2 * n, depth + 1);
    default: return NULL;                              /* 0xC1 */
    }
}

/* str header reader: NULL when the object at p is not a str */
static const uint8_t *mp_str_hdr(const uint8_t *p, const uint8_t *end,
                                 long long *len_out) {
    if (p >= end) return NULL;
    uint8_t b = *p;
    if (b >= 0xA0 && b <= 0xBF) { *len_out = b & 0x1F; return p + 1; }
    if (b == 0xD9 && end - p >= 2) { *len_out = p[1]; return p + 2; }
    if (b == 0xDA && end - p >= 3) {
        *len_out = ((long long)p[1] << 8) | p[2]; return p + 3;
    }
    if (b == 0xDB && end - p >= 5) {
        *len_out = ((long long)p[1] << 24) | ((long long)p[2] << 16)
                 | ((long long)p[3] << 8) | p[4];
        return p + 5;
    }
    return NULL;
}

/* strict RFC 3629 validator — mirrors CPython's UTF-8 decoder, which
 * replaces exactly the sequences this rejects (so a fully valid span
 * means errors="replace" upstream was an identity). */
static int utf8_valid(const uint8_t *p, long long n) {
    const uint8_t *end = p + n;
    while (p < end) {
        uint8_t c = *p;
        if (c < 0x80) { p++; continue; }
        if (c < 0xC2) return 0;
        if (c < 0xE0) {
            if (end - p < 2 || (p[1] & 0xC0) != 0x80) return 0;
            p += 2; continue;
        }
        if (c < 0xF0) {
            uint8_t lo = 0x80, hi = 0xBF;
            if (c == 0xE0) lo = 0xA0;
            else if (c == 0xED) hi = 0x9F;      /* no surrogates */
            if (end - p < 3 || p[1] < lo || p[1] > hi
                    || (p[2] & 0xC0) != 0x80) return 0;
            p += 3; continue;
        }
        if (c < 0xF5) {
            uint8_t lo = 0x80, hi = 0xBF;
            if (c == 0xF0) lo = 0x90;
            else if (c == 0xF4) hi = 0x8F;      /* <= U+10FFFF */
            if (end - p < 4 || p[1] < lo || p[1] > hi
                    || (p[2] & 0xC0) != 0x80
                    || (p[3] & 0xC0) != 0x80) return 0;
            p += 4; continue;
        }
        return 0;
    }
    return 1;
}

/* canonicality walk: 0 = decode→pack_obj round-trips to the same
 * bytes, JT_FALLBACK = it would not (or we cannot prove it), sets
 * *nx to the element end. Applied to the [ts, meta] header of parsed
 * records, whose bytes the transcoder copies verbatim in place of the
 * per-record path's re-encode. */
static int mp_canonical(const uint8_t *p, const uint8_t *end, int depth,
                        const uint8_t **nx) {
    if (depth > JT_MAX_DEPTH || p >= end) return JT_FALLBACK;
    uint8_t b = *p;
    long long n, i;
    const uint8_t *q;
    if (b < 0x80 || b >= 0xE0) { *nx = p + 1; return 0; }  /* fixint */
    if (b <= 0x8F || b == 0xDE || b == 0xDF) {             /* map */
        if (b <= 0x8F) { n = b & 0x0F; q = p + 1; }
        else if (b == 0xDE) {
            if (end - p < 3) return JT_FALLBACK;
            n = ((long long)p[1] << 8) | p[2]; q = p + 3;
            if (n < 16) return JT_FALLBACK;
        } else {
            if (end - p < 5) return JT_FALLBACK;
            n = ((long long)p[1] << 24) | ((long long)p[2] << 16)
              | ((long long)p[3] << 8) | p[4];
            q = p + 5;
            if (n <= 0xFFFF) return JT_FALLBACK;
        }
        /* map keys: require str keys and no duplicates — anything else
         * (int/float key collisions, dup dedup) can re-pack differently */
        const uint8_t *keys[16];
        long long klens[16];
        for (i = 0; i < n; i++) {
            long long klen;
            const uint8_t *kstr = mp_str_hdr(q, end, &klen);
            if (!kstr || klen > end - kstr) return JT_FALLBACK;
            int rc = mp_canonical(q, end, depth + 1, &q);
            if (rc) return rc;
            if (i < 16) {
                for (long long j = 0; j < i; j++)
                    if (klens[j] == klen
                            && memcmp(keys[j], kstr, klen) == 0)
                        return JT_FALLBACK;
                keys[i] = kstr; klens[i] = klen;
            } else {
                return JT_FALLBACK;  /* >16 keys: skip the dup proof */
            }
            rc = mp_canonical(q, end, depth + 1, &q);
            if (rc) return rc;
        }
        *nx = q;
        return 0;
    }
    if (b <= 0x9F || b == 0xDC || b == 0xDD) {             /* array */
        if (b <= 0x9F) { n = b & 0x0F; q = p + 1; }
        else if (b == 0xDC) {
            if (end - p < 3) return JT_FALLBACK;
            n = ((long long)p[1] << 8) | p[2]; q = p + 3;
            if (n < 16) return JT_FALLBACK;
        } else {
            if (end - p < 5) return JT_FALLBACK;
            n = ((long long)p[1] << 24) | ((long long)p[2] << 16)
              | ((long long)p[3] << 8) | p[4];
            q = p + 5;
            if (n <= 0xFFFF) return JT_FALLBACK;
        }
        for (i = 0; i < n; i++) {
            int rc = mp_canonical(q, end, depth + 1, &q);
            if (rc) return rc;
        }
        *nx = q;
        return 0;
    }
    if ((b >= 0xA0 && b <= 0xBF) || b == 0xD9 || b == 0xDA
            || b == 0xDB) {                                /* str */
        long long slen;
        const uint8_t *s = mp_str_hdr(p, end, &slen);
        if (!s || slen > end - s) return JT_FALLBACK;
        if (b == 0xD9 && slen < 32) return JT_FALLBACK;
        if (b == 0xDA && slen <= 0xFF) return JT_FALLBACK;
        if (b == 0xDB && slen <= 0xFFFF) return JT_FALLBACK;
        if (!utf8_valid(s, slen)) return JT_FALLBACK;  /* replace ≠ id */
        *nx = s + slen;
        return 0;
    }
    switch (b) {
    case 0xC0: case 0xC2: case 0xC3: *nx = p + 1; return 0;
    case 0xC4:                                             /* bin8 */
        if (end - p < 2) return JT_FALLBACK;
        n = p[1];
        if (end - (p + 2) < n) return JT_FALLBACK;
        *nx = p + 2 + n;
        return 0;
    case 0xC5:
        if (end - p < 3) return JT_FALLBACK;
        n = ((long long)p[1] << 8) | p[2];
        if (n <= 0xFF || end - (p + 3) < n) return JT_FALLBACK;
        *nx = p + 3 + n;
        return 0;
    case 0xC6:
        if (end - p < 5) return JT_FALLBACK;
        n = ((long long)p[1] << 24) | ((long long)p[2] << 16)
          | ((long long)p[3] << 8) | p[4];
        if (n <= 0xFFFF || end - (p + 5) < n) return JT_FALLBACK;
        *nx = p + 5 + n;
        return 0;
    case 0xCB: return (end - p >= 9) ? (*nx = p + 9, 0) : JT_FALLBACK;
    case 0xCC:
        if (end - p < 2 || p[1] < 0x80) return JT_FALLBACK;
        *nx = p + 2; return 0;
    case 0xCD: {
        if (end - p < 3) return JT_FALLBACK;
        uint64_t v = ((uint64_t)p[1] << 8) | p[2];
        if (v <= 0xFF) return JT_FALLBACK;
        *nx = p + 3; return 0;
    }
    case 0xCE: {
        if (end - p < 5) return JT_FALLBACK;
        uint64_t v = ((uint64_t)p[1] << 24) | ((uint64_t)p[2] << 16)
                   | ((uint64_t)p[3] << 8) | p[4];
        if (v <= 0xFFFF) return JT_FALLBACK;
        *nx = p + 5; return 0;
    }
    case 0xCF: {
        if (end - p < 9) return JT_FALLBACK;
        uint64_t v = 0;
        for (i = 1; i <= 8; i++) v = (v << 8) | p[i];
        if (v <= 0xFFFFFFFFULL) return JT_FALLBACK;
        *nx = p + 9; return 0;
    }
    case 0xD0: {
        if (end - p < 2) return JT_FALLBACK;
        int8_t v = (int8_t)p[1];
        if (v >= -32) return JT_FALLBACK;
        *nx = p + 2; return 0;
    }
    case 0xD1: {
        if (end - p < 3) return JT_FALLBACK;
        int16_t v = (int16_t)(((uint16_t)p[1] << 8) | p[2]);
        if (v >= -128) return JT_FALLBACK;
        *nx = p + 3; return 0;
    }
    case 0xD2: {
        if (end - p < 5) return JT_FALLBACK;
        int32_t v = (int32_t)(((uint32_t)p[1] << 24)
                              | ((uint32_t)p[2] << 16)
                              | ((uint32_t)p[3] << 8) | p[4]);
        if (v >= -32768) return JT_FALLBACK;
        *nx = p + 5; return 0;
    }
    case 0xD3: {
        if (end - p < 9) return JT_FALLBACK;
        uint64_t u = 0;
        for (i = 1; i <= 8; i++) u = (u << 8) | p[i];
        if ((int64_t)u >= -2147483648LL) return JT_FALLBACK;
        *nx = p + 9; return 0;
    }
    case 0xD7:                    /* fixext8: EventTime round-trips */
        if (end - p < 10 || p[1] != 0x00) return JT_FALLBACK;
        *nx = p + 10;
        return 0;
    /* float32 re-packs as float64; other ext types build ExtType —
     * both change bytes on re-encode */
    default: return JT_FALLBACK;
    }
}

/* ---- JSON scanner/emitter ---- */

typedef struct {
    const uint8_t *p, *end;
    wr *w;
    int depth;
} jt;

static int jt_value(jt *t);

static void jt_ws(jt *t) {
    while (t->p < t->end) {
        uint8_t c = *t->p;
        if (c == ' ' || c == '\t' || c == '\n' || c == '\r') t->p++;
        else break;
    }
}

static int wr_insert(wr *w, Py_ssize_t at, const uint8_t *hdr, int n) {
    if (wr_reserve(w, n) < 0) return JT_NOMEM;
    memmove(w->buf + at + n, w->buf + at, w->len - at);
    memcpy(w->buf + at, hdr, n);
    w->len += n;
    return 0;
}

static int jt_close_str(wr *w, Py_ssize_t start) {
    Py_ssize_t n = w->len - start;
    uint8_t hdr[5];
    int hl;
    if (n < 32) { hdr[0] = (uint8_t)(0xA0 | n); hl = 1; }
    else if (n <= 0xFF) { hdr[0] = 0xD9; hdr[1] = (uint8_t)n; hl = 2; }
    else if (n <= 0xFFFF) {
        hdr[0] = 0xDA; hdr[1] = (uint8_t)(n >> 8); hdr[2] = (uint8_t)n;
        hl = 3;
    } else {
        hdr[0] = 0xDB;
        hdr[1] = (uint8_t)(n >> 24); hdr[2] = (uint8_t)(n >> 16);
        hdr[3] = (uint8_t)(n >> 8); hdr[4] = (uint8_t)n;
        hl = 5;
    }
    return wr_insert(w, start, hdr, hl);
}

static int jt_close_seq(wr *w, Py_ssize_t start, long long n,
                        uint8_t fixbase, uint8_t b16, uint8_t b32) {
    uint8_t hdr[5];
    int hl;
    if (n < 16) { hdr[0] = (uint8_t)(fixbase | n); hl = 1; }
    else if (n <= 0xFFFF) {
        hdr[0] = b16; hdr[1] = (uint8_t)(n >> 8); hdr[2] = (uint8_t)n;
        hl = 3;
    } else {
        hdr[0] = b32;
        hdr[1] = (uint8_t)(n >> 24); hdr[2] = (uint8_t)(n >> 16);
        hdr[3] = (uint8_t)(n >> 8); hdr[4] = (uint8_t)n;
        hl = 5;
    }
    return wr_insert(w, start, hdr, hl);
}

static int wr_utf8cp(wr *w, uint32_t cp) {
    uint8_t b[4];
    int n;
    if (cp < 0x80) { b[0] = (uint8_t)cp; n = 1; }
    else if (cp < 0x800) {
        b[0] = 0xC0 | (cp >> 6); b[1] = 0x80 | (cp & 0x3F); n = 2;
    } else if (cp < 0x10000) {
        b[0] = 0xE0 | (cp >> 12); b[1] = 0x80 | ((cp >> 6) & 0x3F);
        b[2] = 0x80 | (cp & 0x3F); n = 3;
    } else {
        b[0] = 0xF0 | (cp >> 18); b[1] = 0x80 | ((cp >> 12) & 0x3F);
        b[2] = 0x80 | ((cp >> 6) & 0x3F); b[3] = 0x80 | (cp & 0x3F);
        n = 4;
    }
    return wr_bytes(w, b, n) < 0 ? JT_NOMEM : 0;
}

static int jt_hex4(jt *t, uint32_t *out) {
    if (t->end - t->p < 4) return JT_SYNTAX;
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
        uint8_t c = t->p[i];
        uint32_t d;
        if (c >= '0' && c <= '9') d = c - '0';
        else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
        else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
        else return JT_SYNTAX;
        v = (v << 4) | d;
    }
    t->p += 4;
    *out = v;
    return 0;
}

static int jt_string(jt *t) {
    t->p++;  /* opening quote */
    Py_ssize_t start = t->w->len;
    for (;;) {
        /* bulk-copy the plain run */
        const uint8_t *run = t->p;
        while (t->p < t->end && *t->p != '"' && *t->p != '\\'
               && *t->p >= 0x20)
            t->p++;
        if (t->p > run && wr_bytes(t->w, run, t->p - run) < 0)
            return JT_NOMEM;
        if (t->p >= t->end) return JT_SYNTAX;
        uint8_t c = *t->p;
        if (c == '"') { t->p++; break; }
        if (c < 0x20) return JT_SYNTAX;  /* strict: raw control char */
        t->p++;  /* backslash */
        if (t->p >= t->end) return JT_SYNTAX;
        uint8_t e = *t->p++;
        int rc = 0;
        switch (e) {
        case '"': rc = wr_u8(t->w, '"'); break;
        case '\\': rc = wr_u8(t->w, '\\'); break;
        case '/': rc = wr_u8(t->w, '/'); break;
        case 'b': rc = wr_u8(t->w, '\b'); break;
        case 'f': rc = wr_u8(t->w, '\f'); break;
        case 'n': rc = wr_u8(t->w, '\n'); break;
        case 'r': rc = wr_u8(t->w, '\r'); break;
        case 't': rc = wr_u8(t->w, '\t'); break;
        case 'u': {
            uint32_t cp;
            int hrc = jt_hex4(t, &cp);
            if (hrc) return hrc;
            if (cp >= 0xDC00 && cp <= 0xDFFF)
                return JT_FALLBACK;  /* lone low surrogate */
            if (cp >= 0xD800 && cp <= 0xDBFF) {
                if (t->end - t->p < 6 || t->p[0] != '\\'
                        || t->p[1] != 'u')
                    return JT_FALLBACK;  /* lone high surrogate */
                t->p += 2;
                uint32_t lo;
                hrc = jt_hex4(t, &lo);
                if (hrc) return hrc;
                if (lo < 0xDC00 || lo > 0xDFFF) return JT_FALLBACK;
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            hrc = wr_utf8cp(t->w, cp);
            if (hrc) return hrc;
            rc = 0;
            break;
        }
        default: return JT_SYNTAX;
        }
        if (rc < 0) return JT_NOMEM;
    }
    return jt_close_str(t->w, start);
}

static int wr_pack_int(wr *w, int neg, unsigned long long mag) {
    int rc;
    if (!neg) {
        if (mag < 0x80) return wr_u8(w, (uint8_t)mag) < 0 ? JT_NOMEM : 0;
        if (mag <= 0xFF) {
            rc = wr_u8(w, 0xCC) < 0 || wr_u8(w, (uint8_t)mag) < 0;
        } else if (mag <= 0xFFFF) {
            rc = wr_u8(w, 0xCD) < 0 || wr_be(w, mag, 2) < 0;
        } else if (mag <= 0xFFFFFFFFULL) {
            rc = wr_u8(w, 0xCE) < 0 || wr_be(w, mag, 4) < 0;
        } else {
            rc = wr_u8(w, 0xCF) < 0 || wr_be(w, mag, 8) < 0;
        }
        return rc ? JT_NOMEM : 0;
    }
    if (mag > 0x8000000000000000ULL) return JT_FALLBACK;  /* < i64 min */
    long long v = (long long)(0 - mag);
    if (v >= -32)
        return wr_u8(w, (uint8_t)(int8_t)v) < 0 ? JT_NOMEM : 0;
    if (v >= -128)
        rc = wr_u8(w, 0xD0) < 0 || wr_u8(w, (uint8_t)(int8_t)v) < 0;
    else if (v >= -32768)
        rc = wr_u8(w, 0xD1) < 0
            || wr_be(w, (uint64_t)(uint16_t)(int16_t)v, 2) < 0;
    else if (v >= -2147483648LL)
        rc = wr_u8(w, 0xD2) < 0
            || wr_be(w, (uint64_t)(uint32_t)(int32_t)v, 4) < 0;
    else
        rc = wr_u8(w, 0xD3) < 0 || wr_be(w, (uint64_t)v, 8) < 0;
    return rc ? JT_NOMEM : 0;
}

static int wr_pack_f64(wr *w, double d) {
    union { double d; uint64_t u; } c;
    c.d = d;
    if (wr_u8(w, 0xCB) < 0 || wr_be(w, c.u, 8) < 0) return JT_NOMEM;
    return 0;
}

static int wr_pack_f64_bits(wr *w, uint64_t bits) {
    if (wr_u8(w, 0xCB) < 0 || wr_be(w, bits, 8) < 0) return JT_NOMEM;
    return 0;
}

static int jt_number(jt *t) {
    const uint8_t *tok = t->p;
    int neg = 0, is_float = 0;
    if (t->p < t->end && *t->p == '-') { neg = 1; t->p++; }
    if (t->p >= t->end) return JT_SYNTAX;
    if (*t->p == '0') {
        t->p++;
        if (t->p < t->end && *t->p >= '0' && *t->p <= '9')
            return JT_SYNTAX;  /* leading zero */
    } else if (*t->p >= '1' && *t->p <= '9') {
        while (t->p < t->end && *t->p >= '0' && *t->p <= '9') t->p++;
    } else {
        return JT_SYNTAX;
    }
    if (t->p < t->end && *t->p == '.') {
        is_float = 1;
        t->p++;
        if (t->p >= t->end || *t->p < '0' || *t->p > '9')
            return JT_SYNTAX;
        while (t->p < t->end && *t->p >= '0' && *t->p <= '9') t->p++;
    }
    if (t->p < t->end && (*t->p == 'e' || *t->p == 'E')) {
        is_float = 1;
        t->p++;
        if (t->p < t->end && (*t->p == '+' || *t->p == '-')) t->p++;
        if (t->p >= t->end || *t->p < '0' || *t->p > '9')
            return JT_SYNTAX;
        while (t->p < t->end && *t->p >= '0' && *t->p <= '9') t->p++;
    }
    Py_ssize_t toklen = t->p - tok;
    if (is_float) {
        char buf[384];
        if (toklen >= (Py_ssize_t)sizeof(buf)) return JT_FALLBACK;
        memcpy(buf, tok, toklen);
        buf[toklen] = '\0';
        char *endp = NULL;
        double d = strtod(buf, &endp);
        if (endp != buf + toklen) return JT_FALLBACK;
        return wr_pack_f64(t->w, d);
    }
    /* integer: accumulate magnitude with overflow detection */
    const uint8_t *q = tok + neg;
    unsigned long long mag = 0;
    for (; q < t->p; q++) {
        unsigned long long d = (unsigned long long)(*q - '0');
        if (mag > (0xFFFFFFFFFFFFFFFFULL - d) / 10)
            return JT_FALLBACK;  /* Python bigint territory */
        mag = mag * 10 + d;
    }
    return wr_pack_int(t->w, neg, mag);
}

static int jt_object(jt *t) {
    if (++t->depth > JT_MAX_DEPTH) { t->depth--; return JT_FALLBACK; }
    t->p++;  /* '{' */
    wr *w = t->w;
    Py_ssize_t start = w->len;
    struct { Py_ssize_t koff, kend, vend; } ents[JT_MAX_ENTRIES];
    long long n = 0;
    jt_ws(t);
    if (t->p < t->end && *t->p == '}') {
        t->p++;
    } else {
        for (;;) {
            jt_ws(t);
            if (t->p >= t->end || *t->p != '"') { t->depth--; return JT_SYNTAX; }
            Py_ssize_t koff = w->len;
            int rc = jt_string(t);
            if (rc) { t->depth--; return rc; }
            Py_ssize_t kend = w->len;
            jt_ws(t);
            if (t->p >= t->end || *t->p != ':') { t->depth--; return JT_SYNTAX; }
            t->p++;
            jt_ws(t);
            rc = jt_value(t);
            if (rc) { t->depth--; return rc; }
            Py_ssize_t vend = w->len;
            /* duplicate key → Python dict semantics: keep the FIRST
             * position, take the LAST value */
            long long dup = -1;
            for (long long i = 0; i < n; i++) {
                if (ents[i].kend - ents[i].koff == kend - koff
                        && memcmp(w->buf + ents[i].koff, w->buf + koff,
                                  kend - koff) == 0) {
                    dup = i;
                    break;
                }
            }
            if (dup >= 0) {
                Py_ssize_t nvlen = vend - kend;
                Py_ssize_t ovoff = ents[dup].kend;
                Py_ssize_t ovend = ents[dup].vend;
                Py_ssize_t ovlen = ovend - ovoff;
                uint8_t *tmp = (uint8_t *)PyMem_Malloc(nvlen ? nvlen : 1);
                if (!tmp) { t->depth--; return JT_NOMEM; }
                memcpy(tmp, w->buf + kend, nvlen);
                w->len = koff;  /* drop the new entry from the tail */
                Py_ssize_t delta = nvlen - ovlen;
                if (delta > 0 && wr_reserve(w, delta) < 0) {
                    PyMem_Free(tmp);
                    t->depth--;
                    return JT_NOMEM;
                }
                memmove(w->buf + ovoff + nvlen, w->buf + ovend,
                        w->len - ovend);
                memcpy(w->buf + ovoff, tmp, nvlen);
                PyMem_Free(tmp);
                w->len += delta;
                for (long long i = 0; i < n; i++) {
                    if (ents[i].koff > ovoff) {
                        ents[i].koff += delta;
                        ents[i].kend += delta;
                    }
                    if (ents[i].vend >= ovend) ents[i].vend += delta;
                }
            } else {
                if (n >= JT_MAX_ENTRIES) { t->depth--; return JT_FALLBACK; }
                ents[n].koff = koff;
                ents[n].kend = kend;
                ents[n].vend = vend;
                n++;
            }
            jt_ws(t);
            if (t->p >= t->end) { t->depth--; return JT_SYNTAX; }
            if (*t->p == ',') { t->p++; continue; }
            if (*t->p == '}') { t->p++; break; }
            t->depth--;
            return JT_SYNTAX;
        }
    }
    t->depth--;
    return jt_close_seq(w, start, n, 0x80, 0xDE, 0xDF);
}

static int jt_array(jt *t) {
    if (++t->depth > JT_MAX_DEPTH) { t->depth--; return JT_FALLBACK; }
    t->p++;  /* '[' */
    Py_ssize_t start = t->w->len;
    long long n = 0;
    jt_ws(t);
    if (t->p < t->end && *t->p == ']') {
        t->p++;
    } else {
        for (;;) {
            jt_ws(t);
            int rc = jt_value(t);
            if (rc) { t->depth--; return rc; }
            n++;
            jt_ws(t);
            if (t->p >= t->end) { t->depth--; return JT_SYNTAX; }
            if (*t->p == ',') { t->p++; continue; }
            if (*t->p == ']') { t->p++; break; }
            t->depth--;
            return JT_SYNTAX;
        }
    }
    t->depth--;
    return jt_close_seq(t->w, start, n, 0x90, 0xDC, 0xDD);
}

static int jt_lit(jt *t, const char *word, Py_ssize_t wl) {
    if (t->end - t->p < wl || memcmp(t->p, word, wl) != 0)
        return JT_SYNTAX;
    t->p += wl;
    return 0;
}

static int jt_value(jt *t) {
    if (t->p >= t->end) return JT_SYNTAX;
    uint8_t c = *t->p;
    int rc;
    switch (c) {
    case '{': return jt_object(t);
    case '[': return jt_array(t);
    case '"': return jt_string(t);
    case 't':
        rc = jt_lit(t, "true", 4);
        if (rc) return rc;
        return wr_u8(t->w, 0xC3) < 0 ? JT_NOMEM : 0;
    case 'f':
        rc = jt_lit(t, "false", 5);
        if (rc) return rc;
        return wr_u8(t->w, 0xC2) < 0 ? JT_NOMEM : 0;
    case 'n':
        rc = jt_lit(t, "null", 4);
        if (rc) return rc;
        return wr_u8(t->w, 0xC0) < 0 ? JT_NOMEM : 0;
    /* CPython's json accepts these constants by default and maps them
     * to float('nan')/float('inf') — match the exact bit patterns */
    case 'N':
        rc = jt_lit(t, "NaN", 3);
        if (rc) return rc;
        return wr_pack_f64_bits(t->w, 0x7FF8000000000000ULL);
    case 'I':
        rc = jt_lit(t, "Infinity", 8);
        if (rc) return rc;
        return wr_pack_f64_bits(t->w, 0x7FF0000000000000ULL);
    case '-':
        if (t->end - t->p >= 2 && t->p[1] == 'I') {
            rc = jt_lit(t, "-Infinity", 9);
            if (rc) return rc;
            return wr_pack_f64_bits(t->w, 0xFFF0000000000000ULL);
        }
        return jt_number(t);
    default:
        if (c >= '0' && c <= '9') return jt_number(t);
        return JT_SYNTAX;
    }
}

/* one record: 1 = parsed + re-emitted, 0 = copied verbatim,
 * JT_FALLBACK / JT_NOMEM on the chunk-decline paths */
static int transcode_record(const uint8_t *rec, const uint8_t *end,
                            const uint8_t *key, Py_ssize_t keylen,
                            wr *w, const uint8_t **rec_end_out) {
    const uint8_t *rend = mp_skip_span(rec, end, 0);
    if (!rend) return JT_FALLBACK;  /* malformed or torn tail */
    *rec_end_out = rend;
    /* the per-record path re-encodes legacy / odd-arity records as V2;
     * only the exact [[ts, meta], body] shape copies through */
    if (*rec != 0x92) return JT_FALLBACK;
    const uint8_t *hdr = rec + 1;
    if (hdr >= end || *hdr != 0x92) return JT_FALLBACK;
    const uint8_t *ts = hdr + 1;
    const uint8_t *meta = mp_skip_span(ts, end, 0);
    if (!meta) return JT_FALLBACK;
    const uint8_t *body = mp_skip_span(meta, end, 0);
    if (!body || body >= rend) return JT_FALLBACK;
    /* body must be a map; otherwise the record passes through */
    uint8_t b = *body;
    long long pairs;
    const uint8_t *kv;
    if (b >= 0x80 && b <= 0x8F) { pairs = b & 0x0F; kv = body + 1; }
    else if (b == 0xDE && end - body >= 3) {
        pairs = ((long long)body[1] << 8) | body[2];
        kv = body + 3;
    } else if (b == 0xDF && end - body >= 5) {
        pairs = ((long long)body[1] << 24) | ((long long)body[2] << 16)
              | ((long long)body[3] << 8) | body[4];
        kv = body + 5;
    } else {
        goto verbatim;
    }
    {
        /* find the LAST occurrence of the key (dict decode keeps it) */
        const uint8_t *vstr = NULL;
        long long vlen = 0;
        int hit_kind = 0;  /* 0 none, 1 str, 2 other, 3 bin */
        for (long long i = 0; i < pairs; i++) {
            long long klen;
            const uint8_t *kstr = mp_str_hdr(kv, end, &klen);
            const uint8_t *val;
            int match = 0;
            if (kstr && klen <= end - kstr) {
                val = kstr + klen;
                match = (klen == keylen && memcmp(kstr, key, klen) == 0);
            } else {
                val = mp_skip_span(kv, end, 0);  /* non-str key */
                if (!val) return JT_FALLBACK;
            }
            if (match) {
                if (val >= end) return JT_FALLBACK;
                long long sl;
                const uint8_t *s = mp_str_hdr(val, end, &sl);
                if (s && sl <= end - s) {
                    vstr = s;
                    vlen = sl;
                    hit_kind = 1;
                } else if (*val == 0xC4 || *val == 0xC5
                           || *val == 0xC6) {
                    /* bin value: _to_str decodes with errors="replace"
                     * and still parses — C can't reproduce that */
                    hit_kind = 3;
                } else {
                    hit_kind = 2;  /* non-string: _to_str → None */
                }
            }
            kv = mp_skip_span(val, end, 0);
            if (!kv) return JT_FALLBACK;
        }
        if (hit_kind == 3) return JT_FALLBACK;
        if (hit_kind != 1) goto verbatim;
        /* JSON must be an object for _do_json to replace the body */
        const uint8_t *jp = vstr, *jend = vstr + vlen;
        while (jp < jend && (*jp == ' ' || *jp == '\t' || *jp == '\n'
                             || *jp == '\r'))
            jp++;
        if (jp >= jend || *jp != '{') goto verbatim;
        if (!utf8_valid(vstr, vlen)) return JT_FALLBACK;
        /* the header bytes stand in for the per-record re-encode, so
         * they must be canonical (decode→pack round-trip identity) */
        const uint8_t *nx;
        if (mp_canonical(ts, meta, 0, &nx) || nx != meta)
            return JT_FALLBACK;
        if (mp_canonical(meta, body, 0, &nx) || nx != body)
            return JT_FALLBACK;
        Py_ssize_t ckpt = w->len;
        if (wr_u8(w, 0x92) < 0 || wr_u8(w, 0x92) < 0
                || wr_bytes(w, ts, body - ts) < 0)
            return JT_NOMEM;
        jt t = {jp, jend, w, 0};
        int rc = jt_object(&t);
        if (rc == 0) {
            jt_ws(&t);
            if (t.p != t.end) rc = JT_SYNTAX;  /* trailing garbage */
        }
        if (rc == JT_SYNTAX) {
            w->len = ckpt;  /* json.loads would fail → verbatim */
            goto verbatim;
        }
        if (rc) return rc;
        return 1;
    }
verbatim:
    if (wr_bytes(w, rec, rend - rec) < 0) return JT_NOMEM;
    return 0;
}

static PyObject *py_parser_json_batch(PyObject *self, PyObject *args) {
    Py_buffer view;
    const char *key;
    Py_ssize_t keylen;
    if (!PyArg_ParseTuple(args, "y*y#", &view, &key, &keylen))
        return NULL;
    const uint8_t *p = (const uint8_t *)view.buf;
    const uint8_t *end = p + view.len;
    wr w = {NULL, 0, 0, 0};
    long long n = 0, parsed = 0;
    int rc = 0;
    while (p < end) {
        const uint8_t *rec_end = NULL;
        rc = transcode_record(p, end, (const uint8_t *)key, keylen,
                              &w, &rec_end);
        if (rc < 0) break;
        parsed += rc;
        n++;
        p = rec_end;
    }
    if (rc < 0) {
        PyMem_Free(w.buf);
        PyBuffer_Release(&view);
        if (rc == JT_FALLBACK)
            PyErr_SetString(g_fallback,
                            "record outside the fast-transcode set");
        else if (!PyErr_Occurred())
            PyErr_NoMemory();
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    PyBuffer_Release(&view);
    if (!out) return NULL;
    PyObject *res = Py_BuildValue("(NLL)", out, n, parsed);
    return res;
}

static PyObject *py_init(PyObject *self, PyObject *args) {
    PyObject *logevent, *eventtime;
    if (!PyArg_ParseTuple(args, "OO", &logevent, &eventtime)) return NULL;
    Py_XINCREF(logevent);
    Py_XINCREF(eventtime);
    Py_XSETREF(g_logevent, logevent);
    Py_XSETREF(g_eventtime, eventtime);
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"decode_events", py_decode_events, METH_O,
     "decode a concatenated log-event msgpack buffer → list[LogEvent]"},
    {"pack_event", py_pack_event, METH_VARARGS,
     "pack_event(ts, meta, body) → V2 log-event msgpack bytes"},
    {"parser_json_batch", py_parser_json_batch, METH_VARARGS,
     "parser_json_batch(buf, key) → (out, n_records, n_parsed): "
     "whole-chunk JSON field transcode (filter_parser fast path); "
     "raises FallbackError when the per-record path must run"},
    {"_init", py_init, METH_VARARGS,
     "register the LogEvent and EventTime classes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fbtpu_codec",
    "C msgpack log-event decoder", -1, methods,
};

PyMODINIT_FUNC PyInit_fbtpu_codec(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    g_fallback = PyErr_NewException("fbtpu_codec.FallbackError",
                                    PyExc_ValueError, NULL);
    if (!g_fallback || PyModule_AddObject(m, "FallbackError",
                                          g_fallback) < 0) {
        Py_XDECREF(g_fallback);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(g_fallback);  /* module owns one, we keep one */
    g_truncated = PyErr_NewException("fbtpu_codec.TruncatedError",
                                     PyExc_ValueError, NULL);
    if (!g_truncated || PyModule_AddObject(m, "TruncatedError",
                                           g_truncated) < 0) {
        Py_XDECREF(g_truncated);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(g_truncated);
    return m;
}
