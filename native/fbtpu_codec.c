/* fbtpu_codec — CPython C-API msgpack event decoder.
 *
 * The decode path (codec/events.decode_events → pure-Python Unpacker)
 * costs ~30µs/record and caps every non-raw filter stage near 50k
 * lines/s; this extension decodes the same log-event subset straight
 * into Python objects (~10x). Byte-for-byte semantic twin of
 * codec/msgpack.Unpacker + codec/events._to_event:
 *   - strings decode UTF-8 with errors="replace"
 *   - unhashable map keys degrade to repr()
 *   - ext type 0 (len 8) → EventTime(sec, nsec)
 *   - any OTHER ext type raises FallbackError: the caller reruns the
 *     pure-Python decoder (ExtType construction is not worth porting)
 *   - V2 [[ts, meta], body] and legacy [ts, body] records both map to
 *     LogEvent(timestamp, body, metadata, raw-span)
 *
 * Reference precedent: the hot decode loop is C in fluent-bit too
 * (lib/msgpack-c via flb_log_event_decoder, src/flb_log_event_decoder.c).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

static PyObject *g_logevent = NULL;   /* codec.events.LogEvent */
static PyObject *g_eventtime = NULL;  /* codec.msgpack.EventTime */
static PyObject *g_fallback = NULL;   /* fbtpu_codec.FallbackError */
static PyObject *g_truncated = NULL;  /* internal: torn trailing record */

/* nesting bound: the pure-Python decoder dies with a recoverable
 * RecursionError around CPython's ~1000-frame limit; unbounded C
 * recursion would overflow the REAL stack and segfault the process on
 * a hostile buffer (b"\x91" * N). 512 covers any sane log event. */
#define MAX_DEPTH 512

typedef struct {
    const uint8_t *p;
    const uint8_t *end;
    int depth;
} rd;

static int need(rd *r, Py_ssize_t n) {
    if (r->end - r->p < n) {
        /* the Python Unpacker treats a torn tail as end-of-stream
         * (OutOfData stops iteration, the decoded prefix is returned);
         * decode_events must mirror that, so truncation gets its own
         * exception type the loop can swallow */
        PyErr_SetString(g_truncated, "truncated msgpack");
        return -1;
    }
    return 0;
}

static uint64_t rd_be(rd *r, int n) { /* caller already need()ed */
    uint64_t v = 0;
    for (int i = 0; i < n; i++) v = (v << 8) | r->p[i];
    r->p += n;
    return v;
}

static PyObject *decode_obj(rd *r);

static PyObject *decode_str(rd *r, Py_ssize_t n) {
    if (need(r, n) < 0) return NULL;
    PyObject *s = PyUnicode_DecodeUTF8((const char *)r->p, n, "replace");
    r->p += n;
    return s;
}

static PyObject *decode_bin(rd *r, Py_ssize_t n) {
    if (need(r, n) < 0) return NULL;
    PyObject *b = PyBytes_FromStringAndSize((const char *)r->p, n);
    r->p += n;
    return b;
}

static PyObject *decode_ext(rd *r, int code, Py_ssize_t n) {
    if (need(r, n) < 0) return NULL;
    if (code == 0 && n == 8) {
        uint32_t sec = ((uint32_t)r->p[0] << 24) | ((uint32_t)r->p[1] << 16)
                     | ((uint32_t)r->p[2] << 8) | r->p[3];
        uint32_t nsec = ((uint32_t)r->p[4] << 24) | ((uint32_t)r->p[5] << 16)
                      | ((uint32_t)r->p[6] << 8) | r->p[7];
        r->p += 8;
        return PyObject_CallFunction(g_eventtime, "kk",
                                     (unsigned long)sec,
                                     (unsigned long)nsec);
    }
    /* non-EventTime ext: the Python decoder builds ExtType — punt */
    PyErr_SetString(g_fallback, "non-EventTime ext type");
    return NULL;
}

static PyObject *decode_array(rd *r, Py_ssize_t n) {
    PyObject *lst = PyList_New(n);
    if (!lst) return NULL;
    r->depth++;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = decode_obj(r);
        if (!it) { r->depth--; Py_DECREF(lst); return NULL; }
        PyList_SET_ITEM(lst, i, it);
    }
    r->depth--;
    return lst;
}

static PyObject *decode_map(rd *r, Py_ssize_t n) {
    PyObject *d = PyDict_New();
    if (!d) return NULL;
    r->depth++;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *k = decode_obj(r);
        if (!k) { r->depth--; Py_DECREF(d); return NULL; }
        if (PyDict_Check(k) || PyList_Check(k)) {
            /* unhashable keys degrade to repr() (msgpack.py parity) */
            PyObject *rep = PyObject_Repr(k);
            Py_DECREF(k);
            if (!rep) { Py_DECREF(d); return NULL; }
            k = rep;
        }
        PyObject *v = decode_obj(r);
        if (!v) { r->depth--; Py_DECREF(k); Py_DECREF(d); return NULL; }
        if (PyDict_SetItem(d, k, v) < 0) {
            r->depth--;
            Py_DECREF(k); Py_DECREF(v); Py_DECREF(d);
            return NULL;
        }
        Py_DECREF(k);
        Py_DECREF(v);
    }
    r->depth--;
    return d;
}

static PyObject *decode_obj(rd *r) {
    if (r->depth >= MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "msgpack nesting too deep");
        return NULL;
    }
    if (need(r, 1) < 0) return NULL;
    uint8_t b = *r->p++;
    if (b < 0x80) return PyLong_FromLong(b);
    if (b >= 0xE0) return PyLong_FromLong((long)b - 0x100);
    if (b <= 0x8F) return decode_map(r, b & 0x0F);
    if (b <= 0x9F) return decode_array(r, b & 0x0F);
    if (b <= 0xBF) return decode_str(r, b & 0x1F);
    switch (b) {
    case 0xC0: Py_RETURN_NONE;
    case 0xC2: Py_RETURN_FALSE;
    case 0xC3: Py_RETURN_TRUE;
    case 0xC4: if (need(r, 1) < 0) return NULL;
        return decode_bin(r, (Py_ssize_t)rd_be(r, 1));
    case 0xC5: if (need(r, 2) < 0) return NULL;
        return decode_bin(r, (Py_ssize_t)rd_be(r, 2));
    case 0xC6: if (need(r, 4) < 0) return NULL;
        return decode_bin(r, (Py_ssize_t)rd_be(r, 4));
    case 0xC7: {
        if (need(r, 2) < 0) return NULL;
        Py_ssize_t n = (Py_ssize_t)rd_be(r, 1);
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xC8: {
        if (need(r, 3) < 0) return NULL;
        Py_ssize_t n = (Py_ssize_t)rd_be(r, 2);
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xC9: {
        if (need(r, 5) < 0) return NULL;
        Py_ssize_t n = (Py_ssize_t)rd_be(r, 4);
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xCA: {
        if (need(r, 4) < 0) return NULL;
        union { uint32_t u; float f; } c;
        c.u = (uint32_t)rd_be(r, 4);
        return PyFloat_FromDouble((double)c.f);
    }
    case 0xCB: {
        if (need(r, 8) < 0) return NULL;
        union { uint64_t u; double d; } c;
        c.u = rd_be(r, 8);
        return PyFloat_FromDouble(c.d);
    }
    case 0xCC: if (need(r, 1) < 0) return NULL;
        return PyLong_FromUnsignedLong((unsigned long)rd_be(r, 1));
    case 0xCD: if (need(r, 2) < 0) return NULL;
        return PyLong_FromUnsignedLong((unsigned long)rd_be(r, 2));
    case 0xCE: if (need(r, 4) < 0) return NULL;
        return PyLong_FromUnsignedLong((unsigned long)rd_be(r, 4));
    case 0xCF: if (need(r, 8) < 0) return NULL;
        return PyLong_FromUnsignedLongLong(
            (unsigned long long)rd_be(r, 8));
    case 0xD0: if (need(r, 1) < 0) return NULL;
        return PyLong_FromLong((int8_t)rd_be(r, 1));
    case 0xD1: if (need(r, 2) < 0) return NULL;
        return PyLong_FromLong((int16_t)rd_be(r, 2));
    case 0xD2: if (need(r, 4) < 0) return NULL;
        return PyLong_FromLong((int32_t)rd_be(r, 4));
    case 0xD3: if (need(r, 8) < 0) return NULL;
        return PyLong_FromLongLong((int64_t)rd_be(r, 8));
    case 0xD4: case 0xD5: case 0xD6: case 0xD7: case 0xD8: {
        Py_ssize_t n = (Py_ssize_t)1 << (b - 0xD4);
        if (need(r, 1) < 0) return NULL;
        int code = (int8_t)rd_be(r, 1);
        return decode_ext(r, code, n);
    }
    case 0xD9: if (need(r, 1) < 0) return NULL;
        return decode_str(r, (Py_ssize_t)rd_be(r, 1));
    case 0xDA: if (need(r, 2) < 0) return NULL;
        return decode_str(r, (Py_ssize_t)rd_be(r, 2));
    case 0xDB: if (need(r, 4) < 0) return NULL;
        return decode_str(r, (Py_ssize_t)rd_be(r, 4));
    case 0xDC: if (need(r, 2) < 0) return NULL;
        return decode_array(r, (Py_ssize_t)rd_be(r, 2));
    case 0xDD: if (need(r, 4) < 0) return NULL;
        return decode_array(r, (Py_ssize_t)rd_be(r, 4));
    case 0xDE: if (need(r, 2) < 0) return NULL;
        return decode_map(r, (Py_ssize_t)rd_be(r, 2));
    case 0xDF: if (need(r, 4) < 0) return NULL;
        return decode_map(r, (Py_ssize_t)rd_be(r, 4));
    default:
        PyErr_Format(PyExc_ValueError, "invalid msgpack byte 0x%02x", b);
        return NULL;
    }
}

/* obj (the decoded outer list) + raw span → LogEvent
 * (codec/events._to_event parity) */
static PyObject *to_event(PyObject *obj, PyObject *raw) {
    if (!PyList_Check(obj) || PyList_GET_SIZE(obj) == 0) {
        PyObject *rep = PyObject_Repr(obj);
        PyErr_Format(PyExc_ValueError, "invalid log event: %U",
                     rep ? rep : PyUnicode_FromString("?"));
        Py_XDECREF(rep);
        return NULL;
    }
    PyObject *header = PyList_GET_ITEM(obj, 0);  /* borrowed */
    PyObject *ts, *meta, *body;
    if (PyList_Check(header)) {
        ts = PyList_GET_SIZE(header) > 0
            ? PyList_GET_ITEM(header, 0) : NULL;
        if (ts == NULL) {
            ts = PyLong_FromLong(0);
        } else {
            Py_INCREF(ts);
        }
        meta = PyList_GET_SIZE(header) > 1
            && PyDict_Check(PyList_GET_ITEM(header, 1))
            ? PyList_GET_ITEM(header, 1) : NULL;
        body = PyList_GET_SIZE(obj) > 1
            && PyDict_Check(PyList_GET_ITEM(obj, 1))
            ? PyList_GET_ITEM(obj, 1) : NULL;
    } else {
        ts = header;
        Py_INCREF(ts);
        meta = NULL;
        body = PyList_GET_SIZE(obj) > 1
            && PyDict_Check(PyList_GET_ITEM(obj, 1))
            ? PyList_GET_ITEM(obj, 1) : NULL;
    }
    if (body == NULL) {
        body = PyDict_New();
        if (!body) { Py_DECREF(ts); return NULL; }
    } else {
        Py_INCREF(body);
    }
    if (meta == NULL) {
        meta = PyDict_New();
        if (!meta) { Py_DECREF(ts); Py_DECREF(body); return NULL; }
    } else {
        Py_INCREF(meta);
    }
    PyObject *ev = PyObject_CallFunctionObjArgs(
        g_logevent, ts, body, meta, raw, NULL);
    Py_DECREF(ts);
    Py_DECREF(body);
    Py_DECREF(meta);
    return ev;
}

/* ------------------------------------------------------------------ */
/* Packing — byte-exact twin of codec/msgpack._pack (exact-type
 * dispatch; anything outside the known set raises FallbackError and
 * the caller reruns the Python packer). */

typedef struct {
    uint8_t *buf;
    Py_ssize_t len, cap;
    int depth;
} wr;

static int wr_reserve(wr *w, Py_ssize_t extra) {
    if (w->len + extra <= w->cap) return 0;
    Py_ssize_t ncap = w->cap ? w->cap : 256;
    while (ncap < w->len + extra) ncap *= 2;
    uint8_t *nb = PyMem_Realloc(w->buf, ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    w->buf = nb;
    w->cap = ncap;
    return 0;
}

static int wr_bytes(wr *w, const void *p, Py_ssize_t n) {
    if (wr_reserve(w, n) < 0) return -1;
    memcpy(w->buf + w->len, p, n);
    w->len += n;
    return 0;
}

static int wr_u8(wr *w, uint8_t b) { return wr_bytes(w, &b, 1); }

static int wr_be(wr *w, uint64_t v, int n) {
    uint8_t tmp[8];
    for (int i = n - 1; i >= 0; i--) { tmp[i] = v & 0xff; v >>= 8; }
    return wr_bytes(w, tmp, n);
}

static int pack_obj(wr *w, PyObject *obj);

static int pack_header(wr *w, Py_ssize_t n, uint8_t fixbase,
                       uint8_t b16, uint8_t b32, int fixmax) {
    if (n < fixmax) return wr_u8(w, (uint8_t)(fixbase | n));
    if (n <= 0xFFFF) {
        if (wr_u8(w, b16) < 0) return -1;
        return wr_be(w, (uint64_t)n, 2);
    }
    if (wr_u8(w, b32) < 0) return -1;
    return wr_be(w, (uint64_t)n, 4);
}

static int pack_obj(wr *w, PyObject *obj) {
    if (w->depth >= MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "msgpack nesting too deep");
        return -1;
    }
    if (obj == Py_None) return wr_u8(w, 0xC0);
    PyTypeObject *t = Py_TYPE(obj);
    if (obj == Py_True) return wr_u8(w, 0xC3);
    if (obj == Py_False) return wr_u8(w, 0xC2);
    if (t == &PyLong_Type) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
        if (overflow > 0) {  /* > i64 max: may still fit u64 */
            unsigned long long u = PyLong_AsUnsignedLongLong(obj);
            if (PyErr_Occurred()) {
                PyErr_Clear();
                PyErr_SetString(PyExc_OverflowError,
                                "int too large for msgpack");
                return -1;
            }
            if (wr_u8(w, 0xCF) < 0) return -1;
            return wr_be(w, (uint64_t)u, 8);
        }
        if (overflow < 0) {
            PyErr_SetString(PyExc_OverflowError,
                            "int too small for msgpack");
            return -1;
        }
        if (v >= 0) {
            if (v < 0x80) return wr_u8(w, (uint8_t)v);
            if (v <= 0xFF) {
                if (wr_u8(w, 0xCC) < 0) return -1;
                return wr_u8(w, (uint8_t)v);
            }
            if (v <= 0xFFFF) {
                if (wr_u8(w, 0xCD) < 0) return -1;
                return wr_be(w, (uint64_t)v, 2);
            }
            if (v <= 0xFFFFFFFFLL) {
                if (wr_u8(w, 0xCE) < 0) return -1;
                return wr_be(w, (uint64_t)v, 4);
            }
            if (wr_u8(w, 0xCF) < 0) return -1;
            return wr_be(w, (uint64_t)v, 8);
        }
        if (v >= -32) return wr_u8(w, (uint8_t)(int8_t)v);
        if (v >= -128) {
            if (wr_u8(w, 0xD0) < 0) return -1;
            return wr_u8(w, (uint8_t)(int8_t)v);
        }
        if (v >= -32768) {
            if (wr_u8(w, 0xD1) < 0) return -1;
            return wr_be(w, (uint64_t)(uint16_t)(int16_t)v, 2);
        }
        if (v >= -2147483648LL) {
            if (wr_u8(w, 0xD2) < 0) return -1;
            return wr_be(w, (uint64_t)(uint32_t)(int32_t)v, 4);
        }
        if (wr_u8(w, 0xD3) < 0) return -1;
        return wr_be(w, (uint64_t)v, 8);
    }
    if (t == &PyFloat_Type) {
        union { double d; uint64_t u; } c;
        c.d = PyFloat_AS_DOUBLE(obj);
        if (wr_u8(w, 0xCB) < 0) return -1;
        return wr_be(w, c.u, 8);
    }
    if (t == &PyUnicode_Type) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!s) return -1;
        if (n < 32) {
            if (wr_u8(w, (uint8_t)(0xA0 | n)) < 0) return -1;
        } else if (n <= 0xFF) {
            if (wr_u8(w, 0xD9) < 0 || wr_u8(w, (uint8_t)n) < 0)
                return -1;
        } else if (n <= 0xFFFF) {
            if (wr_u8(w, 0xDA) < 0 || wr_be(w, (uint64_t)n, 2) < 0)
                return -1;
        } else {
            if (wr_u8(w, 0xDB) < 0 || wr_be(w, (uint64_t)n, 4) < 0)
                return -1;
        }
        return wr_bytes(w, s, n);
    }
    if (t == &PyBytes_Type || t == &PyByteArray_Type
            || t == &PyMemoryView_Type) {
        PyObject *b = PyBytes_FromObject(obj);
        if (!b) return -1;
        Py_ssize_t n = PyBytes_GET_SIZE(b);
        int rc;
        if (n <= 0xFF)
            rc = wr_u8(w, 0xC4) < 0 ? -1 : wr_u8(w, (uint8_t)n);
        else if (n <= 0xFFFF)
            rc = wr_u8(w, 0xC5) < 0 ? -1 : wr_be(w, (uint64_t)n, 2);
        else
            rc = wr_u8(w, 0xC6) < 0 ? -1 : wr_be(w, (uint64_t)n, 4);
        if (rc == 0) rc = wr_bytes(w, PyBytes_AS_STRING(b), n);
        Py_DECREF(b);
        return rc;
    }
    if (t == &PyList_Type || t == &PyTuple_Type) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        if (pack_header(w, n, 0x90, 0xDC, 0xDD, 16) < 0) return -1;
        PyObject **items = PySequence_Fast_ITEMS(obj);
        w->depth++;
        for (Py_ssize_t i = 0; i < n; i++)
            if (pack_obj(w, items[i]) < 0) { w->depth--; return -1; }
        w->depth--;
        return 0;
    }
    if (t == &PyDict_Type) {
        Py_ssize_t n = PyDict_GET_SIZE(obj);
        if (pack_header(w, n, 0x80, 0xDE, 0xDF, 16) < 0) return -1;
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        w->depth++;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (pack_obj(w, k) < 0) { w->depth--; return -1; }
            if (pack_obj(w, v) < 0) { w->depth--; return -1; }
        }
        w->depth--;
        return 0;
    }
    if ((PyObject *)t == g_eventtime) {
        PyObject *sec = PyObject_GetAttrString(obj, "sec");
        PyObject *nsec = PyObject_GetAttrString(obj, "nsec");
        if (!sec || !nsec) { Py_XDECREF(sec); Py_XDECREF(nsec); return -1; }
        uint32_t s = (uint32_t)PyLong_AsUnsignedLongLongMask(sec);
        uint32_t ns = (uint32_t)PyLong_AsUnsignedLongLongMask(nsec);
        Py_DECREF(sec);
        Py_DECREF(nsec);
        if (wr_u8(w, 0xD7) < 0 || wr_u8(w, 0x00) < 0) return -1;
        if (wr_be(w, s, 4) < 0) return -1;
        return wr_be(w, ns, 4);
    }
    /* ExtType, subclasses, exotic types: let the Python packer decide */
    PyErr_SetString(g_fallback, "type outside the fast-pack set");
    return -1;
}

static PyObject *py_pack_event(PyObject *self, PyObject *args) {
    PyObject *ts, *meta, *body;
    if (!PyArg_ParseTuple(args, "OOO", &ts, &meta, &body)) return NULL;
    wr w = {NULL, 0, 0, 0};
    /* [[ts, meta], body] */
    if (wr_u8(&w, 0x92) < 0 || wr_u8(&w, 0x92) < 0
            || pack_obj(&w, ts) < 0 || pack_obj(&w, meta) < 0
            || pack_obj(&w, body) < 0) {
        PyMem_Free(w.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

static PyObject *py_decode_events(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    rd r = {(const uint8_t *)view.buf,
            (const uint8_t *)view.buf + view.len, 0};
    PyObject *events = PyList_New(0);
    if (!events) { PyBuffer_Release(&view); return NULL; }
    while (r.p < r.end) {
        const uint8_t *start = r.p;
        PyObject *obj = decode_obj(&r);
        if (!obj) {
            if (PyErr_ExceptionMatches(g_truncated)) {
                /* torn trailing record: Python-parity — keep prefix */
                PyErr_Clear();
                break;
            }
            goto fail;
        }
        PyObject *raw = PyBytes_FromStringAndSize(
            (const char *)start, r.p - start);
        if (!raw) { Py_DECREF(obj); goto fail; }
        PyObject *ev = to_event(obj, raw);
        Py_DECREF(obj);
        Py_DECREF(raw);
        if (!ev) goto fail;
        int rc = PyList_Append(events, ev);
        Py_DECREF(ev);
        if (rc < 0) goto fail;
    }
    PyBuffer_Release(&view);
    return events;
fail:
    Py_DECREF(events);
    PyBuffer_Release(&view);
    return NULL;
}

static PyObject *py_init(PyObject *self, PyObject *args) {
    PyObject *logevent, *eventtime;
    if (!PyArg_ParseTuple(args, "OO", &logevent, &eventtime)) return NULL;
    Py_XINCREF(logevent);
    Py_XINCREF(eventtime);
    Py_XSETREF(g_logevent, logevent);
    Py_XSETREF(g_eventtime, eventtime);
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"decode_events", py_decode_events, METH_O,
     "decode a concatenated log-event msgpack buffer → list[LogEvent]"},
    {"pack_event", py_pack_event, METH_VARARGS,
     "pack_event(ts, meta, body) → V2 log-event msgpack bytes"},
    {"_init", py_init, METH_VARARGS,
     "register the LogEvent and EventTime classes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fbtpu_codec",
    "C msgpack log-event decoder", -1, methods,
};

PyMODINIT_FUNC PyInit_fbtpu_codec(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    g_fallback = PyErr_NewException("fbtpu_codec.FallbackError",
                                    PyExc_ValueError, NULL);
    if (!g_fallback || PyModule_AddObject(m, "FallbackError",
                                          g_fallback) < 0) {
        Py_XDECREF(g_fallback);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(g_fallback);  /* module owns one, we keep one */
    g_truncated = PyErr_NewException("fbtpu_codec.TruncatedError",
                                     PyExc_ValueError, NULL);
    if (!g_truncated || PyModule_AddObject(m, "TruncatedError",
                                           g_truncated) < 0) {
        Py_XDECREF(g_truncated);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(g_truncated);
    return m;
}
