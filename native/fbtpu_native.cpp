// fbtpu_native — msgpack hot-path scanner + batch staging.
//
// The C++ data-plane shim promised by SURVEY §7 ("msgpack chunk codec +
// staging buffers"): the reference keeps its hot loops in C
// (lib/msgpack-c, src/flb_mp.c record counting at
// src/flb_input_chunk.c:3041); this is the TPU build's equivalent. The
// Python codec (fluentbit_tpu/codec/msgpack.py) remains the semantic
// reference; this library accelerates three operations on the ingest
// path:
//
//   fbtpu_count_records  — count top-level msgpack objects (no decode)
//   fbtpu_scan_offsets   — per-record byte offsets (raw span slicing)
//   fbtpu_stage_field    — fill the [B, L] u8 staging matrix + lengths
//                          with each record's top-level string field
//                          (feeds the DFA/sketch kernels directly, no
//                          Python-object round trip)
//
// Exposed via ctypes (no pybind11 in this image). All functions return
// -1 on malformed input; the caller falls back to the Python codec.

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#if defined(__SSE2__) || defined(_M_X64) || defined(__x86_64__)
#include <emmintrin.h>
#define FBTPU_HAVE_SSE2 1
#endif

// W-way interleaved DFA over extracted values: W independent
// state-transition chains hide the dependent-load latency that caps a
// scalar table walk. DEAD(0)/ACC(1) rows and the EOL class are all
// absorbing in these tables (regex/dfa.py construction), so rows
// shorter than the block's max length just spin on EOL — branch-free.
#define FBTPU_DFA_LANES 8

static void dfa_run_block(const int16_t *trans, const int32_t *cmap,
                          int32_t C, int32_t start,
                          const uint8_t *const *vals,
                          const uint32_t *lens, int nrows,
                          uint8_t *out) {
    const int W = FBTPU_DFA_LANES;
    int32_t eol = cmap[256];
    int32_t s[W];
    const uint8_t *v[W];
    uint32_t l[W], maxlen = 0;
    for (int j = 0; j < W; j++) {
        if (j < nrows && vals[j] != nullptr) {
            v[j] = vals[j];
            l[j] = lens[j];
            s[j] = start;
            if (l[j] > maxlen) maxlen = l[j];
        } else {
            v[j] = nullptr;
            l[j] = 0;
            s[j] = 0;  // DEAD: missing/non-string value never matches
        }
    }
    for (uint32_t pos = 0; pos <= maxlen; pos++) {
        int32_t c[W], acc = 0;
        for (int j = 0; j < W; j++)
            c[j] = pos < l[j] ? cmap[v[j][pos]] : eol;
        for (int j = 0; j < W; j++) {
            s[j] = trans[s[j] * C + c[j]];
            acc |= s[j];
        }
        // states are non-negative, so OR <= 1 iff every chain is in
        // {DEAD, ACC} — all absorbed, result final
        if (acc <= 1) break;
    }
    // every live row consumed >= 1 EOL symbol inside the loop (pos runs
    // to maxlen inclusive), and an early break means all chains were
    // already absorbed — the final states are final
    for (int j = 0; j < W && j < nrows; j++)
        out[j] = (uint8_t)(s[j] == 1);
}

// k>=2 variant: trans_k[s, c1*C^(k-1) + ... + ck] tables pre-composed
// host-side (GrepTables packs them while S*C^k fits the budget) cut the
// dependent-load chain k-fold — k bytes per step, EOL^k absorbing.
template <int K>
static void dfa_run_block_k(const int16_t *transk, const int32_t *cmap,
                            int32_t C, int32_t start,
                            const uint8_t *const *vals,
                            const uint32_t *lens, int nrows,
                            uint8_t *out) {
    const int W = FBTPU_DFA_LANES;
    int32_t eol = cmap[256];
    int32_t Ck = 1;
    for (int b = 0; b < K; b++) Ck *= C;
    int32_t s[W];
    const uint8_t *v[W];
    uint32_t l[W], maxlen = 0;
    for (int j = 0; j < W; j++) {
        if (j < nrows && vals[j] != nullptr) {
            v[j] = vals[j];
            l[j] = lens[j];
            s[j] = start;
            if (l[j] > maxlen) maxlen = l[j];
        } else {
            v[j] = nullptr;
            l[j] = 0;
            s[j] = 0;
        }
    }
    // pos <= maxlen guarantees every row sees >= 1 EOL symbol: the
    // step group containing index l always runs (l <= maxlen), and pad
    // positions inside a group read as EOL
    for (uint32_t pos = 0; pos <= maxlen; pos += K) {
        int32_t c[W], acc = 0;
        for (int j = 0; j < W; j++) {
            int32_t cc = 0;
            for (int b = 0; b < K; b++) {
                int32_t cb = pos + b < l[j] ? cmap[v[j][pos + b]] : eol;
                cc = cc * C + cb;
            }
            c[j] = cc;
        }
        for (int j = 0; j < W; j++) {
            s[j] = transk[s[j] * Ck + c[j]];
            acc |= s[j];
        }
        if (acc <= 1) break;
    }
    for (int j = 0; j < W && j < nrows; j++)
        out[j] = (uint8_t)(s[j] == 1);
}



extern "C" {

// ---------------------------------------------------------------------
// msgpack skip: advance over one object, headers only
// ---------------------------------------------------------------------

static const uint8_t *skip_obj(const uint8_t *p, const uint8_t *end,
                               int depth) {
    if (p >= end || depth > 64) return nullptr;
    uint8_t b = *p++;
    uint32_t n;
    if (b <= 0x7f || b >= 0xe0) return p;                 // fixint
    if ((b & 0xe0) == 0xa0) {                             // fixstr
        n = b & 0x1f;
        return p + n <= end ? p + n : nullptr;
    }
    if ((b & 0xf0) == 0x90) {                             // fixarray
        n = b & 0x0f;
        for (uint32_t i = 0; i < n; i++) {
            p = skip_obj(p, end, depth + 1);
            if (!p) return nullptr;
        }
        return p;
    }
    if ((b & 0xf0) == 0x80) {                             // fixmap
        n = b & 0x0f;
        for (uint32_t i = 0; i < 2 * n; i++) {
            p = skip_obj(p, end, depth + 1);
            if (!p) return nullptr;
        }
        return p;
    }
    switch (b) {
    case 0xc0: case 0xc2: case 0xc3: return p;            // nil/bool
    case 0xcc: case 0xd0: return p + 1 <= end ? p + 1 : nullptr;
    case 0xcd: case 0xd1: return p + 2 <= end ? p + 2 : nullptr;
    case 0xce: case 0xd2: case 0xca: return p + 4 <= end ? p + 4 : nullptr;
    case 0xcf: case 0xd3: case 0xcb: return p + 8 <= end ? p + 8 : nullptr;
    case 0xd9: case 0xc4:                                 // str8/bin8
        if (p + 1 > end) return nullptr;
        n = p[0]; p += 1;
        return p + n <= end ? p + n : nullptr;
    case 0xda: case 0xc5:                                 // str16/bin16
        if (p + 2 > end) return nullptr;
        n = ((uint32_t)p[0] << 8) | p[1]; p += 2;
        return p + n <= end ? p + n : nullptr;
    case 0xdb: case 0xc6:                                 // str32/bin32
        if (p + 4 > end) return nullptr;
        n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
          | ((uint32_t)p[2] << 8) | p[3]; p += 4;
        return p + n <= end ? p + n : nullptr;
    case 0xdc:                                            // array16
        if (p + 2 > end) return nullptr;
        n = ((uint32_t)p[0] << 8) | p[1]; p += 2;
        for (uint32_t i = 0; i < n; i++) {
            p = skip_obj(p, end, depth + 1);
            if (!p) return nullptr;
        }
        return p;
    case 0xdd:                                            // array32
        if (p + 4 > end) return nullptr;
        n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
          | ((uint32_t)p[2] << 8) | p[3]; p += 4;
        for (uint32_t i = 0; i < n; i++) {
            p = skip_obj(p, end, depth + 1);
            if (!p) return nullptr;
        }
        return p;
    case 0xde:                                            // map16
        if (p + 2 > end) return nullptr;
        n = ((uint32_t)p[0] << 8) | p[1]; p += 2;
        for (uint32_t i = 0; i < 2 * n; i++) {
            p = skip_obj(p, end, depth + 1);
            if (!p) return nullptr;
        }
        return p;
    case 0xdf:                                            // map32
        if (p + 4 > end) return nullptr;
        n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
          | ((uint32_t)p[2] << 8) | p[3]; p += 4;
        for (uint32_t i = 0; i < 2 * n; i++) {
            p = skip_obj(p, end, depth + 1);
            if (!p) return nullptr;
        }
        return p;
    case 0xd4: return p + 2 <= end ? p + 2 : nullptr;     // fixext1
    case 0xd5: return p + 3 <= end ? p + 3 : nullptr;     // fixext2
    case 0xd6: return p + 5 <= end ? p + 5 : nullptr;     // fixext4
    case 0xd7: return p + 9 <= end ? p + 9 : nullptr;     // fixext8
    case 0xd8: return p + 17 <= end ? p + 17 : nullptr;   // fixext16
    case 0xc7:                                            // ext8
        if (p + 2 > end) return nullptr;
        n = p[0]; p += 2;
        return p + n <= end ? p + n : nullptr;
    case 0xc8:                                            // ext16
        if (p + 3 > end) return nullptr;
        n = ((uint32_t)p[0] << 8) | p[1]; p += 3;
        return p + n <= end ? p + n : nullptr;
    case 0xc9:                                            // ext32
        if (p + 5 > end) return nullptr;
        n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
          | ((uint32_t)p[2] << 8) | p[3]; p += 5;
        return p + n <= end ? p + n : nullptr;
    }
    return nullptr;
}

// helpers: read container headers at p (returns elem count, advances)
static const uint8_t *read_array_hdr(const uint8_t *p, const uint8_t *end,
                                     uint32_t *n) {
    if (p >= end) return nullptr;
    uint8_t b = *p++;
    if ((b & 0xf0) == 0x90) { *n = b & 0x0f; return p; }
    if (b == 0xdc) {
        if (p + 2 > end) return nullptr;
        *n = ((uint32_t)p[0] << 8) | p[1];
        return p + 2;
    }
    if (b == 0xdd) {
        if (p + 4 > end) return nullptr;
        *n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
           | ((uint32_t)p[2] << 8) | p[3];
        return p + 4;
    }
    return nullptr;
}

static const uint8_t *read_map_hdr(const uint8_t *p, const uint8_t *end,
                                   uint32_t *n) {
    if (p >= end) return nullptr;
    uint8_t b = *p++;
    if ((b & 0xf0) == 0x80) { *n = b & 0x0f; return p; }
    if (b == 0xde) {
        if (p + 2 > end) return nullptr;
        *n = ((uint32_t)p[0] << 8) | p[1];
        return p + 2;
    }
    if (b == 0xdf) {
        if (p + 4 > end) return nullptr;
        *n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
           | ((uint32_t)p[2] << 8) | p[3];
        return p + 4;
    }
    return nullptr;
}

static const uint8_t *read_str_hdr(const uint8_t *p, const uint8_t *end,
                                   uint32_t *n) {
    if (p >= end) return nullptr;
    uint8_t b = *p++;
    if ((b & 0xe0) == 0xa0) { *n = b & 0x1f; return p; }
    if (b == 0xd9) {
        if (p + 1 > end) return nullptr;
        *n = p[0];
        return p + 1;
    }
    if (b == 0xda) {
        if (p + 2 > end) return nullptr;
        *n = ((uint32_t)p[0] << 8) | p[1];
        return p + 2;
    }
    if (b == 0xdb) {
        if (p + 4 > end) return nullptr;
        *n = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
           | ((uint32_t)p[2] << 8) | p[3];
        return p + 4;
    }
    return nullptr;
}

// ---------------------------------------------------------------------
// public API
// ---------------------------------------------------------------------

long long fbtpu_count_records(const uint8_t *buf, long long len) {
    const uint8_t *p = buf, *end = buf + len;
    long long count = 0;
    while (p < end) {
        p = skip_obj(p, end, 0);
        if (!p) return -1;
        count++;
    }
    return count;
}

// offsets[count+1]: record i spans [offsets[i], offsets[i+1])
long long fbtpu_scan_offsets(const uint8_t *buf, long long len,
                             long long *offsets, long long max_records) {
    const uint8_t *p = buf, *end = buf + len;
    long long count = 0;
    while (p < end) {
        if (count >= max_records) return -2;  // caller buffer too small
        offsets[count] = p - buf;
        p = skip_obj(p, end, 0);
        if (!p) return -1;
        count++;
    }
    offsets[count] = len;
    return count;
}

// One record's field extraction: stage the top-level string field
// `key` of the record at rec_start into out_row[max_len]. Returns the
// staged length, -1 missing/non-string/non-map, -2 oversize. When the
// record is the common 2-element [[ts, meta], body] shape, *rec_end_out
// gets the end discovered by the pair walk (sparing the caller a
// second full skip_obj walk); otherwise it is left untouched.
static inline int32_t stage_one_record(const uint8_t *rec_start,
                                       const uint8_t *end,
                                       const uint8_t *key, long long keylen,
                                       uint8_t *out_row, long long max_len,
                                       const uint8_t **rec_end_out) {
    uint32_t outer;
    const uint8_t *q = read_array_hdr(rec_start, end, &outer);
    int32_t flen = -1;
    if (q && outer >= 2) {
        // skip the header element (array [ts, meta] or scalar ts)
        const uint8_t *body = skip_obj(q, end, 0);
        if (body) {
            uint32_t pairs;
            const uint8_t *kv = read_map_hdr(body, end, &pairs);
            if (kv) {
                // scan ALL pairs: duplicate map keys are legal
                // msgpack, and the Python dict decode keeps the
                // LAST occurrence — so must we
                const uint8_t *hit = nullptr;
                uint32_t hit_len = 0;
                int hit_kind = 0;  // 0 none, 1 string, 2 non-string
                for (uint32_t i = 0; i < pairs && kv; i++) {
                    uint32_t klen;
                    const uint8_t *kstr = read_str_hdr(kv, end, &klen);
                    const uint8_t *val;
                    bool match = false;
                    if (kstr) {
                        val = kstr + klen;
                        if (val > end) { kv = nullptr; break; }
                        match = ((long long)klen == keylen &&
                                 memcmp(kstr, key, klen) == 0);
                    } else {
                        val = skip_obj(kv, end, 0);  // non-str key
                        if (!val) { kv = nullptr; break; }
                    }
                    if (match) {
                        uint32_t vlen;
                        const uint8_t *vstr =
                            read_str_hdr(val, end, &vlen);
                        if (vstr && vstr + vlen <= end) {
                            hit = vstr;
                            hit_len = vlen;
                            hit_kind = 1;
                        } else {
                            hit_kind = 2;  // non-string value
                        }
                    }
                    kv = skip_obj(val, end, 0);
                }
                if (hit_kind == 1) {
                    if ((long long)hit_len > max_len) {
                        flen = -2;  // overflow row
                    } else {
                        memcpy(out_row, hit, hit_len);
                        flen = (int32_t)hit_len;
                    }
                }
                if (kv && outer == 2 && rec_end_out)
                    *rec_end_out = kv;  // pair walk ended at record end
            }
        }
    }
    return flen;
}

// Stage each record's top-level string field `key` into out[B][max_len].
// Records are [[ts, meta], body] (V2) or [ts, body] (legacy); non-map
// bodies and missing/non-string values get length -1; oversize -2.
// offsets: optional record offsets out (B+1) or NULL.
long long fbtpu_stage_field(const uint8_t *buf, long long buflen,
                            const uint8_t *key, long long keylen,
                            uint8_t *out, int32_t *lengths,
                            long long max_records, long long max_len,
                            long long *offsets) {
    const uint8_t *p = buf, *end = buf + buflen;
    long long rec = 0;
    while (p < end) {
        if (rec >= max_records) return -2;
        if (offsets) offsets[rec] = p - buf;
        const uint8_t *rec_start = p;
        const uint8_t *rec_end = nullptr;
        lengths[rec] = stage_one_record(rec_start, end, key, keylen,
                                        out + rec * max_len, max_len,
                                        &rec_end);
        p = rec_end ? rec_end : skip_obj(rec_start, end, 0);
        if (!p) return -1;
        rec++;
    }
    if (offsets) offsets[rec] = buflen;
    return rec;
}

// ---------------------------------------------------------------------
// Numeric column staging (fbtpu-flux): each record's top-level NUMERIC
// field `key` → out[i] double + kinds[i] (0 missing/non-numeric,
// 1 integer, 2 float). msgpack bools are NOT numeric (mirrors the
// Python aggregate rule `isinstance(v, (int, float)) and not bool`,
// stream_processor._Agg.add); strings are NOT parsed — the exact
// Python evaluation path skips numeric-looking strings, and the flux
// plane must stay bit-identical to it. int64/uint64 → double uses the
// same IEEE round-to-nearest Python's float(int) applies.
// ---------------------------------------------------------------------

static inline int read_numeric(const uint8_t *p, const uint8_t *end,
                               double *out) {
    if (p >= end) return 0;
    uint8_t b = *p++;
    if (b <= 0x7f) { *out = (double)b; return 1; }            // pos fixint
    if (b >= 0xe0) { *out = (double)(int8_t)b; return 1; }    // neg fixint
    switch (b) {
    case 0xcc: if (p + 1 > end) return 0;
        *out = (double)p[0]; return 1;
    case 0xcd: if (p + 2 > end) return 0;
        *out = (double)(((uint32_t)p[0] << 8) | p[1]); return 1;
    case 0xce: if (p + 4 > end) return 0;
        *out = (double)(((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
                        | ((uint32_t)p[2] << 8) | p[3]);
        return 1;
    case 0xcf: {
        if (p + 8 > end) return 0;
        uint64_t v = 0;
        for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
        *out = (double)v;
        return 1;
    }
    case 0xd0: if (p + 1 > end) return 0;
        *out = (double)(int8_t)p[0]; return 1;
    case 0xd1: if (p + 2 > end) return 0;
        *out = (double)(int16_t)(((uint16_t)p[0] << 8) | p[1]); return 1;
    case 0xd2: if (p + 4 > end) return 0;
        *out = (double)(int32_t)(((uint32_t)p[0] << 24)
                                 | ((uint32_t)p[1] << 16)
                                 | ((uint32_t)p[2] << 8) | p[3]);
        return 1;
    case 0xd3: {
        if (p + 8 > end) return 0;
        uint64_t v = 0;
        for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
        *out = (double)(int64_t)v;
        return 1;
    }
    case 0xca: {
        if (p + 4 > end) return 0;
        uint32_t v = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
                   | ((uint32_t)p[2] << 8) | p[3];
        float f;
        memcpy(&f, &v, 4);
        *out = (double)f;
        return 2;
    }
    case 0xcb: {
        if (p + 8 > end) return 0;
        uint64_t v = 0;
        for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
        double d;
        memcpy(&d, &v, 8);
        *out = d;
        return 2;
    }
    }
    return 0;
}

long long fbtpu_stage_field_f64(const uint8_t *buf, long long buflen,
                                const uint8_t *key, long long keylen,
                                double *out, uint8_t *kinds,
                                long long max_records, long long *offsets) {
    const uint8_t *p = buf, *end = buf + buflen;
    long long rec = 0;
    while (p < end) {
        if (rec >= max_records) return -2;
        if (offsets) offsets[rec] = p - buf;
        const uint8_t *rec_start = p;
        out[rec] = 0.0;
        kinds[rec] = 0;
        uint32_t outer;
        const uint8_t *q = read_array_hdr(rec_start, end, &outer);
        const uint8_t *rec_end = nullptr;
        if (q && outer >= 2) {
            const uint8_t *body = skip_obj(q, end, 0);
            if (body) {
                uint32_t pairs;
                const uint8_t *kv = read_map_hdr(body, end, &pairs);
                if (kv) {
                    // scan ALL pairs: duplicate keys keep the LAST
                    // occurrence, same as the dict decode / stage_field
                    for (uint32_t i = 0; i < pairs && kv; i++) {
                        uint32_t klen;
                        const uint8_t *kstr = read_str_hdr(kv, end, &klen);
                        const uint8_t *val;
                        bool match = false;
                        if (kstr) {
                            val = kstr + klen;
                            if (val > end) { kv = nullptr; break; }
                            match = ((long long)klen == keylen &&
                                     memcmp(kstr, key, klen) == 0);
                        } else {
                            val = skip_obj(kv, end, 0);
                            if (!val) { kv = nullptr; break; }
                        }
                        if (match) {
                            double v;
                            int kind = read_numeric(val, end, &v);
                            if (kind) {
                                out[rec] = v;
                                kinds[rec] = (uint8_t)kind;
                            } else {
                                kinds[rec] = 0;  // last occurrence rules
                            }
                        }
                        kv = skip_obj(val, end, 0);
                    }
                    if (kv && outer == 2) rec_end = kv;
                }
            }
        }
        p = rec_end ? rec_end : skip_obj(rec_start, end, 0);
        if (!p) return -1;
        rec++;
    }
    if (offsets) offsets[rec] = buflen;
    return rec;
}

// ---------------------------------------------------------------------
// Host-pinned sketch updates (fbtpu-flux): the bit-identical C twins of
// the device HLL/count-min kernels (fluentbit_tpu/ops/sketch.py), used
// while the backend is still attaching (or pinned to CPU). Hash is
// finalized FNV-1a 32 + murmur3 fmix32, exactly _hash32_cpu.
// ---------------------------------------------------------------------

static inline uint32_t fnv1a_mix32(const uint8_t *v, int32_t len) {
    uint32_t h = 0x811C9DC5u;
    for (int32_t i = 0; i < len; i++)
        h = (h ^ v[i]) * 0x01000193u;
    h ^= h >> 16; h *= 0x85EBCA6Bu;
    h ^= h >> 13; h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

// rows with lengths[i] < 0 are skipped (missing/overflow markers)
void fbtpu_hll_update(const uint8_t *batch, const int32_t *lengths,
                      long long B, long long L, int32_t p,
                      int32_t *registers) {
    int32_t max_rank = 32 - p + 1;
    for (long long i = 0; i < B; i++) {
        int32_t len = lengths[i];
        if (len < 0) continue;
        uint32_t h = fnv1a_mix32(batch + i * L, len);
        uint32_t idx = h >> (32 - p);
        uint32_t rest = (uint32_t)(h << p);
        int32_t nlz = rest ? __builtin_clz(rest) : 32;
        int32_t rank = nlz + 1 < max_rank ? nlz + 1 : max_rank;
        if (rank > registers[idx]) registers[idx] = rank;
    }
}

// table is [depth, width] of elem_size-byte signed counters (4 or 8 —
// CountMin keys its dtype off jax_enable_x64); weight 1 per valid row.
long long fbtpu_cms_update(const uint8_t *batch, const int32_t *lengths,
                           long long B, long long L, int32_t depth,
                           int32_t width, void *table, int32_t elem_size) {
    if (elem_size != 4 && elem_size != 8) return -1;
    for (long long i = 0; i < B; i++) {
        int32_t len = lengths[i];
        if (len < 0) continue;
        uint32_t h1 = fnv1a_mix32(batch + i * L, len);
        uint32_t h2 = h1;
        h2 ^= h2 >> 16; h2 *= 0x85EBCA6Bu;
        h2 ^= h2 >> 13; h2 *= 0xC2B2AE35u;
        h2 ^= h2 >> 16;
        h2 |= 1u;
        for (int32_t r = 0; r < depth; r++) {
            uint32_t col = (uint32_t)(h1 + (uint32_t)r * h2)
                           % (uint32_t)width;
            if (elem_size == 4)
                ((int32_t *)table)[(long long)r * width + col] += 1;
            else
                ((int64_t *)table)[(long long)r * width + col] += 1;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------
// Threaded staging: phase 1 is the serial boundary walk (record i+1's
// start depends on record i's end — inherently sequential, but it only
// skips headers), phase 2 fans the per-record field extraction +
// row memcpy out over a PERSISTENT worker pool. Per-chunk thread spawn
// would eat the win at bench chunk rates (~6k dispatches/s), so the
// pool parks workers on a condvar between jobs; dispatch is one
// notify_all + one condvar wait for the caller.
// ---------------------------------------------------------------------

}  // extern "C" — the pool below needs C++ linkage (templates)

namespace {

// generic slice-parallel job: fn(ctx, slice_idx) for slices 1..n-1 on
// pool workers, slice 0 on the caller's thread
typedef void (*pool_fn)(const void *ctx, int slice);

struct PoolJob {
    pool_fn fn;
    const void *ctx;
    int n_slices;
};

struct WorkPool {
    std::mutex m;
    std::condition_variable cv_work;
    std::condition_variable cv_done;
    uint64_t gen = 0;
    int remaining = 0;
    int n_workers = 0;
    PoolJob job{};

    void worker(int idx) {
        uint64_t seen = 0;
        for (;;) {
            PoolJob j;
            {
                std::unique_lock<std::mutex> lk(m);
                cv_work.wait(lk, [&] { return gen != seen; });
                seen = gen;
                j = job;
            }
            // slice 0 runs on the caller's thread; workers take 1..n
            if (idx + 1 < j.n_slices) j.fn(j.ctx, idx + 1);
            {
                std::lock_guard<std::mutex> lk(m);
                if (--remaining == 0) cv_done.notify_one();
            }
        }
    }

    // start exactly once; pool size is fixed at first use (daemon
    // threads, process lifetime — the .so is never unloaded)
    void ensure(int want_workers) {
        std::lock_guard<std::mutex> lk(m);
        if (n_workers > 0) return;
        n_workers = want_workers;
        for (int i = 0; i < want_workers; i++)
            std::thread([this, i] { worker(i); }).detach();
    }

    // serializes dispatch: threaded inputs may enter concurrently, and
    // the pool's job/remaining slots are single-occupancy. Waiters
    // queue here; each dispatch still fans out over every worker.
    std::mutex run_m;

    void run(pool_fn fn, const void *ctx, int n_slices) {
        std::lock_guard<std::mutex> run_lk(run_m);
        {
            std::lock_guard<std::mutex> lk(m);
            job = PoolJob{fn, ctx, n_slices};
            remaining = n_workers;
            gen++;
        }
        cv_work.notify_all();
        fn(ctx, 0);
        std::unique_lock<std::mutex> lk(m);
        cv_done.wait(lk, [&] { return remaining == 0; });
    }
};

// deliberately leaked: detached workers may be parked in cv_work.wait
// at process exit, and destroying a condvar/mutex with waiters is UB —
// a static instance's destructor would run exactly then
WorkPool &g_pool = *new WorkPool;

// FBTPU_DFA_THREADS: unset → all cores (capped 16); 0 or negative →
// threading disabled (1). The ONE parser for every threaded path.
// FBTPU_THREADS_NO_HW_CAP lifts the core clamp so single-core CI can
// still EXERCISE the pool dispatch paths (oversubscribed but correct).
int pool_threads_wanted() {
    unsigned hw = std::thread::hardware_concurrency();
    const char *env = getenv("FBTPU_DFA_THREADS");
    long want;
    if (env != nullptr) {
        want = strtol(env, nullptr, 10);
        if (want <= 0) return 1;
    } else {
        want = hw ? (long)hw : 1;
    }
    if (hw && want > (long)hw
            && getenv("FBTPU_THREADS_NO_HW_CAP") == nullptr)
        want = hw;
    if (want > 16) want = 16;
    return (int)want;
}

struct StageJob {
    const uint8_t *buf;
    const uint8_t *end;
    const uint8_t *key;
    long long keylen;
    uint8_t *out;
    int32_t *lengths;
    const long long *offsets;
    long long n_rec;
    long long max_len;
    long long slice;  // records per slice
    int n_slices;
};

static void stage_run_slice(const StageJob &j, int sx) {
    long long lo = (long long)sx * j.slice;
    long long hi = lo + j.slice < j.n_rec ? lo + j.slice : j.n_rec;
    for (long long r = lo; r < hi; r++)
        j.lengths[r] = stage_one_record(j.buf + j.offsets[r], j.end,
                                        j.key, j.keylen,
                                        j.out + r * j.max_len, j.max_len,
                                        nullptr);
}

static void stage_slice_adapter(const void *ctx, int sx) {
    stage_run_slice(*(const StageJob *)ctx, sx);
}

}  // namespace

extern "C" {

// How many slices a stage call requesting `nthreads` would ACTUALLY
// fan out to after the hardware/16-way caps — the introspection probe
// behind fluentbit_tpu.native.stage_threads_effective(), so the bench
// RESULT records the real slice count instead of the env request.
int32_t fbtpu_stage_effective_threads(int32_t nthreads) {
    unsigned hw = std::thread::hardware_concurrency();
    if (hw && nthreads > (int32_t)hw
            && getenv("FBTPU_THREADS_NO_HW_CAP") == nullptr)
        nthreads = (int32_t)hw;
    if (nthreads > 16) nthreads = 16;
    if (nthreads < 2) return 1;
    int pool = pool_threads_wanted();
    return nthreads < pool ? nthreads : pool;
}

// Threaded fbtpu_stage_field. offsets is REQUIRED (n+1 entries filled
// by the phase-1 scan). nthreads counts total slices including the
// caller's; the pool is sized on first call and later calls are capped
// to it. Falls back to the serial walk for small batches where the
// dispatch handshake would dominate.
long long fbtpu_stage_field_mt(const uint8_t *buf, long long buflen,
                               const uint8_t *key, long long keylen,
                               uint8_t *out, int32_t *lengths,
                               long long max_records, long long max_len,
                               long long *offsets, int nthreads) {
    unsigned hw = std::thread::hardware_concurrency();
    if (hw && nthreads > (int)hw
            && getenv("FBTPU_THREADS_NO_HW_CAP") == nullptr)
        nthreads = (int)hw;
    if (nthreads > 16) nthreads = 16;
    if (nthreads < 2)
        // single-core host: the fused one-walk serial path beats the
        // two-phase split (no separate boundary scan)
        return fbtpu_stage_field(buf, buflen, key, keylen, out, lengths,
                                 max_records, max_len, offsets);
    long long n = fbtpu_scan_offsets(buf, buflen, offsets, max_records);
    if (n < 0) return n;
    if (n < 1024) {
        StageJob j{buf, buf + buflen, key, keylen, out, lengths,
                   offsets, n, max_len, n, 1};
        stage_run_slice(j, 0);
        return n;
    }
    // pool is sized once to the machine-wide cap; each dispatch caps
    // its own slice count (workers past n_slices no-op), so one
    // caller's thread request never inflates another's
    g_pool.ensure(pool_threads_wanted() - 1);
    int slices = g_pool.n_workers + 1;
    if (slices > nthreads) slices = nthreads;
    long long slice = (n + slices - 1) / slices;
    StageJob j{buf, buf + buflen, key, keylen, out, lengths,
               offsets, n, max_len, slice,
               (int)((n + slice - 1) / slice)};
    g_pool.run(stage_slice_adapter, &j, j.n_slices);
    return n;
}

// ---------------------------------------------------------------------
// One-pass grep: field extraction + DFA execution straight off chunk
// bytes. The host-side twin of the device kernel (fluentbit_tpu/ops/
// grep.py): identical table semantics (DEAD=0 / ACC=1 absorbing, bytes
// then one EOL step), so verdicts are bit-exact with both the jax
// kernel and the Python regex engine. Used when the device backend is
// not attached (or is the jax CPU backend, which a table-driven C loop
// beats by orders of magnitude) — reference precedent: the hot filter
// loop is host-native C in fluent-bit (plugins/filter_grep/grep.c:286).
//
//   keys_cat/key_offs : n_keys concatenated field names
//   key_of_rule       : rule r matches field keys[key_of_rule[r]]
//   trans_cat/troffs  : per-rule [S*C] int32 transition tables
//   cmaps             : [R][257] byte->class maps (entry 256 = EOL)
//   starts, ncls      : per-rule start state / class count
//   match_out         : [R][max_records] u8 verdict matrix
//   offsets           : record byte offsets (max_records+1)
// Returns record count, -1 malformed, -2 capacity exceeded.
// ---------------------------------------------------------------------

#define FBTPU_MAX_KEYS 64

long long fbtpu_grep_match_v2(const uint8_t *buf, long long buflen,
                           const uint8_t *keys_cat,
                           const long long *key_offs, long long n_keys,
                           const int32_t *key_of_rule, long long n_rules,
                           const int16_t *trans_cat,
                           const long long *troffs,
                           const int32_t *cmaps, const int32_t *starts,
                           const int32_t *ncls,
                           uint8_t *match_out, long long max_records,
                           long long *offsets) {
    if (n_keys > FBTPU_MAX_KEYS) return -1;
    const uint8_t *p = buf, *end = buf + buflen;
    long long rec = 0;
    // phase 1: one msgpack walk extracts every key's (ptr, len) per
    // record into scratch, so phase 2 can run each rule's DFA over
    // contiguous rows with FBTPU_DFA_LANES-way interleaving
    const uint8_t **vals = new const uint8_t *[n_keys * max_records];
    uint32_t *vlens = new uint32_t[n_keys * max_records];
    while (p < end) {
        if (rec >= max_records) {
            delete[] vals;
            delete[] vlens;
            return -2;
        }
        if (offsets) offsets[rec] = p - buf;
        const uint8_t *rec_start = p;
        for (long long kx = 0; kx < n_keys; kx++)
            vals[kx * max_records + rec] = nullptr;
        uint32_t outer;
        const uint8_t *rec_end = nullptr;
        const uint8_t *q = read_array_hdr(p, end, &outer);
        if (q && outer >= 2) {
            const uint8_t *body = skip_obj(q, end, 0);
            if (body) {
                uint32_t pairs;
                const uint8_t *kv = read_map_hdr(body, end, &pairs);
                if (kv) {
                    // one map walk resolves every rule's field; LAST
                    // duplicate occurrence wins (dict-decode parity)
                    for (uint32_t i = 0; i < pairs && kv; i++) {
                        uint32_t klen;
                        const uint8_t *kstr = read_str_hdr(kv, end, &klen);
                        const uint8_t *val;
                        long long match_kx = -1;
                        if (kstr) {
                            val = kstr + klen;
                            if (val > end) { kv = nullptr; break; }
                            for (long long kx = 0; kx < n_keys; kx++) {
                                long long kl =
                                    key_offs[kx + 1] - key_offs[kx];
                                if (kl == (long long)klen &&
                                    memcmp(kstr, keys_cat + key_offs[kx],
                                           klen) == 0) {
                                    match_kx = kx;
                                    break;
                                }
                            }
                        } else {
                            val = skip_obj(kv, end, 0);  // non-str key
                            if (!val) { kv = nullptr; break; }
                        }
                        if (match_kx >= 0) {
                            uint32_t vlen;
                            const uint8_t *vstr =
                                read_str_hdr(val, end, &vlen);
                            long long slot =
                                match_kx * max_records + rec;
                            if (vstr && vstr + vlen <= end) {
                                vals[slot] = vstr;
                                vlens[slot] = vlen;
                            } else {
                                vals[slot] = nullptr;  // non-string
                            }
                        }
                        kv = skip_obj(val, end, 0);
                    }
                    // the pair walk ended exactly at the map's end: for
                    // the common [[ts, meta], body] shape that IS the
                    // record end — reuse it instead of re-walking the
                    // whole record with skip_obj
                    if (kv && outer == 2) rec_end = kv;
                }
            }
        }
        p = rec_end ? rec_end : skip_obj(rec_start, end, 0);
        if (!p) {
            delete[] vals;
            delete[] vlens;
            return -1;
        }
        rec++;
    }
    if (offsets) offsets[rec] = buflen;
    // phase 2: per-rule interleaved DFA sweep. Rows are independent, so
    // large batches fan out across host threads (the ctypes caller has
    // already released the GIL). FBTPU_DFA_THREADS caps the fan-out.
    auto sweep = [&](long long r, long long lo, long long hi) {
        const int16_t *trans = trans_cat + troffs[r];
        const int32_t *cmap = cmaps + r * 257;
        const uint8_t *const *kv = vals + key_of_rule[r] * max_records;
        const uint32_t *kl = vlens + key_of_rule[r] * max_records;
        uint8_t *out = match_out + r * max_records;
        // ncls encodes C and the super-step k: C + 1000*(k-1)
        int32_t enc = ncls[r];
        int k = enc / 1000 + 1;
        int32_t C = enc % 1000;
        for (long long i = lo; i < hi; i += FBTPU_DFA_LANES) {
            int nrows = (int)(hi - i < FBTPU_DFA_LANES
                              ? hi - i : FBTPU_DFA_LANES);
            if (k == 4)
                dfa_run_block_k<4>(trans, cmap, C, starts[r],
                                   kv + i, kl + i, nrows, out + i);
            else if (k == 3)
                dfa_run_block_k<3>(trans, cmap, C, starts[r],
                                   kv + i, kl + i, nrows, out + i);
            else if (k == 2)
                dfa_run_block_k<2>(trans, cmap, C, starts[r],
                                   kv + i, kl + i, nrows, out + i);
            else
                dfa_run_block(trans, cmap, C, starts[r],
                              kv + i, kl + i, nrows, out + i);
        }
    };
    int nthreads = rec >= 4096 ? pool_threads_wanted() : 1;
    if (nthreads <= 1) {
        for (long long r = 0; r < n_rules; r++) sweep(r, 0, rec);
    } else {
        // split rows into nthreads slices (lane-aligned), all rules in
        // each slice — one spawn wave regardless of rule count
        std::thread workers[16];
        long long step = (rec + nthreads - 1) / nthreads;
        step = ((step + FBTPU_DFA_LANES - 1) / FBTPU_DFA_LANES)
               * FBTPU_DFA_LANES;
        int spawned = 0;
        for (int t = 0; t < nthreads; t++) {
            long long lo = (long long)t * step;
            if (lo >= rec) break;
            long long hi = lo + step < rec ? lo + step : rec;
            workers[spawned++] = std::thread([&sweep, n_rules, lo, hi] {
                for (long long r = 0; r < n_rules; r++)
                    sweep(r, lo, hi);
            });
        }
        for (int t = 0; t < spawned; t++) workers[t].join();
    }
    delete[] vals;
    delete[] vlens;
    return rec;
}

// Copy the records whose keep[i] != 0 into out, preserving order.
// offsets has n+1 entries (from fbtpu_stage_field / fbtpu_scan_offsets).
// Returns bytes written; out must hold buflen bytes (worst case).
long long fbtpu_compact(const uint8_t *buf, long long buflen,
                        const long long *offsets, const uint8_t *keep,
                        long long n, uint8_t *out) {
    long long w = 0;
    for (long long i = 0; i < n; i++) {
        if (!keep[i]) continue;
        long long a = offsets[i], b = offsets[i + 1];
        if (a < 0 || b > buflen || b < a) return -1;
        memcpy(out + w, buf + a, (size_t)(b - a));
        w += b - a;
    }
    return w;
}

// ---------------------------------------------------------------------
// Fused grep filter: one pass over chunk bytes doing field extraction,
// accelerated DFA matching, verdict, and run-coalesced compaction.
//
// The DFA acceleration exploits the dominant shape of log-matching
// automata (apache2-style "[^ ]* ... [^\]]* ... .*$" skeletons): most
// live states SELF-LOOP on nearly every byte and leave only on one or
// two delimiter bytes. The Python side (native.GrepFilterTables)
// precomputes, per state, the escape-byte set; states with <=2 escape
// bytes carry an accel word and the runtime skips straight to the next
// escape byte with a 16-lane SIMD compare instead of walking the
// transition table byte-by-byte. Self-loop skipping is exact (the
// state is unchanged by skipped bytes, by construction), so verdicts
// stay bit-identical to the table walk, the jax kernel, and the Python
// regex engine.
//
// accel[s] encoding: bits 0-1 = 0 none / 1 one escape byte / 2 two /
// 3 no escape bytes at all (skip to end); bits 8-15 byte1; 16-23 byte2.
// ---------------------------------------------------------------------

// ---------------------------------------------------------------------
// Super-symbol prepass + interleaved walk (the fused filter's matcher).
//
// A DFA walk is a serial dependency chain; the classic fix (8
// interleaved lanes, dfa_run_block above) leaves the per-step class
// lookups and k-byte combines INSIDE the latency-bound loop. Splitting
// the work makes both halves fast:
//   A. prepass — per record, byte classes combine into k-byte
//      super-symbols in a branchless, position-independent loop the
//      CPU can run at superscalar width;
//   B. walk — per 8-lane block, each step is exactly one scratch load
//      and one dependent table load: s = T[s*Ck + sym].
// Pad steps use the absorbing EOL super-symbol, so lanes of different
// lengths stay in lockstep with no branches.
// ---------------------------------------------------------------------

#define FBTPU_PRE_LANES 16

// ---------------------------------------------------------------------
// Escape-byte accelerated scalar matcher (the accel[s] design in the
// fused-filter comment above): a state that leaves only on one or two
// bytes skips straight to the next escape byte with memchr / a 16-wide
// SIMD compare; a state with NO escape bytes is fixed until EOL. Exact:
// skipped bytes provably keep the state unchanged.
//   accel[s]: bits 0-1 kind (0 step / 1 one byte / 2 two / 3 fixed),
//   bits 8-15 byte1, 16-23 byte2.
// ---------------------------------------------------------------------

static inline uint32_t scan_one_byte(const uint8_t *v, uint32_t i,
                                     uint32_t len, uint8_t b1) {
#ifdef FBTPU_HAVE_SSE2
    __m128i m1 = _mm_set1_epi8((char)b1);
    while (i + 16 <= len) {
        __m128i x = _mm_loadu_si128((const __m128i *)(v + i));
        int mask = _mm_movemask_epi8(_mm_cmpeq_epi8(x, m1));
        if (mask) return i + (uint32_t)__builtin_ctz((unsigned)mask);
        i += 16;
    }
#endif
    for (; i < len; i++)
        if (v[i] == b1) return i;
    return 0xFFFFFFFFu;
}

static inline uint32_t scan_two_bytes(const uint8_t *v, uint32_t i,
                                      uint32_t len, uint8_t b1,
                                      uint8_t b2) {
#ifdef FBTPU_HAVE_SSE2
    __m128i m1 = _mm_set1_epi8((char)b1), m2 = _mm_set1_epi8((char)b2);
    while (i + 16 <= len) {
        __m128i x = _mm_loadu_si128((const __m128i *)(v + i));
        int mask = _mm_movemask_epi8(
            _mm_or_si128(_mm_cmpeq_epi8(x, m1), _mm_cmpeq_epi8(x, m2)));
        if (mask) return i + (uint32_t)__builtin_ctz((unsigned)mask);
        i += 16;
    }
#endif
    for (; i < len; i++)
        if (v[i] == b1 || v[i] == b2) return i;
    return 0xFFFFFFFFu;
}

// One record through the tables with skipping — a HYBRID walk:
// skippy states (<=2 escape bytes) jump via memchr/SIMD; dense states
// step through the k-composed table (4 bytes per dependent load when
// the pair-class table is available) so a skip-poor stretch costs no
// more than the lockstep engine's per-byte work. DEAD(0) and ACC(1)
// are absorbing; the trailing EOL step is safe from either.
static inline uint8_t dfa_accel_match(const int16_t *bt,
                                      const int32_t *cmap, int32_t C,
                                      int32_t start,
                                      const uint32_t *accel,
                                      const int16_t *transk,
                                      const uint16_t *cmap2,
                                      int k, int32_t Ck,
                                      const uint8_t *v, uint32_t len) {
    int32_t s = start;
    int32_t C2 = C * C;
    uint32_t i = 0;
    while (i < len) {
        uint32_t a = accel[s];
        uint32_t kind = a & 3u;
        if (kind == 0u) {
            // dense state: composed 4-byte step when possible
            if (k == 4 && cmap2 != nullptr && i + 4 <= len) {
                uint16_t w0, w1;
                memcpy(&w0, v + i, 2);
                memcpy(&w1, v + i + 2, 2);
                s = transk[s * Ck + (int32_t)cmap2[w0] * C2 + cmap2[w1]];
                i += 4;
            } else {
                s = bt[s * C + cmap[v[i]]];
                i++;
            }
            if (s <= 1) break;
            continue;
        }
        if (kind == 3u) {
            i = len;  // state cannot change before EOL
            break;
        }
        if (kind == 1u) {
            i = scan_one_byte(v, i, len, (uint8_t)((a >> 8) & 0xffu));
            if (i == 0xFFFFFFFFu) { i = len; break; }
        } else {  // kind == 2
            i = scan_two_bytes(v, i, len, (uint8_t)((a >> 8) & 0xffu),
                               (uint8_t)((a >> 16) & 0xffu));
            if (i == 0xFFFFFFFFu) { i = len; break; }
        }
        s = bt[s * C + cmap[v[i]]];  // step on the escape byte
        i++;
        if (s <= 1) break;  // absorbed
    }
    s = bt[s * C + cmap[256]];  // EOL step
    return (uint8_t)(s == 1);
}

// cmap2 (optional, even k only): 64K-entry byte-PAIR class table
// cmap2[b0 + (b1<<8)] = class(b0)*C + class(b1) — one load classifies
// two bytes, and for k=4 two pair-lookups make a whole super-symbol:
// sym = p01*C^2 + p23. Halves the prepass load count, which dominates
// the matcher once the walk is down to two loads per step.
static void dfa_prepass_block(const int16_t *transk, const int32_t *cmap,
                              const uint16_t *cmap2,
                              int32_t C, int k, int32_t Ck, int32_t start,
                              const uint8_t *const *vals,
                              const uint32_t *lens, int nrows,
                              uint8_t *out, uint16_t *syms) {
    const int W = FBTPU_PRE_LANES;
    int32_t eol = cmap[256];
    int32_t eol_super = 0;
    for (int b = 0; b < k; b++) eol_super = eol_super * C + eol;
    long long steps[W];
    long long max_steps = 0;
    for (int j = 0; j < W; j++) {
        long long len =
            (j < nrows && vals[j] != nullptr) ? (long long)lens[j] : -1LL;
        if (len < 0) {
            steps[j] = 0;  // missing/non-string: stays DEAD
        } else {
            steps[j] = len / k + 1;  // >=1 trailing EOL symbol
            if (steps[j] > max_steps) max_steps = steps[j];
        }
    }
    // phase A: branchless super-symbol build. Scratch layout is
    // [step][lane] so phase B's 8 lane loads per step share one cache
    // line instead of touching 8 strided rows.
    for (int j = 0; j < W; j++) {
        if (steps[j] == 0) {
            // lane is DEAD from the start (missing/non-string field or
            // j >= nrows). Phase B still LOADS this lane's column every
            // step, so it must hold valid symbols (< Ck) — fill with the
            // absorbing EOL super-symbol. Leaving it uninitialized reads
            // garbage that can index past the transition table.
            uint16_t *col = syms + j;
            for (long long i = 0; i < max_steps; i++)
                col[i * W] = (uint16_t)eol_super;
            continue;
        }
        uint16_t *col = syms + j;
        const uint8_t *v = vals[j];
        long long len = lens[j];
        long long full = len / k;  // groups with no pad byte
        long long i = 0;
        if (cmap2 != nullptr && k == 4) {
            int32_t C2 = C * C;
            for (; i < full; i++) {
                const uint8_t *g = v + i * 4;
                uint16_t w0, w1;
                memcpy(&w0, g, 2);      // little-endian: b0 + (b1<<8)
                memcpy(&w1, g + 2, 2);
                col[i * W] = (uint16_t)(cmap2[w0] * C2 + cmap2[w1]);
            }
        } else if (cmap2 != nullptr && k == 2) {
            for (; i < full; i++) {
                uint16_t w0;
                memcpy(&w0, v + i * 2, 2);
                col[i * W] = cmap2[w0];
            }
        } else {
            for (; i < full; i++) {
                long long base = i * k;
                int32_t cc = cmap[v[base]];
                for (int b = 1; b < k; b++)
                    cc = cc * C + cmap[v[base + b]];
                col[i * W] = (uint16_t)cc;
            }
        }
        for (; i < steps[j]; i++) {  // tail group: pad with EOL
            long long base = i * k;
            int32_t cc = 0;
            for (int b = 0; b < k; b++) {
                long long idx = base + b;
                cc = cc * C + (idx < len ? cmap[v[idx]] : eol);
            }
            col[i * W] = (uint16_t)cc;
        }
        for (; i < max_steps; i++) col[i * W] = (uint16_t)eol_super;
    }
    // phase B: lockstep walk — 2 loads per lane-step
    int32_t s[W];
    for (int j = 0; j < W; j++)
        s[j] = steps[j] ? start : 0;
    const uint16_t *row = syms;
    for (long long i = 0; i < max_steps; i++, row += W) {
        int32_t acc = 0;
        for (int j = 0; j < W; j++) {
            s[j] = transk[s[j] * Ck + row[j]];
            acc |= s[j];
        }
        if (acc <= 1) break;  // all lanes absorbed (DEAD/ACC)
    }
    for (int j = 0; j < W && j < nrows; j++)
        out[j] = (uint8_t)(s[j] == 1);
}

// slice-parallel jobs for the fused filter's phase 2 (records within
// a rule are independent; mrow writes are disjoint per slice)
struct GrepAccelJob {
    const int16_t *bt;
    const int32_t *cmap;
    const uint32_t *accel;
    const int16_t *transk;
    const uint16_t *cmap2;
    int32_t C;
    int k;
    int32_t Ck;
    int32_t start;
    const uint8_t *const *kv;
    const uint32_t *kl;
    uint8_t *mrow;
    long long n_rec;
    long long slice;
    int n_slices;
};

static void grep_accel_slice(const void *ctx, int sx) {
    const GrepAccelJob *j = (const GrepAccelJob *)ctx;
    long long lo = (long long)sx * j->slice;
    long long hi = lo + j->slice < j->n_rec ? lo + j->slice : j->n_rec;
    for (long long i = lo; i < hi; i++)
        j->mrow[i] = j->kv[i] != nullptr
            ? dfa_accel_match(j->bt, j->cmap, j->C, j->start, j->accel,
                              j->transk, j->cmap2, j->k, j->Ck,
                              j->kv[i], j->kl[i])
            : 0;
}

struct GrepBlockJob {
    const int16_t *trans;
    const int32_t *cmap;
    const uint16_t *cmap2;
    int32_t C;
    int k;
    int32_t Ck;
    int32_t start;
    long long max_vlen;
    const uint8_t *const *kv;
    const uint32_t *kl;
    const int32_t *ord;
    uint8_t *mrow;
    long long n_rec;
    long long slice;  // records per slice (multiple of FBTPU_PRE_LANES)
    int n_slices;
};

static void grep_block_slice(const void *ctx, int sx) {
    const GrepBlockJob *j = (const GrepBlockJob *)ctx;
    // per-worker prepass scratch (grows to the chunk's longest value)
    static thread_local uint16_t *syms = nullptr;
    static thread_local long long syms_cap = 0;
    long long need = FBTPU_PRE_LANES * (j->max_vlen / j->k + 2);
    if (need > syms_cap) {
        delete[] syms;
        syms = new uint16_t[need];
        syms_cap = need;
    }
    long long lo = (long long)sx * j->slice;
    long long hi = lo + j->slice < j->n_rec ? lo + j->slice : j->n_rec;
    const uint8_t *bv[FBTPU_PRE_LANES];
    uint32_t bl[FBTPU_PRE_LANES];
    uint8_t bm[FBTPU_PRE_LANES];
    for (long long i = lo; i < hi; i += FBTPU_PRE_LANES) {
        int nrows = (int)(hi - i < FBTPU_PRE_LANES
                          ? hi - i : FBTPU_PRE_LANES);
        for (int jj = 0; jj < nrows; jj++) {
            bv[jj] = j->kv[j->ord[i + jj]];
            bl[jj] = j->kl[j->ord[i + jj]];
        }
        dfa_prepass_block(j->trans, j->cmap, j->cmap2, j->C, j->k,
                          j->Ck, j->start, bv, bl, nrows, bm, syms);
        for (int jj = 0; jj < nrows; jj++)
            j->mrow[j->ord[i + jj]] = bm[jj];
    }
}

#define FBTPU_OP_LEGACY 0
#define FBTPU_OP_AND 1
#define FBTPU_OP_OR 2

// Verdict semantics are grep.c's (plugins/filter_grep/grep.c:167-284 in
// the reference; same logic as plugins/filter_grep.py keep_record /
// keep_mask):
//  legacy — first matching rule decides (!exclude), a non-matching
//           keep-rule decides EXCLUDE, fallthrough keeps;
//  AND/OR — all/any rules match, verdict = found XOR exclude (rule
//           kinds are uniform in these modes, enforced at config time).
//
// Three phases over chunk bytes:
//   1. one msgpack walk extracts every key's (ptr, len) per record
//   2. per rule, the interleaved accel matcher fills a match row
//   3. verdict + run-coalesced compaction (contiguous kept records
//      collapse into single memcpys; an all-kept chunk copies nothing
//      and the caller reuses the input buffer)
//
// out_info[0]=n_records, out_info[1]=n_kept, out_info[2]=1 if `out`
// holds the compacted bytes (0 = every record kept, out untouched).
// Returns bytes written, -1 malformed, -2 capacity exceeded.
long long fbtpu_grep_filter(const uint8_t *buf, long long buflen,
                            const uint8_t *keys_cat,
                            const long long *key_offs, long long n_keys,
                            const int32_t *key_of_rule, long long n_rules,
                            const int16_t *trans_cat,
                            const long long *troffs,
                            const int32_t *cmaps, const int32_t *starts,
                            const int32_t *ncls,
                            const uint16_t *cmap2_cat,
                            const long long *cm2offs,
                            const int16_t *btrans_cat,
                            const long long *btroffs,
                            const uint32_t *accel_cat,
                            const long long *aoffs,
                            const uint8_t *rule_exclude, int32_t op_mode,
                            long long max_records,
                            uint8_t *out, long long *out_info) {
    if (n_keys > FBTPU_MAX_KEYS) return -1;
    const uint8_t *p = buf, *end = buf + buflen;
    long long n_rec = 0;
    // ---- phase 1: extraction walk ----
    // thread-local growable scratch: the fused filter runs per chunk on
    // the ingest hot path, so per-call new[]/delete[] of multi-MB
    // arrays (and the page faults behind them) must not recur
    static thread_local const uint8_t **vals = nullptr;
    static thread_local uint32_t *vlens = nullptr;
    static thread_local long long *offsets = nullptr;
    static thread_local uint8_t *match = nullptr;
    static thread_local long long cap_vals = 0, cap_offs = 0, cap_match = 0;
    if (n_keys * max_records > cap_vals) {
        delete[] vals; delete[] vlens;
        cap_vals = n_keys * max_records;
        vals = new const uint8_t *[cap_vals];
        vlens = new uint32_t[cap_vals];
    }
    if (max_records + 1 > cap_offs) {
        delete[] offsets;
        cap_offs = max_records + 1;
        offsets = new long long[cap_offs];
    }
    if (n_rules * max_records > cap_match) {
        delete[] match;
        cap_match = n_rules * max_records;
        match = new uint8_t[cap_match];
    }
    while (p < end) {
        if (n_rec >= max_records) return -2;
        offsets[n_rec] = p - buf;
        const uint8_t *rec_start = p;
        for (long long kx = 0; kx < n_keys; kx++)
            vals[kx * max_records + n_rec] = nullptr;
        uint32_t outer;
        const uint8_t *rec_end = nullptr;
        const uint8_t *q = read_array_hdr(p, end, &outer);
        if (q && outer >= 2) {
            const uint8_t *body = skip_obj(q, end, 0);
            if (body) {
                uint32_t pairs;
                const uint8_t *kv = read_map_hdr(body, end, &pairs);
                if (kv) {
                    // one map walk resolves every rule's field; LAST
                    // duplicate occurrence wins (dict-decode parity)
                    for (uint32_t i = 0; i < pairs && kv; i++) {
                        uint32_t klen;
                        const uint8_t *kstr = read_str_hdr(kv, end, &klen);
                        const uint8_t *val;
                        long long match_kx = -1;
                        if (kstr) {
                            val = kstr + klen;
                            if (val > end) { kv = nullptr; break; }
                            for (long long kx = 0; kx < n_keys; kx++) {
                                long long kl =
                                    key_offs[kx + 1] - key_offs[kx];
                                if (kl == (long long)klen &&
                                    memcmp(kstr, keys_cat + key_offs[kx],
                                           klen) == 0) {
                                    match_kx = kx;
                                    break;
                                }
                            }
                        } else {
                            val = skip_obj(kv, end, 0);  // non-str key
                            if (!val) { kv = nullptr; break; }
                        }
                        if (match_kx >= 0) {
                            uint32_t vlen;
                            const uint8_t *vstr =
                                read_str_hdr(val, end, &vlen);
                            long long slot = match_kx * max_records + n_rec;
                            if (vstr && vstr + vlen <= end) {
                                vals[slot] = vstr;
                                vlens[slot] = vlen;
                            } else {
                                vals[slot] = nullptr;  // non-string
                            }
                        }
                        kv = skip_obj(val, end, 0);
                    }
                    if (kv && outer == 2) rec_end = kv;
                }
            }
        }
        p = rec_end ? rec_end : skip_obj(rec_start, end, 0);
        if (!p) return -1;
        n_rec++;
    }
    offsets[n_rec] = buflen;
    // ---- phase 2: per-rule prepass + lockstep walk ----
    // scratch sized to the longest value in the chunk
    long long max_vlen = 0;
    for (long long kx = 0; kx < n_keys; kx++)
        for (long long i = 0; i < n_rec; i++)
            if (vals[kx * max_records + i] != nullptr &&
                (long long)vlens[kx * max_records + i] > max_vlen)
                max_vlen = vlens[kx * max_records + i];
    // length-sorted processing order (per key): blocks of 16 lanes pad
    // every lane to the block's longest value, so feeding blocks
    // length-homogeneous records removes the padding waste of mixed
    // traffic. Counting sort over 64-byte length buckets; match rows
    // are written through the order array, so output order is intact.
    static thread_local int32_t *order = nullptr;
    static thread_local long long order_cap = 0;
    if (n_keys * n_rec > order_cap) {
        delete[] order;
        order_cap = n_keys * n_rec;
        order = new int32_t[order_cap];
    }
    bool order_built[FBTPU_MAX_KEYS] = {false};
    const int N_BUCKETS = 64;
    for (long long r = 0; r < n_rules; r++) {
        if (aoffs != nullptr && aoffs[r] >= 0)
            continue;  // accel rules don't use the sorted order
        long long kx = key_of_rule[r];
        if (!order_built[kx]) {
            order_built[kx] = true;
            int32_t *ord = order + kx * n_rec;
            const uint8_t *const *kv = vals + kx * max_records;
            const uint32_t *kl = vlens + kx * max_records;
            long long counts[N_BUCKETS + 1] = {0};
            auto bucket = [&](long long i) -> int {
                if (kv[i] == nullptr) return 0;
                long long b = kl[i] / 64 + 1;
                return b > N_BUCKETS ? N_BUCKETS : (int)b;
            };
            for (long long i = 0; i < n_rec; i++) counts[bucket(i)]++;
            long long pos = 0;
            long long starts_b[N_BUCKETS + 1];
            for (int b = 0; b <= N_BUCKETS; b++) {
                starts_b[b] = pos;
                pos += counts[b];
            }
            for (long long i = 0; i < n_rec; i++)
                ord[starts_b[bucket(i)]++] = (int32_t)i;
        }
    }
    // records are independent within a rule, so each rule's matcher
    // fans out over LANE-ALIGNED record slices on the worker pool when
    // the host has cores to spend (the per-worker prepass scratch is
    // thread_local inside the slice fns). A 1-core host keeps the
    // single-slice path with zero dispatch overhead.
    int p2_threads = n_rec >= 4096 ? pool_threads_wanted() : 1;
    if (p2_threads > 1) g_pool.ensure(pool_threads_wanted() - 1);
    for (long long r = 0; n_rec > 0 && r < n_rules; r++) {
        const int32_t *cmap = cmaps + r * 257;
        if (aoffs != nullptr && aoffs[r] >= 0) {
            // skip-friendly DFA: escape-byte hybrid matcher (memchr /
            // SIMD skips in self-loop states, composed 4-byte steps in
            // dense ones)
            int32_t enc_a = ncls[r];
            GrepAccelJob aj;
            aj.bt = btrans_cat + btroffs[r];
            aj.cmap = cmap;
            aj.accel = accel_cat + aoffs[r];
            aj.transk = trans_cat + troffs[r];
            aj.cmap2 = cm2offs[r] >= 0 ? cmap2_cat + cm2offs[r] : nullptr;
            aj.C = enc_a % 1000;
            aj.k = enc_a / 1000 + 1;
            aj.Ck = 1;
            for (int b = 0; b < aj.k; b++) aj.Ck *= aj.C;
            aj.start = starts[r];
            aj.kv = vals + key_of_rule[r] * max_records;
            aj.kl = vlens + key_of_rule[r] * max_records;
            aj.mrow = match + r * max_records;
            aj.n_rec = n_rec;
            int slices = p2_threads > 1 ? g_pool.n_workers + 1 : 1;
            if (slices > p2_threads) slices = p2_threads;
            aj.slice = (n_rec + slices - 1) / slices;
            aj.n_slices = (int)((n_rec + aj.slice - 1) / aj.slice);
            if (aj.n_slices > 1)
                g_pool.run(grep_accel_slice, &aj, aj.n_slices);
            else
                grep_accel_slice(&aj, 0);
            continue;
        }
        int32_t enc = ncls[r];
        GrepBlockJob bj;
        bj.trans = trans_cat + troffs[r];
        bj.cmap = cmap;
        bj.cmap2 = cm2offs[r] >= 0 ? cmap2_cat + cm2offs[r] : nullptr;
        // ncls encodes C and the super-step k: C + 1000*(k-1)
        bj.k = enc / 1000 + 1;
        bj.C = enc % 1000;
        bj.Ck = 1;
        for (int b = 0; b < bj.k; b++) bj.Ck *= bj.C;
        bj.start = starts[r];
        bj.max_vlen = max_vlen;
        bj.kv = vals + key_of_rule[r] * max_records;
        bj.kl = vlens + key_of_rule[r] * max_records;
        bj.ord = order + key_of_rule[r] * n_rec;
        bj.mrow = match + r * max_records;
        bj.n_rec = n_rec;
        int slices = p2_threads > 1 ? g_pool.n_workers + 1 : 1;
        if (slices > p2_threads) slices = p2_threads;
        long long per = (n_rec + slices - 1) / slices;
        // lane-aligned slices: blocks of FBTPU_PRE_LANES stay whole
        per = ((per + FBTPU_PRE_LANES - 1) / FBTPU_PRE_LANES)
              * FBTPU_PRE_LANES;
        bj.slice = per;
        bj.n_slices = (int)((n_rec + per - 1) / per);
        if (bj.n_slices > 1)
            g_pool.run(grep_block_slice, &bj, bj.n_slices);
        else
            grep_block_slice(&bj, 0);
    }
    // ---- phase 3: verdict + run-coalesced compaction ----
    long long n_keep = 0, w = 0, run_s = 0, run_e = 0;
    for (long long i = 0; i < n_rec; i++) {
        int keep;
        if (n_rules == 0) {
            keep = 1;
        } else if (op_mode == FBTPU_OP_LEGACY) {
            keep = 1;  // fallthrough keeps
            for (long long r = 0; r < n_rules; r++) {
                if (match[r * max_records + i]) {
                    keep = !rule_exclude[r];
                    break;
                }
                if (!rule_exclude[r]) { keep = 0; break; }
            }
        } else {
            int found = (op_mode == FBTPU_OP_AND);
            for (long long r = 0; r < n_rules; r++) {
                found = match[r * max_records + i];
                if (op_mode == FBTPU_OP_OR && found) break;
                if (op_mode == FBTPU_OP_AND && !found) break;
            }
            keep = rule_exclude[0] ? !found : found;
        }
        if (keep) {
            n_keep++;
            long long rs = offsets[i], re = offsets[i + 1];
            if (rs == run_e) {
                run_e = re;  // contiguous keep: extend the pending run
            } else {
                if (run_e > run_s) {
                    memcpy(out + w, buf + run_s, (size_t)(run_e - run_s));
                    w += run_e - run_s;
                }
                run_s = rs;
                run_e = re;
            }
        }
    }
    out_info[0] = n_rec;
    out_info[1] = n_keep;
    if (n_keep == n_rec) {
        out_info[2] = 0;  // nothing dropped: caller reuses the input
        return 0;
    }
    if (run_e > run_s) {
        memcpy(out + w, buf + run_s, (size_t)(run_e - run_s));
        w += run_e - run_s;
    }
    out_info[2] = 1;
    return w;
}


}  // extern "C"
