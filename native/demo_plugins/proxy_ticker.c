/* Go-proxy-contract demo INPUT (reference src/proxy/go/go.c
 * proxy_go_input_*): FLBPluginInputCallback returns a malloc'd
 * msgpack event buffer the host ingests and then hands to
 * FLBPluginInputCleanupCallback. */

#include <stdlib.h>
#include <string.h>

struct flb_plugin_proxy_def {
    int type;
    int proxy;
    int flags;
    char *name;
    char *description;
    int event_type;
};

/* include/fluent-bit/flb_api.h layout — custom_* entries LAST (same
 * contract proxy_counter.c documents; both demos pin the host table) */
struct flb_api {
    char *(*output_get_property)(char *, void *);
    char *(*input_get_property)(char *, void *);
    void *(*output_get_cmt_instance)(void *);
    void *(*input_get_cmt_instance)(void *);
    void *log_print;
    int (*input_log_check)(void *, int);
    int (*output_log_check)(void *, int);
    char *(*custom_get_property)(char *, void *);
    int (*custom_log_check)(void *, int);
};

struct flbgo_input_plugin {
    char *name;
    struct flb_api *api;
    void *i_ins;
    void *context;
    int (*cb_init)(struct flbgo_input_plugin *);
    int (*cb_collect)(void **, size_t *);
    int (*cb_collect_ctx)(void *, void **, size_t *);
    int (*cb_cleanup)(void *);
    int (*cb_cleanup_ctx)(void *, void *);
    int (*cb_exit)(void);
};

#define FLB_PROXY_INPUT_PLUGIN 1
#define FLB_PROXY_GOLANG 11

static int g_ticks = 0;
static int g_cleanups = 0;
static int g_logcheck = -1;

int FLBPluginRegister(struct flb_plugin_proxy_def *def)
{
    def->type = FLB_PROXY_INPUT_PLUGIN;
    def->proxy = FLB_PROXY_GOLANG;
    def->flags = 0;
    def->name = strdup("goticker");
    def->description = strdup("proxy-contract demo input");
    def->event_type = 0;
    return 0;
}

int FLBPluginInit(struct flbgo_input_plugin *p)
{
    /* exercise mid-table api slots (input_get_property = slot 1,
     * input_log_check = slot 5 in the header layout): a shifted table
     * would hand back the wrong function kinds here */
    char *start = p->api->input_get_property((char *) "start", p->i_ins);
    if (start != NULL && start[0] != '\0') {
        g_ticks = atoi(start);
    }
    g_logcheck = p->api->input_log_check(p->i_ins, 3);
    return 1;
}

/* legacy msgpack event: [double ts, {"msg": "tick", "n": <i>}] */
int FLBPluginInputCallback(void **data, size_t *size)
{
    unsigned char *buf = malloc(64);
    size_t w = 0;
    union { double d; unsigned long long u; } ts;
    int i;

    if (buf == NULL) {
        return -1;
    }
    ts.d = 1700000000.0 + g_ticks;
    buf[w++] = 0x92;              /* fixarray 2 */
    buf[w++] = 0xcb;              /* float64, big-endian */
    for (i = 7; i >= 0; i--) {
        buf[w++] = (unsigned char) ((ts.u >> (i * 8)) & 0xff);
    }
    buf[w++] = 0x82;              /* fixmap 2 */
    buf[w++] = 0xa3; memcpy(buf + w, "msg", 3); w += 3;
    buf[w++] = 0xa4; memcpy(buf + w, "tick", 4); w += 4;
    buf[w++] = 0xa1; buf[w++] = 'n';
    buf[w++] = (unsigned char) (g_ticks & 0x7f);  /* positive fixint */
    g_ticks++;
    *data = buf;
    *size = w;
    return 0;
}

int FLBPluginInputCleanupCallback(void *data)
{
    free(data);
    g_cleanups++;
    return 0;
}

/* test hooks */
int demo_ticks(void) { return g_ticks; }
int demo_cleanups(void) { return g_cleanups; }
int demo_logcheck(void) { return g_logcheck; }

int FLBPluginExit(void)
{
    return 1;
}
