/* Go-proxy-contract demo OUTPUT — the shape a cgo-built
 * fluent-bit-go plugin exports (reference src/proxy/go/go.{c,h},
 * flb_plugin_proxy.c:347-433): the host calls FLBPluginRegister with
 * a definition struct the plugin fills, then FLBPluginInit receives
 * the plugin table and reads config through the api callback table;
 * FLBPluginFlush gets raw msgpack chunk bytes. Built live by the
 * runtime tests (tests/test_dso_proxy.py). */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

struct flb_plugin_proxy_def {
    int type;
    int proxy;
    int flags;
    char *name;
    char *description;
    int event_type;
};

/* include/fluent-bit/flb_api.h layout — the custom_* entries sit at
 * the END ("to preserve ABI"); indexing them anywhere else misroutes
 * every slot after output/input_get_property. */
struct flb_api {
    char *(*output_get_property)(char *, void *);
    char *(*input_get_property)(char *, void *);
    void *(*output_get_cmt_instance)(void *);
    void *(*input_get_cmt_instance)(void *);
    void *log_print;
    int (*input_log_check)(void *, int);
    int (*output_log_check)(void *, int);
    char *(*custom_get_property)(char *, void *);
    int (*custom_log_check)(void *, int);
};

struct flbgo_output_plugin {
    char *name;
    struct flb_api *api;
    void *o_ins;
    void *context;
    int (*cb_init)(struct flbgo_output_plugin *);
    int (*cb_flush)(const void *, size_t, const char *);
    int (*cb_flush_ctx)(void *, const void *, size_t, char *);
    int (*cb_exit)(void);
    int (*cb_exit_ctx)(void *);
};

#define FLB_PROXY_OUTPUT_PLUGIN 2
#define FLB_PROXY_GOLANG 11
#define FLB_ERROR 0
#define FLB_OK 1
#define FLB_RETRY 2

static char out_path[1024];
static char banner[256];
static int banner_logcheck = -1;

int FLBPluginRegister(struct flb_plugin_proxy_def *def)
{
    def->type = FLB_PROXY_OUTPUT_PLUGIN;
    def->proxy = FLB_PROXY_GOLANG;
    def->flags = 0;
    def->name = strdup("gocounter");
    def->description = strdup("proxy-contract demo output");
    def->event_type = 0;
    return 0;
}

int FLBPluginInit(struct flbgo_output_plugin *p)
{
    char *v = p->api->output_get_property((char *) "path", p->o_ins);
    char *b;
    if (v == NULL || v[0] == '\0') {
        return FLB_ERROR;
    }
    snprintf(out_path, sizeof(out_path), "%s", v);
    /* exercise NON-slot-0 api entries: custom_get_property lives in the
     * LAST pointer slots — a host whose table diverges from flb_api.h
     * (the assignment-order bug) hands back an int-returning function
     * here and the banner comes out garbage/crash */
    b = p->api->custom_get_property((char *) "banner", p->o_ins);
    if (b != NULL) {
        snprintf(banner, sizeof(banner), "%s", b);
        banner_logcheck = p->api->output_log_check(p->o_ins, 3);
    }
    return FLB_OK;
}

int FLBPluginFlush(const void *data, size_t size, const char *tag)
{
    FILE *f = fopen(out_path, "ab");
    if (f == NULL) {
        return FLB_RETRY;
    }
    if (banner[0] != '\0') {
        fprintf(f, "banner=%s logcheck=%d\n", banner, banner_logcheck);
        banner[0] = '\0';
    }
    fprintf(f, "tag=%s size=%zu\n", tag, size);
    fwrite(data, 1, size, f);
    fputc('\n', f);
    fclose(f);
    return FLB_OK;
}

int FLBPluginExit(void)
{
    FILE *f = fopen(out_path, "ab");
    if (f != NULL) {
        fputs("EXIT\n", f);
        fclose(f);
    }
    return FLB_OK;
}
