/* Demo native INPUT plugin for the fbtpu dynamic plugin ABI: each
 * collect emits `copies` JSON records carrying a running counter
 * (an in_dummy written in C++, proving the input side of the ABI the
 * way the reference's Zig bindings prove its vtables). */

#include <cstdio>
#include <cstring>
#include <string>

#include "../fbtpu_plugin.h"

namespace {

struct Ctx {
    long long counter = 0;
    int copies = 1;
};

int json_int_prop(const char *json, const char *key, int fallback) {
    std::string needle = std::string("\"") + key + "\":";
    const char *p = strstr(json, needle.c_str());
    if (!p) return fallback;
    p += needle.size();
    while (*p == ' ') p++;
    if (*p == '"') p++;
    int v = atoi(p);
    return v > 0 ? v : fallback;
}

void *demo_init(const char *props_json) {
    Ctx *ctx = new Ctx();
    ctx->copies = json_int_prop(props_json ? props_json : "{}",
                                "copies", 1);
    return ctx;
}

int demo_collect(void *vctx, void *host, const char *tag,
                 fbtpu_emit_fn emit) {
    Ctx *ctx = static_cast<Ctx *>(vctx);
    char buf[128];
    for (int i = 0; i < ctx->copies; i++) {
        int n = snprintf(buf, sizeof(buf),
                         "{\"source\": \"native\", \"n\": %lld}",
                         ctx->counter++);
        emit(host, tag, buf, n);
    }
    return ctx->copies;
}

void demo_destroy(void *vctx) {
    delete static_cast<Ctx *>(vctx);
}

}  // namespace

extern "C" fbtpu_input_plugin in_demo_plugin = {
    FBTPU_PLUGIN_ABI_VERSION,
    "native_demo",
    "demo native input (dynamic plugin ABI)",
    0.05,
    demo_init,
    demo_collect,
    demo_destroy,
};
