/* Demo native OUTPUT plugin for the fbtpu dynamic plugin ABI.
 *
 * The out_zig_demo role (reference plugins/out_zig_demo/main.zig:
 * a native-language plugin implementing the output vtable): each
 * flush appends one line `<tag> <bytes> <records>` to the file given
 * by the `path` property, counting records by walking the msgpack
 * event stream's top-level array headers.
 *
 * Built by the runtime tests with:
 *   g++ -shared -fPIC -O2 -I native -o out_demo.so \
 *       native/demo_plugins/out_demo.cpp
 */

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "../fbtpu_plugin.h"

namespace {

struct Ctx {
    std::string path;
    long long flushes = 0;
};

/* minimal "key":"value" scan — enough for the demo's flat props */
std::string json_str_prop(const char *json, const char *key) {
    std::string needle = std::string("\"") + key + "\":";
    const char *p = strstr(json, needle.c_str());
    if (!p) return "";
    p += needle.size();
    while (*p == ' ') p++;
    if (*p != '"') return "";
    p++;
    std::string out;
    while (*p && *p != '"') {
        if (*p == '\\' && p[1]) p++;
        out += *p++;
    }
    return out;
}

/* count top-level msgpack values (each log event is one array) */
long long count_events(const unsigned char *d, long long len) {
    long long n = 0;
    long long i = 0;
    while (i < len) {
        unsigned char b = d[i];
        if (b >= 0x90 && b <= 0x9f) { n++; }        /* fixarray */
        else if (b == 0xdc || b == 0xdd) { n++; }   /* array16/32 */
        else { break; }  /* not an event boundary we recognize */
        /* skip by re-scanning for the next top-level array is complex
         * without a full msgpack walker; the demo proves the ABI, so
         * count only the first header and bail */
        break;
    }
    return n;
}

void *demo_init(const char *props_json) {
    Ctx *ctx = new Ctx();
    ctx->path = json_str_prop(props_json ? props_json : "{}", "path");
    if (ctx->path.empty()) {
        delete ctx;
        return nullptr;  /* `path` is required */
    }
    return ctx;
}

int demo_flush(void *vctx, const unsigned char *data, long long len,
               const char *tag) {
    Ctx *ctx = static_cast<Ctx *>(vctx);
    FILE *f = fopen(ctx->path.c_str(), "a");
    if (!f) return FBTPU_PLUGIN_RETRY;
    fprintf(f, "%s %lld %lld\n", tag ? tag : "", len,
            count_events(data, len));
    fclose(f);
    ctx->flushes++;
    return FBTPU_PLUGIN_OK;
}

void demo_destroy(void *vctx) {
    delete static_cast<Ctx *>(vctx);
}

}  // namespace

extern "C" fbtpu_output_plugin out_demo_plugin = {
    FBTPU_PLUGIN_ABI_VERSION,
    "native_demo",
    "demo native output (dynamic plugin ABI)",
    demo_init,
    demo_flush,
    demo_destroy,
};
