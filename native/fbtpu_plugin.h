/* fbtpu dynamic plugin ABI (v1)
 *
 * The native-plugin surface of the framework: a shared object built
 * against this header is loaded at startup with dlopen (CLI `-e
 * <path>` or a `[PLUGINS]` section), mirroring the reference's
 * dynamic plugin loader (src/flb_plugin.c:200 — dlopen + a
 * registration symbol derived from the file name) and its
 * native-language plugin proof (lib/zig_fluent_bit + out_zig_demo).
 *
 * Contract:
 * - the object exports ONE registration symbol named `<stem>_plugin`
 *   where <stem> is the file name without directory/extension (an
 *   optional `flb-` prefix is stripped): `out_demo.so` must export
 *   `fbtpu_output_plugin out_demo_plugin`.
 * - the stem's prefix picks the type: `in_` → fbtpu_input_plugin,
 *   `out_` → fbtpu_output_plugin.
 * - strings returned by the plugin must stay valid for the object's
 *   lifetime; buffers passed IN are only valid during the call.
 */

#ifndef FBTPU_PLUGIN_H
#define FBTPU_PLUGIN_H

#ifdef __cplusplus
extern "C" {
#endif

#define FBTPU_PLUGIN_ABI_VERSION 1

/* flush verdicts (FLB_OK / FLB_RETRY / FLB_ERROR) */
#define FBTPU_PLUGIN_OK    0
#define FBTPU_PLUGIN_RETRY 1
#define FBTPU_PLUGIN_ERROR 2

/* Host ingest callback handed to input plugins: emit ONE record as a
 * JSON object (the host parses and re-encodes it as a log event). */
typedef void (*fbtpu_emit_fn)(void *host, const char *tag,
                              const char *json, long long len);

typedef struct fbtpu_output_plugin {
    int abi_version;           /* FBTPU_PLUGIN_ABI_VERSION */
    const char *name;          /* registry name */
    const char *description;
    /* props_json: the instance properties as a JSON object.
     * Return a context pointer, or NULL to fail initialization. */
    void *(*init)(const char *props_json);
    /* data: the chunk's raw msgpack event stream. Return a verdict. */
    int (*flush)(void *ctx, const unsigned char *data, long long len,
                 const char *tag);
    void (*destroy)(void *ctx);
} fbtpu_output_plugin;

typedef struct fbtpu_input_plugin {
    int abi_version;
    const char *name;
    const char *description;
    double collect_interval;   /* seconds between collect() calls */
    void *(*init)(const char *props_json);
    /* Called every interval; emit records via emit(host, tag, ...).
     * Return the number of records emitted, or -1 on error. */
    int (*collect)(void *ctx, void *host, const char *tag,
                   fbtpu_emit_fn emit);
    void (*destroy)(void *ctx);
} fbtpu_input_plugin;

#ifdef __cplusplus
}
#endif

#endif /* FBTPU_PLUGIN_H */
