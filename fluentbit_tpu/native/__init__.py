"""Native data-plane shim — ctypes bindings for fbtpu_native.

Builds native/fbtpu_native.cpp with g++ on first use (cached as
``native/build/fbtpu_native.so``; pybind11 is not available in this
image so the ABI is plain C via ctypes). Every entry point degrades
gracefully: if the toolchain or the .so is unavailable, callers fall
back to the pure-Python codec (``available()`` reports which path is
active).

API:
  count_records(buf)                       → int | None
  scan_offsets(buf)                        → numpy int64 offsets | None
  stage_field(buf, key, max_len, pad_to)   → (batch, lengths, offsets,
                                              n) | None
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("flb.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)),
                    "native", "fbtpu_native.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)),
                          "native", "build")
_SO = os.path.join(_BUILD_DIR, "fbtpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("FBTPU_NO_NATIVE"):
            return None
        # hash-cached build with prebuilt trust paths (buildlib: a
        # KNOWN-stale .so never loads — its ABI may not match the
        # Python callers, and a silent mismatch corrupts memory)
        from .buildlib import ensure_built

        cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
               "-pthread", _SRC, "-o", _SO]
        if not ensure_built(_SRC, _SO, cmd):
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native load failed: %s", e)
            return None
        lib.fbtpu_count_records.restype = ctypes.c_longlong
        lib.fbtpu_count_records.argtypes = [ctypes.c_char_p,
                                            ctypes.c_longlong]
        lib.fbtpu_scan_offsets.restype = ctypes.c_longlong
        lib.fbtpu_scan_offsets.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
        ]
        lib.fbtpu_stage_field.restype = ctypes.c_longlong
        lib.fbtpu_stage_field.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong),
        ]
        mt_fn = getattr(lib, "fbtpu_stage_field_mt", None)
        if mt_fn is not None:
            mt_fn.restype = ctypes.c_longlong
            mt_fn.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong,
                ctypes.c_char_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_longlong, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_int32,
            ]
        eff_fn = getattr(lib, "fbtpu_stage_effective_threads", None)
        if eff_fn is not None:
            eff_fn.restype = ctypes.c_int32
            eff_fn.argtypes = [ctypes.c_int32]
        # fbtpu-flux entry points (absent in a stale prebuilt .so:
        # callers then stay on their Python/device paths)
        f64_fn = getattr(lib, "fbtpu_stage_field_f64", None)
        if f64_fn is not None:
            f64_fn.restype = ctypes.c_longlong
            f64_fn.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong,
                ctypes.c_char_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_longlong, ctypes.POINTER(ctypes.c_longlong),
            ]
        hll_fn = getattr(lib, "fbtpu_hll_update", None)
        if hll_fn is not None:
            hll_fn.restype = None
            hll_fn.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
        cms_fn = getattr(lib, "fbtpu_cms_update", None)
        if cms_fn is not None:
            cms_fn.restype = ctypes.c_longlong
            cms_fn.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_int32, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_int32,
            ]
        lib.fbtpu_compact.restype = ctypes.c_longlong
        lib.fbtpu_compact.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_uint8),
        ]
        try:
            grep_fn = lib.fbtpu_grep_match_v2
        except AttributeError:
            # prebuilt .so from an older source (hash-less trust path):
            # the scanner entry points still work; grep_match() reports
            # unavailable and callers use their staged/Python paths
            grep_fn = None
            log.warning("fbtpu_grep_match_v2 absent in %s (stale prebuilt?)",
                        _SO)
        if grep_fn is not None:
            grep_fn.restype = ctypes.c_longlong
            grep_fn.argtypes = _grep_match_argtypes()
        filter_fn = getattr(lib, "fbtpu_grep_filter", None)
        if filter_fn is not None:
            i32p = ctypes.POINTER(ctypes.c_int32)
            i64p = ctypes.POINTER(ctypes.c_longlong)
            filter_fn.restype = ctypes.c_longlong
            filter_fn.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong,       # buf
                ctypes.c_char_p,                          # keys_cat
                i64p, ctypes.c_longlong,                  # key_offs
                i32p, ctypes.c_longlong,                  # key_of_rule
                ctypes.POINTER(ctypes.c_int16),           # trans_cat
                i64p,                                     # troffs
                i32p, i32p, i32p,                         # cmaps/starts/ncls
                ctypes.POINTER(ctypes.c_uint16), i64p,    # cmap2/cm2offs
                ctypes.POINTER(ctypes.c_int16), i64p,     # btrans/btroffs
                ctypes.POINTER(ctypes.c_uint32), i64p,    # accel/aoffs
                ctypes.POINTER(ctypes.c_uint8),           # rule_exclude
                ctypes.c_int32,                           # op_mode
                ctypes.c_longlong,                        # max_records
                ctypes.POINTER(ctypes.c_uint8),           # out
                i64p,                                     # out_info
            ]
        _lib = lib
        return _lib


def _grep_match_argtypes():
    return [
            ctypes.c_char_p, ctypes.c_longlong,          # buf
            ctypes.c_char_p,                             # keys_cat
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int16),              # trans_cat (i16)
            ctypes.POINTER(ctypes.c_longlong),           # troffs
            ctypes.POINTER(ctypes.c_int32),              # cmaps
            ctypes.POINTER(ctypes.c_int32),              # starts
            ctypes.POINTER(ctypes.c_int32),              # ncls
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong),           # offsets
        ]


def available() -> bool:
    return _load() is not None


def _buf_arg(buf):
    """(arg, length, keepalive) presenting any C-contiguous bytes-like
    object to a ``c_char_p`` parameter WITHOUT copying. ``bytes`` goes
    straight through ctypes; memoryview / mmap / bytearray / uint8
    ndarray views travel as a raw pointer into the existing buffer
    (``c_char_p`` rejects non-bytes and ``from_buffer`` fails on
    read-only mmaps, so the pointer is taken through a zero-copy
    ``np.frombuffer`` view). The keepalive object must stay referenced
    for the duration of the native call — callers hold it in a local.

    This is what lets the mmap replay path (core/storage.py) hand
    chunk-file pages straight to the C walker: the bytes are untrusted
    and possibly crash-torn, which is exactly the load the
    untrusted-bytes bounds gate on the native side proves safe
    (analysis/native_gate.py, rule untrusted-bytes-bounds)."""
    if isinstance(buf, bytes):
        return buf, len(buf), None
    arr = np.frombuffer(buf, dtype=np.uint8)
    return arr.ctypes.data_as(ctypes.c_char_p), arr.size, arr


def count_records(buf) -> Optional[int]:
    lib = _load()
    if lib is None:
        return None
    p, blen, _keep = _buf_arg(buf)
    n = lib.fbtpu_count_records(p, blen)
    return None if n < 0 else int(n)


def scan_offsets(buf) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    p, blen, _keep = _buf_arg(buf)
    # worst case: 1-byte records
    cap = blen + 1
    offsets = np.empty(cap + 1, dtype=np.int64)
    n = lib.fbtpu_scan_offsets(
        p, blen,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), cap,
    )
    if n < 0:
        return None
    return offsets[: n + 1]


def compact(buf, offsets: np.ndarray,
            keep: np.ndarray) -> Optional[bytes]:
    """Order-preserving copy of the records with keep[i] True straight
    from the source buffer (the raw grep path's survivor re-emit)."""
    lib = _load()
    if lib is None:
        return None
    p, blen, _keep_ref = _buf_arg(buf)
    n = len(keep)
    out = np.empty(blen, dtype=np.uint8)
    keep_u8 = np.ascontiguousarray(keep, dtype=np.uint8)
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    w = lib.fbtpu_compact(
        p, blen,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        keep_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if w < 0:
        return None
    return out[:w].tobytes()


def _build_accel(trans: np.ndarray, class_map: np.ndarray):
    """Per-state escape-byte acceleration (the self-loop-skipping
    design documented at native/fbtpu_native.cpp: states that leave
    only on <=2 bytes get a memchr/SIMD skip instead of a table walk).

    accel[s] u32: bits 0-1 = 0 none / 1 one escape byte / 2 two /
    3 no escape bytes at all (state is fixed until EOL);
    bits 8-15 byte1; 16-23 byte2. Returns (accel u32[S], usable bool).

    Opt-in (FBTPU_ACCEL=1): on the bench corpus (short ~10-30 byte
    fields between delimiters) the scalar skip chain MEASURES SLOWER
    than the 16-lane interleaved k-composed walk — the skips save few
    table loads while forfeiting cross-record load-latency hiding
    (4.4M vs 8.1M lines/s). It wins on long self-loop runs (multi-KB
    lines, .*-tail patterns), so the engine stays available and
    differentially tested rather than default."""
    S = trans.shape[0]
    if not os.environ.get("FBTPU_ACCEL"):
        return np.zeros(1, dtype=np.uint32), False  # analysis skipped
    cm = class_map[:256].astype(np.int64)
    tb = trans[:, cm]  # [S, 256] next state per BYTE
    esc = tb != np.arange(S, dtype=tb.dtype)[:, None]
    n_esc = esc.sum(axis=1)
    accel = np.zeros(S, dtype=np.uint32)
    accel[n_esc == 0] = 3
    for s in np.nonzero(n_esc == 1)[0]:
        b = int(np.nonzero(esc[s])[0][0])
        accel[s] = 1 | (b << 8)
    for s in np.nonzero(n_esc == 2)[0]:
        b1, b2 = (int(x) for x in np.nonzero(esc[s])[0][:2])
        accel[s] = 2 | (b1 << 8) | (b2 << 16)
    skippy = int((accel != 0).sum())
    return accel, skippy * 20 >= S and skippy >= 2


class GrepTables:
    """Packed DFA tables for the one-pass native grep matcher — the
    host-side twin of ops.grep.GrepProgram (same tables, k=1). Verdicts
    are bit-exact with the device kernel and the Python regex engine."""

    __slots__ = ("n_rules", "keys_cat", "key_offs", "key_of_rule",
                 "trans_cat", "troffs", "cmaps", "starts", "ncls",
                 "cmap2_cat", "cm2offs", "btrans_cat", "btroffs",
                 "accel_cat", "aoffs", "decisions")

    def __init__(self, rules):
        """rules: iterable of (field_key: bytes, dfa) pairs."""
        keys: list = []
        key_idx = {}
        key_of_rule = []
        trans_parts = []
        troffs = [0]
        cmaps = []
        cmap2_parts = []
        cm2offs = []
        cm2_len = 0
        starts = []
        ncls = []
        btrans_parts = []
        btroffs = []
        btrans_len = 0
        accel_parts = []
        aoffs = []
        accel_len = 0
        # fbtpu-shrink audit: per-rule (S, C, chosen native k) plus the
        # compile pass's before-shapes — the native twin of
        # ops.grep.GrepProgram.decision(), recorded so bench/debug can
        # see that the reduced tables actually reached the C walker
        decisions: list = []
        for key, dfa in rules:
            if key not in key_idx:
                key_idx[key] = len(keys)
                keys.append(key)
            key_of_rule.append(key_idx[key])
            from ..regex.dfa import compose_supersteps

            t = np.ascontiguousarray(dfa.trans, dtype=np.int32)
            S, C = t.shape
            # pre-compose to k-byte super-steps (cuts the dependent-load
            # chain k-fold) while [S, C^k] stays cache-friendly; the
            # packed class count encodes C + 1000*(k-1) for the C side
            if S >= 32768:  # int16 table states (never in practice)
                raise ValueError(f"DFA too large for native tables ({S})")
            budget = int(os.environ.get("FBTPU_KTABLE_BUDGET",
                                        str(2 * 1024 * 1024)))
            # EVEN k preferred: the prepass then classifies via the
            # byte-PAIR table (one load per two bytes). k=4 may exceed
            # the plain budget — the walk only touches the visited
            # states' rows, so a larger-but-cold table still wins.
            k4_budget = int(os.environ.get("FBTPU_K4_BUDGET",
                                           str(12 * 1024 * 1024)))
            if C ** 4 <= 65535 and S * (C ** 4) * 2 <= k4_budget:
                k = 4
            else:
                k = 1
                # C^k <= 65535: super-symbols travel as uint16 through
                # the prepass scratch (dfa_prepass_block)
                while (k < 4 and S * (C ** (k + 1)) * 2 <= budget
                       and C ** (k + 1) <= 65535):
                    k += 1
                if k >= 2 and k % 2 == 1:
                    k -= 1  # even k unlocks the pair-table prepass
            st = getattr(dfa, "shrink", None)
            decisions.append({
                "s": S, "c": C, "k": k,
                "s_raw": st.s_raw if st is not None else None,
                "c_raw": st.c_raw if st is not None else None,
                "minimized": bool(st.minimized) if st is not None
                else False,
                "approx_of": st.approx_of if st is not None else None,
                "table_bytes": int(S * (C ** k) * 2),
            })
            tk = compose_supersteps(t, k)
            trans_parts.append(np.ascontiguousarray(
                tk, dtype=np.int16).reshape(-1))
            troffs.append(troffs[-1] + tk.size)
            ncls.append(C + 1000 * (k - 1))
            cmaps.append(np.ascontiguousarray(
                dfa.class_map, dtype=np.int32))
            if k % 2 == 0:
                # cmap2[b0 + (b1<<8)] = class(b0)*C + class(b1)
                cm = dfa.class_map[:256].astype(np.uint32)
                w = np.arange(65536, dtype=np.uint32)
                pair = cm[w & 255] * C + cm[w >> 8]
                cmap2_parts.append(pair.astype(np.uint16))
                cm2offs.append(cm2_len)
                cm2_len += 65536
            else:
                cm2offs.append(-1)
            starts.append(dfa.start)
            # escape-byte accel: byte-level table + skip words for
            # DFAs whose states mostly self-loop (log-matching shapes)
            accel, usable = _build_accel(t, dfa.class_map)
            if usable:
                aoffs.append(accel_len)
                accel_parts.append(accel)
                accel_len += accel.size
                btrans_parts.append(np.ascontiguousarray(
                    t, dtype=np.int16).reshape(-1))
                btroffs.append(btrans_len)
                btrans_len += t.size
            else:
                aoffs.append(-1)
                btroffs.append(0)
        self.n_rules = len(key_of_rule)
        self.decisions = decisions
        self.keys_cat = b"".join(keys)
        offs = [0]
        for k in keys:
            offs.append(offs[-1] + len(k))
        self.key_offs = np.asarray(offs, dtype=np.int64)
        self.key_of_rule = np.asarray(key_of_rule, dtype=np.int32)
        self.trans_cat = np.concatenate(trans_parts)
        self.troffs = np.asarray(troffs[:-1], dtype=np.int64)
        self.cmaps = np.concatenate(cmaps)
        self.cmap2_cat = (np.concatenate(cmap2_parts) if cmap2_parts
                          else np.zeros(1, dtype=np.uint16))
        self.cm2offs = np.asarray(cm2offs, dtype=np.int64)
        # DFA start-STATE ids (bounded by the state count, < 2^15), not
        # byte offsets; the C ABI takes int32 here
        # fbtpu-lint: allow(dtype-narrowing)
        self.starts = np.asarray(starts, dtype=np.int32)
        self.ncls = np.asarray(ncls, dtype=np.int32)
        self.btrans_cat = (np.concatenate(btrans_parts) if btrans_parts
                           else np.zeros(1, dtype=np.int16))
        self.btroffs = np.asarray(btroffs, dtype=np.int64)
        self.accel_cat = (np.concatenate(accel_parts) if accel_parts
                          else np.zeros(1, dtype=np.uint32))
        self.aoffs = np.asarray(aoffs, dtype=np.int64)

    def thread_copy(self) -> "GrepTables":
        """A private copy of the packed arrays for one worker thread.

        The tables are read-only so sharing is CORRECT — but with
        several inputs ingesting concurrently every walker hammers the
        same physical arrays, and on small hosts the shared hot lines
        serialize in the cache hierarchy (BENCH_r05: inputs4 at 0.92×
        of inputs1). Each ingest thread matching through its own copy
        keeps the walk NUMA/cache-local; the copy is a few hundred KB,
        made once per (thread, filter)."""
        new = self.__class__.__new__(self.__class__)
        slots = []
        for klass in type(self).__mro__:
            slots.extend(getattr(klass, "__slots__", ()))
        for slot in slots:
            v = getattr(self, slot)
            setattr(new, slot,
                    v.copy() if isinstance(v, np.ndarray) else v)
        return new


def grep_match(buf, tables: GrepTables, n_hint: Optional[int] = None
               ) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """One-pass field-extract + DFA match over chunk bytes. Returns
    (mask[R, n] bool, offsets[n+1] i64, n) or None (native unavailable /
    malformed buffer)."""
    lib = _load()
    if lib is None or getattr(lib, "fbtpu_grep_match_v2", None) is None:
        return None
    est = n_hint if n_hint is not None else count_records(buf)
    if est is None:
        return None
    p, blen, _keep = _buf_arg(buf)
    R = tables.n_rules
    cap = max(est, 1)  # match/offsets sized to the capacity granted to C
    match = np.empty((R, cap), dtype=np.uint8)
    offsets = np.empty(cap + 1, dtype=np.int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_longlong)
    n = getattr(lib, "fbtpu_grep_match_v2")(
        p, blen,
        tables.keys_cat,
        tables.key_offs.ctypes.data_as(i64p),
        len(tables.key_offs) - 1,
        tables.key_of_rule.ctypes.data_as(i32p), R,
        tables.trans_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        tables.troffs.ctypes.data_as(i64p),
        tables.cmaps.ctypes.data_as(i32p),
        tables.starts.ctypes.data_as(i32p),
        tables.ncls.ctypes.data_as(i32p),
        match.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cap,
        offsets.ctypes.data_as(i64p),
    )
    if n < 0:
        return None
    # u8 0/1 → bool is a reinterpret, not a copy (match is freshly
    # allocated per call, so the view escapes safely)
    return match[:, :n].view(bool), offsets[: n + 1], int(n)


class GrepFilterTables(GrepTables):
    """GrepTables (k-super-stepped int16 transition tables) plus the
    verdict inputs for the fused one-pass filter (fbtpu_grep_filter):
    per-rule exclude flags and the logical_op mode. The matcher splits
    each record into a branchless super-symbol prepass and a
    two-loads-per-step lockstep walk (dfa_prepass_block)."""

    __slots__ = ("excl", "op_mode")

    def __init__(self, rules, op: str = "legacy"):
        """rules: iterable of (field_key: bytes, dfa, is_exclude) trios."""
        rules = list(rules)
        super().__init__([(key, dfa) for key, dfa, _ in rules])
        self.excl = np.asarray(
            [1 if is_exclude else 0 for _, _, is_exclude in rules],
            dtype=np.uint8)
        self.op_mode = {"LEGACY": 0, "AND": 1, "OR": 2}.get(op.upper(), 0)


_tls = threading.local()


def _arena(size: int) -> np.ndarray:
    """Reusable per-thread output buffer (the fused filter writes the
    compacted chunk here; the engine copies it into the chunk store
    before the next call on this thread can overwrite it)."""
    buf = getattr(_tls, "out", None)
    if buf is None or buf.size < size:
        buf = np.empty(max(size, 1 << 20), dtype=np.uint8)
        _tls.out = buf
    return buf


def grep_filter(buf, tables: "GrepFilterTables",
                n_hint: Optional[int] = None):
    """One-pass extract + accel-DFA + verdict + compaction.

    Returns (n_records, n_kept, out) where out is the original ``buf``
    when nothing was dropped, b"" when everything was, else a memoryview
    of this thread's arena holding the surviving records byte-identically
    (caller must consume it before its next grep_filter call on this
    thread). None = native unavailable / malformed buffer."""
    lib = _load()
    if lib is None or getattr(lib, "fbtpu_grep_filter", None) is None:
        return None
    # non-bytes buffers (bytearray / memoryview / mmap view) travel as
    # a raw pointer — the walker reads them in place (the memscope
    # host-redundant-copy fix: this path used to materialize a bytes()
    # copy of every bytearray chunk before the call)
    p, blen, _keep = _buf_arg(buf)
    # no counting pre-pass: the walk discovers the record count, so an
    # unknown count just means sizing scratch to the 3-bytes-per-record
    # floor (array [ts, body] is at least 3 bytes)
    cap = max(n_hint if n_hint is not None else blen // 3 + 1, 1)
    out = _arena(blen)
    if _keep is not None:
        # a chained filter may hand back THIS thread's arena view from
        # a previous call: the walker writes survivors into the arena
        # while reading, so an aliased input must be materialized (the
        # one case the zero-copy pointer path cannot serve)
        p_addr = ctypes.cast(p, ctypes.c_void_p).value or 0
        o_addr = out.ctypes.data
        if o_addr <= p_addr < o_addr + out.size:
            buf = bytes(buf)
            p, blen, _keep = _buf_arg(buf)
    info = np.zeros(3, dtype=np.int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_longlong)
    w = lib.fbtpu_grep_filter(
        p, blen,
        tables.keys_cat,
        tables.key_offs.ctypes.data_as(i64p),
        len(tables.key_offs) - 1,
        tables.key_of_rule.ctypes.data_as(i32p), tables.n_rules,
        tables.trans_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        tables.troffs.ctypes.data_as(i64p),
        tables.cmaps.ctypes.data_as(i32p),
        tables.starts.ctypes.data_as(i32p),
        tables.ncls.ctypes.data_as(i32p),
        tables.cmap2_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        tables.cm2offs.ctypes.data_as(i64p),
        tables.btrans_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        tables.btroffs.ctypes.data_as(i64p),
        tables.accel_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        tables.aoffs.ctypes.data_as(i64p),
        tables.excl.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        tables.op_mode,
        cap,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        info.ctypes.data_as(i64p),
    )
    if w < 0:
        return None
    n, n_keep, wrote = int(info[0]), int(info[1]), int(info[2])
    if not wrote:
        return n, n_keep, buf
    if n_keep == 0:
        return n, 0, b""
    # the arena view IS the documented contract (docstring: consume
    # before this thread's next grep_filter call); the engine copies it
    # into the chunk store
    # fbtpu-lint: allow(host-mutable-view-escape)
    return n, n_keep, memoryview(out)[:w]


_stage_threads_cached: Optional[int] = None


def _stage_threads() -> int:
    global _stage_threads_cached
    if _stage_threads_cached is None:
        try:
            _stage_threads_cached = int(
                os.environ.get("FBTPU_STAGE_THREADS", "0")
            ) or (os.cpu_count() or 1)
        except ValueError:
            _stage_threads_cached = os.cpu_count() or 1
    return _stage_threads_cached


def stage_threads() -> int:
    """Requested stager fan-out (``FBTPU_STAGE_THREADS``, default = all
    cores). The native pool may clamp this to the hardware — see
    :func:`stage_threads_effective`."""
    return _stage_threads()


def stage_threads_effective(requested: Optional[int] = None) -> Optional[int]:
    """What the native pool will ACTUALLY fan a stage call out to after
    its hardware/16-way caps (``fbtpu_stage_effective_threads``) — the
    truth the bench RESULT records so a multi-core lane's scaling
    number can be read against the real slice count. None = native
    unavailable or an older .so without the probe."""
    lib = _load()
    fn = getattr(lib, "fbtpu_stage_effective_threads", None) \
        if lib is not None else None
    if fn is None:
        return None
    return int(fn(requested if requested is not None else _stage_threads()))


def stage_field_into(
    buf, key: bytes, out_batch: np.ndarray,
    out_lengths: np.ndarray, n_hint: Optional[int] = None,
    threads: Optional[int] = None,
    offsets_out: Optional[np.ndarray] = None,
) -> Optional[int]:
    """Stage one top-level string field DIRECTLY into caller-provided
    arrays — the per-device staging path of the mesh plane: the caller
    hands one rule-row slice of its ``[R, Bp, L]`` segment matrix
    (``out_batch`` u8 ``[B, L]`` C-contiguous, ``out_lengths`` i32
    ``[B]``) and the extraction fans out across the native worker pool
    (``FBTPU_STAGE_THREADS`` / ``threads``), each slice of records
    walking lock-free into its own row range. No arena, no copy-out —
    the staged bytes land where the device transfer reads them.

    Writes rows ``[0, n)`` only (bytes past each row's length are NOT
    zeroed; both DFA kernels mask by length); rows past ``n`` are left
    untouched, so pre-fill ``out_lengths`` with -1 for pad rows.
    ``offsets_out`` (i64, ≥ est+1 entries, contiguous) receives the
    record boundary table the walk discovers anyway — callers that
    need it (compaction, overflow decode) must NOT re-scan the buffer.
    Returns the record count, or None (native unavailable / malformed
    buffer / capacity exceeded / non-contiguous or mistyped target)."""
    lib = _load()
    if lib is None:
        return None
    est = n_hint if n_hint is not None else count_records(buf)
    if est is None:
        return None
    B, L = out_batch.shape
    if est > B or out_batch.dtype != np.uint8 \
            or not out_batch.flags["C_CONTIGUOUS"] \
            or out_lengths.dtype != np.int32 or out_lengths.shape[0] < B \
            or not out_lengths.flags["C_CONTIGUOUS"]:
        return None
    if offsets_out is not None:
        if offsets_out.dtype != np.int64 \
                or offsets_out.shape[0] < est + 1 \
                or not offsets_out.flags["C_CONTIGUOUS"]:
            return None
        offsets = offsets_out
    else:
        offsets = np.empty(est + 1, dtype=np.int64)
    p_b = out_batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    p_l = out_lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    p_o = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
    # mmap replay staging: buf may be a read-only view of chunk-file
    # pages — the extraction walks them in place, no host copy between
    # the page cache and the caller's transfer matrix
    p, blen, _keep = _buf_arg(buf)
    mt_fn = getattr(lib, "fbtpu_stage_field_mt", None)
    if mt_fn is not None:
        n = mt_fn(p, blen, key, len(key), p_b, p_l, est, L, p_o,
                  threads if threads is not None else _stage_threads())
    else:
        n = lib.fbtpu_stage_field(p, blen, key, len(key), p_b, p_l,
                                  est, L, p_o)
    return None if n < 0 else int(n)


def stage_field(
    buf, key: bytes, max_len: int, pad_to: Optional[int] = None,
    n_hint: Optional[int] = None, threads: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, int]]:
    """Fill the staging matrix for one top-level string field straight
    from chunk bytes: (batch[B, L] u8, lengths[B] i32, offsets[n+1] i64,
    n_records). ``pad_to`` rounds B for jit shape stability; ``n_hint``
    (a caller-known record count) skips the counting pre-pass.

    The returned arrays are views of a per-thread arena reused across
    calls (the VERDICT-r4 staging-ceiling fix: a fresh zeroed [B, L]
    matrix per chunk was pure memset bandwidth) — consume or copy them
    before this thread's next stage_field call. Bytes past lengths[i]
    in a row are NOT zeroed; consumers mask by length (both DFA kernels
    do). Extraction fans out across the native worker pool
    (fbtpu_stage_field_mt) when the chunk is large enough."""
    lib = _load()
    if lib is None:
        return None
    est = n_hint if n_hint is not None else count_records(buf)
    if est is None:
        return None
    B = pad_to if pad_to and pad_to >= est else est
    arena = getattr(_tls, "stage", None)
    if (arena is None or arena[0].shape[0] < B
            or arena[0].shape[1] != max_len):
        batch = np.zeros((max(B, 1024), max_len), dtype=np.uint8)
        lengths = np.empty((batch.shape[0],), dtype=np.int32)
        offsets = np.empty(batch.shape[0] + 1, dtype=np.int64)
        # ctypes pointers cached alongside: data_as() builds fresh
        # pointer objects (~µs each), pure overhead at bench chunk rates
        _tls.stage = arena = (
            batch, lengths, offsets,
            batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        )
    batch, lengths, offsets, p_b, p_l, p_o = arena
    p, blen, _keep = _buf_arg(buf)
    mt_fn = getattr(lib, "fbtpu_stage_field_mt", None)
    if mt_fn is not None:
        n = mt_fn(p, blen, key, len(key), p_b, p_l, est, max_len,
                  p_o, threads if threads is not None else _stage_threads())
    else:
        n = lib.fbtpu_stage_field(p, blen, key, len(key), p_b, p_l,
                                  est, max_len, p_o)
    if n < 0:
        return None
    n = int(n)
    if n < B:
        lengths[n:B] = -1  # pad rows (jit shape stability) stay "missing"
    return batch[:B], lengths[:B], offsets[: n + 1], n


def stage_field_f64(
    buf: bytes, key: bytes, n_hint: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """Stage one top-level NUMERIC field straight from chunk bytes:
    (values[B] f64, kinds[B] u8, n_records). kinds: 0 = missing/
    non-numeric (strings are non-numeric — the exact aggregate rule),
    1 = msgpack integer, 2 = msgpack float. Freshly allocated arrays
    (no arena: flux window state holds onto per-chunk columns)."""
    lib = _load()
    if lib is None or getattr(lib, "fbtpu_stage_field_f64", None) is None:
        return None
    est = n_hint if n_hint is not None else count_records(buf)
    if est is None:
        return None
    values = np.zeros((max(est, 1),), dtype=np.float64)
    kinds = np.zeros((max(est, 1),), dtype=np.uint8)
    n = lib.fbtpu_stage_field_f64(
        buf, len(buf), key, len(key),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        est, None,
    )
    if n < 0:
        return None
    n = int(n)
    return values[:n], kinds[:n], n


def has_flux_stagers() -> bool:
    """True when the loaded .so exports the flux entry points (a stale
    prebuilt library may predate them — callers should then skip the
    batched flux path once instead of probing per chunk)."""
    lib = _load()
    return lib is not None and \
        getattr(lib, "fbtpu_stage_field_f64", None) is not None


def hll_update(registers: np.ndarray, batch: np.ndarray,
               lengths: np.ndarray, p: int) -> bool:
    """C twin of the device HLL register update over a staged [B, L]
    batch — bit-identical to HyperLogLog.add_cpu row by row. Mutates
    ``registers`` (int32 [2^p]) in place; False = native unavailable."""
    lib = _load()
    if lib is None or getattr(lib, "fbtpu_hll_update", None) is None:
        return False
    batch = np.ascontiguousarray(batch, dtype=np.uint8)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    B, L = batch.shape
    lib.fbtpu_hll_update(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        B, L, int(p),
        registers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return True


def cms_update(table: np.ndarray, batch: np.ndarray,
               lengths: np.ndarray) -> bool:
    """C twin of the device count-min scatter-add (weight 1 per valid
    row). Mutates ``table`` ([d, w] int32/int64) in place."""
    lib = _load()
    if lib is None or getattr(lib, "fbtpu_cms_update", None) is None:
        return False
    if table.dtype == np.int32:
        elem = 4
    elif table.dtype == np.int64:
        elem = 8
    else:
        return False
    batch = np.ascontiguousarray(batch, dtype=np.uint8)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    B, L = batch.shape
    depth, width = table.shape
    rc = lib.fbtpu_cms_update(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        B, L, int(depth), int(width),
        table.ctypes.data_as(ctypes.c_void_p), elem,
    )
    return rc == 0
