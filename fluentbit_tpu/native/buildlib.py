"""Shared on-demand native build machinery (hash-cached compile).

Both native loaders — the ctypes data plane (fluentbit_tpu.native) and
the CPython codec extension (codec._native_codec) — need the same
scheme: compile the source once, cache the artifact with a source-hash
sidecar, rebuild on hash mismatch, and TRUST two prebuilt shapes:

- artifact present but SOURCE missing (binary-only deployment): load it;
- artifact present with no hash sidecar: assume it matches the current
  source and record that assumption so one later source edit triggers
  exactly one rebuild.

A KNOWN-stale artifact (sidecar hash differs from the source) must
never load — its ABI may not match the callers.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
from typing import List, Optional

log = logging.getLogger("flb.native")


def src_hash(src: str) -> Optional[str]:
    try:
        with open(src, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def _compile(cmd: List[str], so: str, digest: Optional[str]) -> bool:
    try:
        os.makedirs(os.path.dirname(so), exist_ok=True)
    except OSError:
        return False
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable: %s", e)
        return False
    if proc.returncode != 0:
        log.warning("native build failed: %s", proc.stderr[-2000:])
        return False
    if digest:
        try:
            with open(so + ".hash", "w") as f:
                f.write(digest)
        except OSError:
            pass  # staleness check degrades; the artifact is fine
    return True


def ensure_built(src: str, so: str, cmd: List[str]) -> bool:
    """→ True when ``so`` exists and is safe to load."""
    have_so = os.path.exists(so)
    if not os.path.exists(src):
        return have_so  # binary-only deployment: trust the artifact
    built_hash = None
    try:
        with open(so + ".hash") as f:
            built_hash = f.read().strip()
    except OSError:
        pass
    digest = src_hash(src)
    if have_so and built_hash is None and digest is not None:
        # prebuilt artifact with no sidecar: adopt the current source's
        # hash (works even when the write fails — read-only checkout)
        built_hash = digest
        try:
            with open(so + ".hash", "w") as f:
                f.write(digest)
        except OSError:
            pass
    if not have_so or (digest is not None and built_hash != digest):
        return _compile(cmd, so, digest)
    return True
