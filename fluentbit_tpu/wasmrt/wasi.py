"""WASI preview1 host surface for the wasmrt interpreter.

Reference: the WAMR runtime fluent-bit vendors provides WASI to
`in_exec_wasi` guests (lib/wasm-micro-runtime-WAMR-2.4.1, bridged by
src/wasm/flb_wasm.c — flb_wasm_instantiate wires stdin/stdout/stderr
fds and the WASI argv). Here the same contract is implemented directly
against `Module`'s host-import hook: a `WasiEnv` captures guest stdout
and stderr into buffers, serves argv/environ/clock/random, and turns
`proc_exit` into a catchable `WasiExit`.

Implemented: args/environ get+sizes, clock_time_get/clock_res_get,
fd_write/fd_read/fd_close/fd_seek/fd_fdstat_get/fd_fdstat_set_flags,
fd_prestat_get (no preopens → EBADF, like a WAMR instance given no
--dir mappings), proc_exit, random_get, sched_yield. Everything else
in the preview1 witx surface answers ENOSYS so toolchain-generated
libc stubs fail loudly instead of corrupting memory.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Dict, List, Optional, Tuple

from . import Trap

ERRNO_SUCCESS = 0
ERRNO_BADF = 8
ERRNO_INVAL = 28
ERRNO_IO = 29
ERRNO_NOSYS = 52
ERRNO_SPIPE = 70

_MODULES = ("wasi_snapshot_preview1", "wasi_unstable")

# the rest of the preview1 surface — registered as loud ENOSYS stubs
_NOSYS = [
    "fd_advise", "fd_allocate", "fd_datasync", "fd_filestat_get",
    "fd_filestat_set_size", "fd_filestat_set_times", "fd_pread",
    "fd_pwrite", "fd_readdir", "fd_renumber", "fd_sync", "fd_tell",
    "path_create_directory", "path_filestat_get",
    "path_filestat_set_times", "path_link", "path_open",
    "path_readlink", "path_remove_directory", "path_rename",
    "path_symlink", "path_unlink_file", "poll_oneoff", "proc_raise",
    "sock_accept", "sock_recv", "sock_send", "sock_shutdown",
    "fd_prestat_dir_name",
]


class WasiExit(Exception):
    """proc_exit — carries the guest's exit code."""

    def __init__(self, code: int):
        super().__init__(f"proc_exit({code})")
        self.code = code


def _check(mod, ptr: int, n: int) -> None:
    """Guest pointers must stay inside linear memory — the host
    surface enforces the same bound the interpreter's load/store
    opcodes do (a bytearray slice-assign would silently append)."""
    if ptr < 0 or n < 0 or ptr + n > len(mod.memory):
        raise Trap(f"WASI pointer out of bounds ({ptr}+{n})")


def _w32(mod, ptr: int, v: int) -> None:
    _check(mod, ptr, 4)
    mod.memory[ptr:ptr + 4] = struct.pack("<I", v & 0xFFFFFFFF)


def _w64(mod, ptr: int, v: int) -> None:
    _check(mod, ptr, 8)
    mod.memory[ptr:ptr + 8] = struct.pack("<Q", v & (2 ** 64 - 1))


class WasiEnv:
    """Per-instance WASI state: argv/env, std streams, exit code."""

    def __init__(self, args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 stdin: bytes = b""):
        self.args = list(args or [])
        self.env = dict(env or {})
        self.stdin = stdin
        self._stdin_off = 0
        self.stdout = bytearray()
        self.stderr = bytearray()
        self.exit_code: Optional[int] = None

    # -- import table --------------------------------------------------

    def imports(self) -> Dict[Tuple[str, str], object]:
        table: Dict[Tuple[str, str], object] = {}
        fns = {
            "args_sizes_get": self._args_sizes_get,
            "args_get": self._args_get,
            "environ_sizes_get": self._environ_sizes_get,
            "environ_get": self._environ_get,
            "clock_res_get": self._clock_res_get,
            "clock_time_get": self._clock_time_get,
            "fd_write": self._fd_write,
            "fd_read": self._fd_read,
            "fd_close": self._fd_close,
            "fd_seek": self._fd_seek,
            "fd_fdstat_get": self._fd_fdstat_get,
            "fd_fdstat_set_flags": self._fd_fdstat_set_flags,
            "fd_prestat_get": self._fd_prestat_get,
            "proc_exit": self._proc_exit,
            "random_get": self._random_get,
            "sched_yield": self._sched_yield,
        }
        def nosys(mod, *a):
            return [ERRNO_NOSYS]

        for name in _NOSYS:
            fns[name] = nosys
        for m in _MODULES:
            for f, fn in fns.items():
                table[(m, f)] = fn
        return table

    # -- args / environ ------------------------------------------------

    def _blobs(self, items: List[str]) -> List[bytes]:
        return [s.encode("utf-8") + b"\0" for s in items]

    def _args_sizes_get(self, mod, argc_ptr, size_ptr):
        blobs = self._blobs(self.args)
        _w32(mod, argc_ptr, len(blobs))
        _w32(mod, size_ptr, sum(len(b) for b in blobs))
        return [ERRNO_SUCCESS]

    def _args_get(self, mod, argv_ptr, buf_ptr):
        for b in self._blobs(self.args):
            _w32(mod, argv_ptr, buf_ptr)
            _check(mod, buf_ptr, len(b))
            mod.memory[buf_ptr:buf_ptr + len(b)] = b
            argv_ptr += 4
            buf_ptr += len(b)
        return [ERRNO_SUCCESS]

    def _environ_sizes_get(self, mod, envc_ptr, size_ptr):
        blobs = self._blobs([f"{k}={v}" for k, v in self.env.items()])
        _w32(mod, envc_ptr, len(blobs))
        _w32(mod, size_ptr, sum(len(b) for b in blobs))
        return [ERRNO_SUCCESS]

    def _environ_get(self, mod, env_ptr, buf_ptr):
        for b in self._blobs([f"{k}={v}" for k, v in self.env.items()]):
            _w32(mod, env_ptr, buf_ptr)
            _check(mod, buf_ptr, len(b))
            mod.memory[buf_ptr:buf_ptr + len(b)] = b
            env_ptr += 4
            buf_ptr += len(b)
        return [ERRNO_SUCCESS]

    # -- clocks / random -----------------------------------------------

    def _clock_res_get(self, mod, clock_id, res_ptr):
        _w64(mod, res_ptr, 1)
        return [ERRNO_SUCCESS]

    def _clock_time_get(self, mod, clock_id, _precision, time_ptr):
        if clock_id == 1:  # monotonic
            _w64(mod, time_ptr, time.monotonic_ns())
        else:  # realtime + process/thread cputime approximations
            _w64(mod, time_ptr, time.time_ns())
        return [ERRNO_SUCCESS]

    def _random_get(self, mod, buf_ptr, buf_len):
        _check(mod, buf_ptr, buf_len)
        data = os.urandom(buf_len)
        mod.memory[buf_ptr:buf_ptr + buf_len] = data
        return [ERRNO_SUCCESS]

    def _sched_yield(self, mod):
        return [ERRNO_SUCCESS]

    # -- fds -----------------------------------------------------------

    def _iovs(self, mod, iovs_ptr, iovs_len) -> List[Tuple[int, int]]:
        _check(mod, iovs_ptr, 8 * iovs_len)
        out = []
        for i in range(iovs_len):
            base = struct.unpack_from("<I", mod.memory,
                                      iovs_ptr + 8 * i)[0]
            ln = struct.unpack_from("<I", mod.memory,
                                    iovs_ptr + 8 * i + 4)[0]
            _check(mod, base, ln)
            out.append((base, ln))
        return out

    def _fd_write(self, mod, fd, iovs_ptr, iovs_len, nwritten_ptr):
        if fd not in (1, 2):
            return [ERRNO_BADF]
        sink = self.stdout if fd == 1 else self.stderr
        total = 0
        for base, ln in self._iovs(mod, iovs_ptr, iovs_len):
            sink += mod.memory[base:base + ln]
            total += ln
        _w32(mod, nwritten_ptr, total)
        return [ERRNO_SUCCESS]

    def _fd_read(self, mod, fd, iovs_ptr, iovs_len, nread_ptr):
        if fd != 0:
            return [ERRNO_BADF]
        total = 0
        for base, ln in self._iovs(mod, iovs_ptr, iovs_len):
            chunk = self.stdin[self._stdin_off:self._stdin_off + ln]
            mod.memory[base:base + len(chunk)] = chunk
            self._stdin_off += len(chunk)
            total += len(chunk)
            if len(chunk) < ln:
                break
        _w32(mod, nread_ptr, total)
        return [ERRNO_SUCCESS]

    def _fd_close(self, mod, fd):
        return [ERRNO_SUCCESS] if fd in (0, 1, 2) else [ERRNO_BADF]

    def _fd_seek(self, mod, fd, _offset, _whence, _newoffset_ptr):
        # std streams are pipes — not seekable
        return [ERRNO_SPIPE] if fd in (0, 1, 2) else [ERRNO_BADF]

    def _fd_fdstat_get(self, mod, fd, buf_ptr):
        if fd not in (0, 1, 2):
            return [ERRNO_BADF]
        _check(mod, buf_ptr, 24)
        # fdstat: u8 filetype(2=char device), u16 flags, u64 rights ×2
        mod.memory[buf_ptr:buf_ptr + 24] = struct.pack(
            "<BxHxxxxQQ", 2, 0, 2 ** 64 - 1, 2 ** 64 - 1)
        return [ERRNO_SUCCESS]

    def _fd_fdstat_set_flags(self, mod, fd, _flags):
        return [ERRNO_SUCCESS] if fd in (0, 1, 2) else [ERRNO_BADF]

    def _fd_prestat_get(self, mod, fd, _buf_ptr):
        # no preopened directories in this sandbox
        return [ERRNO_BADF]

    def _proc_exit(self, mod, code):
        self.exit_code = code
        raise WasiExit(code)
