"""From-scratch WebAssembly MVP interpreter for filter_wasm.

Reference embeds WAMR (lib/wasm-micro-runtime-WAMR-2.4.1 via
src/wasm/flb_wasm.c); this package decodes and interprets the wasm MVP
binary format directly: all sections, structured control flow
(block/loop/if with label-indexed branches), the full i32/i64 numeric
set plus the common f32/f64 ops, linear memory with all load/store
widths, globals, and call/call_indirect. The host surface mirrors
flb_wasm.c: ``dup_data`` copies host bytes into guest memory (the
wasm_runtime_module_dup_data role, flb_wasm.c:269-270) and
``call(name, args)`` invokes an exported function
(wasm_runtime_call_wasm).
"""

from __future__ import annotations

import math
import struct
from typing import Any, Dict, List, Optional, Tuple

PAGE = 65536

I32, I64, F32, F64 = 0x7F, 0x7E, 0x7D, 0x7C


class WasmError(ValueError):
    pass


class Trap(RuntimeError):
    """wasm trap (unreachable, div by zero, OOB access...)."""


# ------------------------------------------------------------- reader


class _Reader:
    __slots__ = ("b", "pos")

    def __init__(self, b: bytes, pos: int = 0):
        self.b = b
        self.pos = pos

    def u8(self) -> int:
        v = self.b[self.pos]
        self.pos += 1
        return v

    def u32(self) -> int:  # LEB128 unsigned
        result = 0
        shift = 0
        while True:
            byte = self.u8()
            result |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return result
            shift += 7
            if shift > 35:
                raise WasmError("u32 LEB overflow")

    def s32(self) -> int:
        return self._sleb(32)

    def s64(self) -> int:
        return self._sleb(64)

    def _sleb(self, bits: int) -> int:
        result = 0
        shift = 0
        while True:
            byte = self.u8()
            result |= (byte & 0x7F) << shift
            shift += 7
            if not byte & 0x80:
                if shift < bits and byte & 0x40:
                    result |= -(1 << shift)
                return result
            if shift > bits + 7:
                raise WasmError("sleb overflow")

    def f32(self) -> float:
        v = struct.unpack_from("<f", self.b, self.pos)[0]
        self.pos += 4
        return v

    def f64(self) -> float:
        v = struct.unpack_from("<d", self.b, self.pos)[0]
        self.pos += 8
        return v

    def bytes_(self, n: int) -> bytes:
        v = self.b[self.pos:self.pos + n]
        if len(v) != n:
            raise WasmError("truncated")
        self.pos += n
        return v

    def name(self) -> str:
        return self.bytes_(self.u32()).decode("utf-8")

    def eof(self) -> bool:
        return self.pos >= len(self.b)


# ----------------------------------------------------------- decoding
# Function bodies decode into a nested structured form:
#   instr = (opcode, *immediates)  |  block structures:
#   (0x02, blocktype, body)            block
#   (0x03, blocktype, body)            loop
#   (0x04, blocktype, then, else)      if


def _decode_expr(r: _Reader, terminators=(0x0B,)) -> Tuple[list, int]:
    body: list = []
    while True:
        op = r.u8()
        if op in terminators:
            return body, op
        if op in (0x02, 0x03):  # block / loop
            bt = r.s32()
            inner, _ = _decode_expr(r)
            body.append((op, bt, inner))
        elif op == 0x04:  # if
            bt = r.s32()
            then, term = _decode_expr(r, (0x0B, 0x05))
            els: list = []
            if term == 0x05:
                els, _ = _decode_expr(r)
            body.append((op, bt, then, els))
        elif op in (0x0C, 0x0D):  # br / br_if
            body.append((op, r.u32()))
        elif op == 0x0E:  # br_table
            n = r.u32()
            targets = [r.u32() for _ in range(n)]
            default = r.u32()
            body.append((op, targets, default))
        elif op == 0x10:  # call
            body.append((op, r.u32()))
        elif op == 0x11:  # call_indirect
            body.append((op, r.u32(), r.u32()))
        elif op in (0x20, 0x21, 0x22, 0x23, 0x24):  # local/global access
            body.append((op, r.u32()))
        elif 0x28 <= op <= 0x3E:  # memory load/store: align + offset
            r.u32()
            body.append((op, r.u32()))
        elif op in (0x3F, 0x40):  # memory.size / grow
            r.u8()
            body.append((op,))
        elif op == 0x41:
            body.append((op, r.s32() & 0xFFFFFFFF))
        elif op == 0x42:
            body.append((op, r.s64() & 0xFFFFFFFFFFFFFFFF))
        elif op == 0x43:
            body.append((op, r.f32()))
        elif op == 0x44:
            body.append((op, r.f64()))
        elif op == 0xFC:  # misc prefix: saturating trunc / bulk memory
            sub = r.u32()
            if sub <= 7:  # iNN.trunc_sat_fNN_{s,u}
                body.append((0xFC, sub))
            elif sub == 10:  # memory.copy
                r.u8()
                r.u8()
                body.append((0xFC, sub))
            elif sub == 11:  # memory.fill
                r.u8()
                body.append((0xFC, sub))
            else:
                raise WasmError(f"unsupported 0xFC sub-opcode {sub}")
        elif op in (0xFB, 0xFD):
            raise WasmError(
                f"unsupported opcode prefix 0x{op:02x} (GC/SIMD)")
        else:
            body.append((op,))


class _Func:
    __slots__ = ("type_idx", "params", "results", "locals", "body",
                 "name")

    def __init__(self, type_idx, params, results, locals_, body):
        self.type_idx = type_idx
        self.params = params
        self.results = results
        self.locals = locals_
        self.body = body
        self.name = ""


class Module:
    """One instantiated module: memory, globals, exported functions."""

    def __init__(self, binary: bytes, max_memory_bytes: int = 0,
                 max_call_depth: int = 256, host_imports=None):
        """max_memory_bytes caps linear memory growth (memory.grow AND
        the dup_data heap — the wasm_heap_size role); max_call_depth is
        the wasm_stack_size analogue. host_imports maps
        ``(module, field)`` to a host callable ``fn(mod, *args)``
        returning a result list (the WASI/native-symbol surface —
        WAMR's wasm_runtime_register_natives role); without it, any
        import is rejected (filter modules stay self-contained)."""
        self.max_call_depth = max(16, int(max_call_depth))
        self.imported: List[tuple] = []  # (callable, type_idx)
        r = _Reader(binary)
        if r.bytes_(4) != b"\0asm":
            raise WasmError("bad magic")
        if struct.unpack("<I", r.bytes_(4))[0] != 1:
            raise WasmError("unsupported wasm version")
        self.types: List[Tuple[list, list]] = []
        self.funcs: List[_Func] = []
        self.exports: Dict[str, Tuple[str, int]] = {}
        self.memory = bytearray()
        self.mem_max_pages = 1 << 16
        if max_memory_bytes:
            self.mem_max_pages = max(1, max_memory_bytes // PAGE)
        self.globals: List[list] = []  # [type, mutable, value]
        self.table: List[Optional[int]] = []
        self.start: Optional[int] = None
        func_types: List[int] = []
        code_bodies: List[bytes] = []
        data_segs: List[Tuple[int, bytes]] = []
        elem_segs: List[Tuple[int, List[int]]] = []
        while not r.eof():
            sec_id = r.u8()
            size = r.u32()
            sec = _Reader(r.bytes_(size))
            if sec_id == 1:  # types
                for _ in range(sec.u32()):
                    if sec.u8() != 0x60:
                        raise WasmError("bad functype")
                    params = [sec.u8() for _ in range(sec.u32())]
                    results = [sec.u8() for _ in range(sec.u32())]
                    self.types.append((params, results))
            elif sec_id == 2:  # imports
                for _ in range(sec.u32()):
                    mod = sec.name()
                    field = sec.name()
                    kind = sec.u8()
                    if kind != 0 or host_imports is None:
                        raise WasmError(
                            f"imports unsupported ({mod}.{field} kind "
                            f"{kind}) — filter modules must be "
                            "self-contained (no WASI)")
                    fn = host_imports.get((mod, field))
                    if fn is None:
                        raise WasmError(
                            f"unresolved import {mod}.{field}")
                    self.imported.append((fn, sec.u32()))
            elif sec_id == 3:  # function decls
                func_types = [sec.u32() for _ in range(sec.u32())]
            elif sec_id == 4:  # table
                for _ in range(sec.u32()):
                    if sec.u8() != 0x70:
                        raise WasmError("bad table elemtype")
                    flags = sec.u8()
                    n = sec.u32()
                    if flags & 1:
                        sec.u32()
                    self.table = [None] * n
            elif sec_id == 5:  # memory
                for _ in range(sec.u32()):
                    flags = sec.u8()
                    n_min = sec.u32()
                    if flags & 1:
                        # host cap wins over the declared maximum
                        self.mem_max_pages = min(self.mem_max_pages,
                                                 sec.u32())
                    if n_min > self.mem_max_pages:
                        # the limit must bind at instantiation too — a
                        # declared 4GiB minimum is the exact exhaustion
                        # max_memory_bytes exists to stop
                        raise WasmError(
                            f"memory minimum {n_min} pages exceeds the "
                            f"limit ({self.mem_max_pages} pages)")
                    self.memory = bytearray(n_min * PAGE)
            elif sec_id == 6:  # globals
                for _ in range(sec.u32()):
                    vt = sec.u8()
                    mut = sec.u8()
                    val = self._eval_const(sec)
                    self.globals.append([vt, mut, val])
            elif sec_id == 7:  # exports
                for _ in range(sec.u32()):
                    name = sec.name()
                    kind = sec.u8()
                    idx = sec.u32()
                    kinds = {0: "func", 1: "table", 2: "mem", 3: "global"}
                    self.exports[name] = (kinds.get(kind, "?"), idx)
            elif sec_id == 8:
                self.start = sec.u32()
            elif sec_id == 9:  # elements
                for _ in range(sec.u32()):
                    if sec.u32() != 0:
                        raise WasmError("unsupported element segment")
                    off = self._eval_const(sec)
                    elem_segs.append(
                        (off, [sec.u32() for _ in range(sec.u32())]))
            elif sec_id == 10:  # code
                for _ in range(sec.u32()):
                    code_bodies.append(sec.bytes_(sec.u32()))
            elif sec_id == 11:  # data
                for _ in range(sec.u32()):
                    if sec.u32() != 0:
                        raise WasmError("unsupported data segment")
                    off = self._eval_const(sec)
                    data_segs.append((off, sec.bytes_(sec.u32())))
            # else: custom/unknown sections skipped
        if len(func_types) != len(code_bodies):
            raise WasmError("func/code section mismatch")
        for ti, raw in zip(func_types, code_bodies):
            br = _Reader(raw)
            locals_: List[int] = []
            for _ in range(br.u32()):
                n = br.u32()
                vt = br.u8()
                locals_.extend([vt] * n)
            body, _ = _decode_expr(br)
            params, results = self.types[ti]
            self.funcs.append(_Func(ti, params, results, locals_, body))
        for off, data in data_segs:
            if off + len(data) > len(self.memory):
                raise WasmError("data segment out of range")
            self.memory[off:off + len(data)] = data
        for off, idxs in elem_segs:
            if off + len(idxs) > len(self.table):
                self.table.extend(
                    [None] * (off + len(idxs) - len(self.table)))
            for i, fi in enumerate(idxs):
                self.table[off + i] = fi
        # dup_data backing (the wasm_runtime_module_dup_data role):
        # when the module exports its own malloc/free, allocations go
        # through it — that is WAMR's behavior and the only way host
        # buffers can coexist with a guest allocator that owns
        # [__heap_base, memory.size). Allocator-less modules (no
        # exported malloc) get a host bump heap in pages above the
        # initial memory; such modules must not malloc (they can't)
        # so the regions cannot collide.
        self._bump_base = len(self.memory)
        self._bump = self._bump_base
        self._mallocs: List[int] = []
        self._guest_alloc = (
            "malloc" in self.exports
            and self.exports["malloc"][0] == "func"
        )
        self._guest_free = (
            "free" in self.exports and self.exports["free"][0] == "func"
        )
        if self.start is not None:
            self._invoke(self.start, [])

    # ------------------------------------------------------- host API

    def dup_data(self, data: bytes) -> int:
        """Copy host bytes (+NUL) into guest memory → guest pointer
        (wasm_runtime_module_dup_data)."""
        need = len(data) + 1
        if self._guest_alloc:
            rets = self.call("malloc", [need])
            ptr = rets[0] if rets else 0
            if not ptr or ptr + need > len(self.memory):
                raise Trap("guest malloc failed for dup_data")
            self._mallocs.append(ptr)
        else:
            if self._bump + need > len(self.memory):
                pages = (self._bump + need - len(self.memory)
                         + PAGE - 1) // PAGE
                if len(self.memory) // PAGE + pages > self.mem_max_pages:
                    raise Trap("dup_data exceeds the memory limit")
                self.memory.extend(bytes(pages * PAGE))
            ptr = self._bump
            self._bump += need
        self.memory[ptr:ptr + len(data)] = data
        self.memory[ptr + len(data)] = 0
        return ptr

    def reset_heap(self) -> None:
        """Release every dup_data allocation (between calls)."""
        if self._guest_alloc and self._guest_free:
            for ptr in self._mallocs:
                try:
                    self.call("free", [ptr])
                except Trap:
                    pass
        self._mallocs.clear()
        self._bump = self._bump_base

    def read_cstr(self, ptr: int, max_len: int = 1 << 20) -> bytes:
        """NUL-terminated guest string at ptr (the filter return
        value)."""
        if ptr <= 0 or ptr >= len(self.memory):
            raise Trap("returned pointer out of range")
        end = self.memory.find(b"\0", ptr, ptr + max_len)
        if end < 0:
            raise Trap("unterminated returned string")
        return bytes(self.memory[ptr:end])

    def call(self, name: str, args: List[Any]) -> List[Any]:
        exp = self.exports.get(name)
        if exp is None or exp[0] != "func":
            raise WasmError(f"exported function {name!r} not found")
        return self._invoke(exp[1], list(args))

    # ----------------------------------------------------- execution

    def _eval_const(self, r: _Reader):
        body, _ = _decode_expr(r)
        if len(body) != 1:
            raise WasmError("unsupported const expr")
        op = body[0]
        if op[0] in (0x41, 0x42, 0x43, 0x44):
            return op[1]
        if op[0] == 0x23:
            return self.globals[op[1]][2]
        raise WasmError("unsupported const expr op")

    def _invoke(self, fidx: int, args: List[Any], depth: int = 0):
        if depth > self.max_call_depth:
            raise Trap("call stack exhausted")
        if fidx < len(self.imported):
            fn, _ti = self.imported[fidx]
            res = fn(self, *args)
            if res is None:
                return []
            return list(res) if isinstance(res, (list, tuple)) else [res]
        f = self.funcs[fidx - len(self.imported)]
        locals_ = list(args)
        for vt in f.locals:
            locals_.append(0.0 if vt in (F32, F64) else 0)
        stack: List[Any] = []
        try:
            self._exec_block(f.body, locals_, stack, depth)
        except _Branch as b:
            # depth 0 here = a br targeting the function frame itself
            # (valid wasm, same as return); -1 = the return opcode
            if b.depth > 0:
                raise Trap("branch escaped function")
        if f.results:
            return stack[-len(f.results):]
        return []

    def _exec_block(self, body: list, locals_: List[Any],
                    stack: List[Any], depth: int) -> None:
        for ins in body:
            op = ins[0]
            if op == 0x02:  # block: branches target the END
                h = len(stack)
                try:
                    self._exec_block(ins[2], locals_, stack, depth)
                except _Branch as b:
                    if b.depth == 0:
                        # void blocktype decodes as SLEB -64 (0x40)
                        arity = 0 if ins[1] == -64 else 1
                        vals = stack[len(stack) - arity:] if arity else []
                        del stack[h:]
                        stack.extend(vals)
                    else:
                        if b.depth > 0:
                            b.depth -= 1
                        raise  # negative depth = function return
            elif op == 0x03:  # loop: branches target the START
                h = len(stack)
                while True:
                    try:
                        self._exec_block(ins[2], locals_, stack, depth)
                        break
                    except _Branch as b:
                        if b.depth == 0:
                            del stack[h:]  # loop params: MVP arity 0
                            continue
                        if b.depth > 0:
                            b.depth -= 1
                        raise
            elif op == 0x04:  # if
                cond = stack.pop()
                h = len(stack)
                try:
                    self._exec_block(ins[2] if cond else ins[3],
                                     locals_, stack, depth)
                except _Branch as b:
                    if b.depth == 0:
                        arity = 0 if ins[1] == -64 else 1
                        vals = stack[len(stack) - arity:] if arity else []
                        del stack[h:]
                        stack.extend(vals)
                    else:
                        if b.depth > 0:
                            b.depth -= 1
                        raise
            elif op == 0x0C:  # br
                raise _Branch(ins[1])
            elif op == 0x0D:  # br_if
                if stack.pop():
                    raise _Branch(ins[1])
            elif op == 0x0E:  # br_table
                i = stack.pop()
                targets, default = ins[1], ins[2]
                raise _Branch(targets[i] if 0 <= i < len(targets)
                              else default)
            elif op == 0x0F:  # return
                raise _Branch(-1)
            elif op == 0x10:  # call
                self._do_call(ins[1], stack, depth)
            elif op == 0x11:  # call_indirect
                ti = ins[1]
                elem = stack.pop()
                if elem < 0 or elem >= len(self.table) \
                        or self.table[elem] is None:
                    raise Trap("undefined table element")
                fi = self.table[elem]
                if fi < len(self.imported):
                    actual_ti = self.imported[fi][1]
                else:
                    actual_ti = self.funcs[fi - len(self.imported)].type_idx
                if actual_ti != ti:
                    raise Trap("indirect call type mismatch")
                self._do_call(fi, stack, depth)
            elif op == 0x00:
                raise Trap("unreachable")
            elif op == 0x01:  # nop
                pass
            elif op == 0x1A:  # drop
                stack.pop()
            elif op == 0x1B:  # select
                c = stack.pop()
                b = stack.pop()
                a = stack.pop()
                stack.append(a if c else b)
            elif op == 0x20:
                stack.append(locals_[ins[1]])
            elif op == 0x21:
                locals_[ins[1]] = stack.pop()
            elif op == 0x22:
                locals_[ins[1]] = stack[-1]
            elif op == 0x23:
                stack.append(self.globals[ins[1]][2])
            elif op == 0x24:
                self.globals[ins[1]][2] = stack.pop()
            elif 0x28 <= op <= 0x35:
                self._load(op, ins[1], stack)
            elif 0x36 <= op <= 0x3E:
                self._store(op, ins[1], stack)
            elif op == 0x3F:
                stack.append(len(self.memory) // PAGE)
            elif op == 0x40:  # memory.grow
                n = stack.pop()
                old = len(self.memory) // PAGE
                if old + n > self.mem_max_pages:
                    stack.append(0xFFFFFFFF)
                else:
                    self.memory.extend(bytes(n * PAGE))
                    # host bump allocations (allocator-less modules
                    # only) stay valid: guest growth extends past them
                    # and the bump base relocates on the next reset
                    if not self._guest_alloc:
                        self._bump_base = max(self._bump_base,
                                              len(self.memory))
                        self._bump = max(self._bump, self._bump_base)
                    stack.append(old)
            elif op in (0x41, 0x42, 0x43, 0x44):
                stack.append(ins[1])
            elif op == 0xFC:
                self._misc(ins[1], stack)
            else:
                self._numeric(op, stack)

    def _misc(self, sub: int, stack: List[Any]) -> None:
        """0xFC prefix: saturating truncations + bulk memory."""
        if sub <= 7:
            bits = 32 if sub < 4 else 64
            signed = sub % 2 == 0
            v = stack.pop()
            if math.isnan(v):
                stack.append(0)
                return
            if math.isinf(v):  # saturate, unlike the trapping trunc
                # sentinel beyond EVERY type's range (u64 max included)
                t = (1 << 64) if v > 0 else -(1 << 64)
            else:
                t = math.trunc(v)
            if signed:
                lo, hi = -(1 << (bits - 1)), (1 << (bits - 1)) - 1
            else:
                lo, hi = 0, (1 << bits) - 1
            stack.append(max(lo, min(hi, t)) & ((1 << bits) - 1))
        elif sub == 10:  # memory.copy
            n = stack.pop()
            src = stack.pop()
            dst = stack.pop()
            if src + n > len(self.memory) or dst + n > len(self.memory) \
                    or n < 0 or src < 0 or dst < 0:
                raise Trap("out of bounds memory access")
            self.memory[dst:dst + n] = self.memory[src:src + n]
        elif sub == 11:  # memory.fill
            n = stack.pop()
            val = stack.pop() & 0xFF
            dst = stack.pop()
            if dst + n > len(self.memory) or n < 0 or dst < 0:
                raise Trap("out of bounds memory access")
            self.memory[dst:dst + n] = bytes([val]) * n
        else:
            raise Trap(f"unsupported misc op {sub}")

    def _do_call(self, fidx: int, stack: List[Any], depth: int) -> None:
        if fidx < len(self.imported):
            n = len(self.types[self.imported[fidx][1]][0])
        else:
            n = len(self.funcs[fidx - len(self.imported)].params)
        args = stack[len(stack) - n:] if n else []
        if n:
            del stack[len(stack) - n:]
        stack.extend(self._invoke(fidx, args, depth + 1))

    # ------------------------------------------------- memory access

    _LOADS = {
        0x28: ("<I", 4, None), 0x29: ("<Q", 8, None),
        0x2A: ("<f", 4, None), 0x2B: ("<d", 8, None),
        0x2C: ("<b", 1, 32), 0x2D: ("<B", 1, 32),
        0x2E: ("<h", 2, 32), 0x2F: ("<H", 2, 32),
        0x30: ("<b", 1, 64), 0x31: ("<B", 1, 64),
        0x32: ("<h", 2, 64), 0x33: ("<H", 2, 64),
        0x34: ("<i", 4, 64), 0x35: ("<I", 4, 64),
    }

    def _load(self, op: int, offset: int, stack: List[Any]) -> None:
        fmt, size, to = self._LOADS[op]
        addr = stack.pop() + offset
        if addr < 0 or addr + size > len(self.memory):
            raise Trap("out of bounds memory access")
        v = struct.unpack_from(fmt, self.memory, addr)[0]
        if to is not None and v < 0:  # signed widen → two's complement
            v &= (1 << to) - 1
        stack.append(v)

    _STORES = {
        0x36: ("<I", 4, 0xFFFFFFFF), 0x37: ("<Q", 8, (1 << 64) - 1),
        0x38: ("<f", 4, None), 0x39: ("<d", 8, None),
        0x3A: ("<B", 1, 0xFF), 0x3B: ("<H", 2, 0xFFFF),
        0x3C: ("<B", 1, 0xFF), 0x3D: ("<H", 2, 0xFFFF),
        0x3E: ("<I", 4, 0xFFFFFFFF),
    }

    def _store(self, op: int, offset: int, stack: List[Any]) -> None:
        fmt, size, mask = self._STORES[op]
        v = stack.pop()
        addr = stack.pop() + offset
        if addr < 0 or addr + size > len(self.memory):
            raise Trap("out of bounds memory access")
        if mask is not None:
            v &= mask
        struct.pack_into(fmt, self.memory, addr, v)

    # ------------------------------------------------- numeric ops

    def _numeric(self, op: int, stack: List[Any]) -> None:
        s = stack
        if op == 0x45:  # i32.eqz
            s.append(int(s.pop() == 0))
        elif 0x46 <= op <= 0x4F:
            b = s.pop()
            a = s.pop()
            s.append(_icmp(op - 0x46, a, b, 32))
        elif op == 0x50:
            s.append(int(s.pop() == 0))
        elif 0x51 <= op <= 0x5A:
            b = s.pop()
            a = s.pop()
            s.append(_icmp(op - 0x51, a, b, 64))
        elif 0x5B <= op <= 0x60:  # f32 cmp
            b = s.pop()
            a = s.pop()
            s.append(_fcmp(op - 0x5B, a, b))
        elif 0x61 <= op <= 0x66:  # f64 cmp
            b = s.pop()
            a = s.pop()
            s.append(_fcmp(op - 0x61, a, b))
        elif op == 0x67:
            s.append(_clz(s.pop(), 32))
        elif op == 0x68:
            s.append(_ctz(s.pop(), 32))
        elif op == 0x69:
            s.append(bin(s.pop()).count("1"))
        elif 0x6A <= op <= 0x78:
            b = s.pop()
            a = s.pop()
            s.append(_ibin(op - 0x6A, a, b, 32))
        elif op == 0x79:
            s.append(_clz(s.pop(), 64))
        elif op == 0x7A:
            s.append(_ctz(s.pop(), 64))
        elif op == 0x7B:
            s.append(bin(s.pop()).count("1"))
        elif 0x7C <= op <= 0x8A:
            b = s.pop()
            a = s.pop()
            s.append(_ibin(op - 0x7C, a, b, 64))
        elif 0x8B <= op <= 0x98:  # f32 unary/binary
            self._fop(op - 0x8B, s, 32)
        elif 0x99 <= op <= 0xA6:  # f64
            self._fop(op - 0x99, s, 64)
        elif op == 0xA7:  # i32.wrap_i64
            s.append(s.pop() & 0xFFFFFFFF)
        elif op in (0xA8, 0xAA):  # i32.trunc_f32_s / f64_s
            s.append(_trunc(s.pop(), 32, True))
        elif op in (0xA9, 0xAB):
            s.append(_trunc(s.pop(), 32, False))
        elif op == 0xAC:  # i64.extend_i32_s
            s.append(_sext(s.pop(), 32) & ((1 << 64) - 1))
        elif op == 0xAD:
            s.append(s.pop() & 0xFFFFFFFF)
        elif op in (0xAE, 0xB0):
            s.append(_trunc(s.pop(), 64, True))
        elif op in (0xAF, 0xB1):
            s.append(_trunc(s.pop(), 64, False))
        elif op in (0xB2, 0xB7):  # fNN.convert_i32_s
            s.append(float(_sext(s.pop(), 32)))
        elif op in (0xB3, 0xB8):
            s.append(float(s.pop() & 0xFFFFFFFF))
        elif op in (0xB4, 0xB9):
            s.append(float(_sext(s.pop(), 64)))
        elif op in (0xB5, 0xBA):
            s.append(float(s.pop() & ((1 << 64) - 1)))
        elif op == 0xB6:  # f32.demote
            s.append(struct.unpack("<f", struct.pack("<f", s.pop()))[0])
        elif op == 0xBB:  # f64.promote
            pass
        elif op == 0xBC:  # i32.reinterpret_f32
            s.append(struct.unpack("<I", struct.pack("<f", s.pop()))[0])
        elif op == 0xBD:
            s.append(struct.unpack("<Q", struct.pack("<d", s.pop()))[0])
        elif op == 0xBE:
            s.append(struct.unpack("<f", struct.pack("<I", s.pop()))[0])
        elif op == 0xBF:
            s.append(struct.unpack("<d", struct.pack("<Q", s.pop()))[0])
        elif op == 0xC0:  # sign-extension ops (widely emitted)
            s.append(_sext(s.pop() & 0xFF, 8) & 0xFFFFFFFF)
        elif op == 0xC1:
            s.append(_sext(s.pop() & 0xFFFF, 16) & 0xFFFFFFFF)
        elif op == 0xC2:
            s.append(_sext(s.pop() & 0xFF, 8) & ((1 << 64) - 1))
        elif op == 0xC3:
            s.append(_sext(s.pop() & 0xFFFF, 16) & ((1 << 64) - 1))
        elif op == 0xC4:
            s.append(_sext(s.pop() & 0xFFFFFFFF, 32) & ((1 << 64) - 1))
        else:
            raise Trap(f"unsupported opcode 0x{op:02x}")

    def _fop(self, sub: int, s: List[Any], bits: int) -> None:
        if sub <= 6:  # unary: abs neg ceil floor trunc nearest sqrt
            a = s.pop()
            if sub == 0:
                v = abs(a)
            elif sub == 1:
                v = -a
            elif sub == 2:
                v = float(math.ceil(a))
            elif sub == 3:
                v = float(math.floor(a))
            elif sub == 4:
                v = float(math.trunc(a))
            elif sub == 5:
                v = float(round(a))  # round-half-even == nearest
            else:
                v = math.sqrt(a) if a >= 0 else math.nan
        else:  # binary: add sub mul div min max copysign
            b = s.pop()
            a = s.pop()
            if sub == 7:
                v = a + b
            elif sub == 8:
                v = a - b
            elif sub == 9:
                v = a * b
            elif sub == 10:
                v = a / b if b != 0 else (
                    math.nan if a == 0 else math.copysign(math.inf, a)
                    * math.copysign(1, b))
            elif sub == 11:
                # wasm min/max propagate NaN regardless of operand
                # order (Python's min/max would return the first arg)
                v = math.nan if math.isnan(a) or math.isnan(b) \
                    else min(a, b)
            elif sub == 12:
                v = math.nan if math.isnan(a) or math.isnan(b) \
                    else max(a, b)
            else:
                v = math.copysign(a, b)
        if bits == 32:
            v = struct.unpack("<f", struct.pack("<f", v))[0]
        s.append(v)


class _Branch(Exception):
    def __init__(self, depth: int):
        self.depth = depth


# --------------------------------------------------- numeric helpers


def _sext(v: int, bits: int) -> int:
    return v - (1 << bits) if v >= (1 << (bits - 1)) else v


def _icmp(sub: int, a: int, b: int, bits: int) -> int:
    sa, sb = _sext(a, bits), _sext(b, bits)
    ops = [a == b, a != b, sa < sb, a < b, sa > sb, a > b,
           sa <= sb, a <= b, sa >= sb, a >= b]
    return int(ops[sub])


def _fcmp(sub: int, a: float, b: float) -> int:
    if math.isnan(a) or math.isnan(b):
        return int(sub == 1)  # only 'ne' is true for NaN operands
    return int([a == b, a != b, a < b, a > b, a <= b, a >= b][sub])


def _ibin(sub: int, a: int, b: int, bits: int) -> int:
    mask = (1 << bits) - 1
    if sub == 0:
        return (a + b) & mask
    if sub == 1:
        return (a - b) & mask
    if sub == 2:
        return (a * b) & mask
    if sub == 3:  # div_s
        sa, sb = _sext(a, bits), _sext(b, bits)
        if sb == 0:
            raise Trap("integer divide by zero")
        q = abs(sa) // abs(sb)
        if (sa < 0) != (sb < 0):
            q = -q
        if q == 1 << (bits - 1):
            raise Trap("integer overflow")
        return q & mask
    if sub == 4:  # div_u
        if b == 0:
            raise Trap("integer divide by zero")
        return (a // b) & mask
    if sub == 5:  # rem_s
        sa, sb = _sext(a, bits), _sext(b, bits)
        if sb == 0:
            raise Trap("integer divide by zero")
        r = abs(sa) % abs(sb)
        if sa < 0:
            r = -r
        return r & mask
    if sub == 6:  # rem_u
        if b == 0:
            raise Trap("integer divide by zero")
        return (a % b) & mask
    if sub == 7:
        return a & b
    if sub == 8:
        return a | b
    if sub == 9:
        return a ^ b
    if sub == 10:
        return (a << (b % bits)) & mask
    if sub == 11:  # shr_s
        return (_sext(a, bits) >> (b % bits)) & mask
    if sub == 12:  # shr_u
        return a >> (b % bits)
    if sub == 13:  # rotl
        n = b % bits
        return ((a << n) | (a >> (bits - n))) & mask if n else a
    if sub == 14:  # rotr
        n = b % bits
        return ((a >> n) | (a << (bits - n))) & mask if n else a
    raise Trap(f"bad ibin {sub}")


def _clz(v: int, bits: int) -> int:
    if v == 0:
        return bits
    return bits - v.bit_length()


def _ctz(v: int, bits: int) -> int:
    if v == 0:
        return bits
    return (v & -v).bit_length() - 1


def _trunc(v: float, bits: int, signed: bool) -> int:
    if math.isnan(v) or math.isinf(v):
        raise Trap("invalid conversion to integer")
    t = math.trunc(v)
    if signed:
        if not -(1 << (bits - 1)) <= t < (1 << (bits - 1)):
            raise Trap("integer overflow")
        return t & ((1 << bits) - 1)
    if not 0 <= t < (1 << bits):
        raise Trap("integer overflow")
    return t
