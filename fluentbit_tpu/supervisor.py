"""Supervisor — parent process that restarts crashed workers.

Reference: src/flb_supervisor.c (supervisor_spawn fork :384-415,
waitpid monitor :314-375, restart-on-request/crash, grace
propagation :268-285). The CLI's ``--supervisor`` flag wraps the run
in this loop: fork a worker running the pipeline; on abnormal exit
(signal/crash) restart it with exponential backoff; SIGTERM/SIGINT
forward to the worker and stop; SIGHUP forwards (hot reload happens
inside the worker).
"""

from __future__ import annotations

import logging
import os
import signal
import time
from typing import Callable, Optional

log = logging.getLogger("flb.supervisor")

RESTART_BACKOFF_BASE = 1.0
RESTART_BACKOFF_CAP = 30.0
#: a nonzero exit faster than this is a startup error (bad config), not
#: a crash — restarting would loop forever on a fatal condition
MIN_UPTIME_FOR_RESTART = 2.0


def run_supervised(worker_main: Callable[[], int],
                   max_restarts: Optional[int] = None) -> int:
    """Fork/monitor loop. Returns the final worker exit code."""
    restarts = 0
    stopping = {"flag": False}
    child = {"pid": 0}

    def forward(signum, frame):
        if signum in (signal.SIGTERM, signal.SIGINT):
            stopping["flag"] = True
        if child["pid"]:
            try:
                os.kill(child["pid"], signum)
            except ProcessLookupError:
                pass

    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        signal.signal(sig, forward)

    while True:
        started = time.time()
        pid = os.fork()
        if pid == 0:
            # worker: default signal dispositions; run the pipeline
            for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
                signal.signal(sig, signal.SIG_DFL)
            os._exit(worker_main())
        child["pid"] = pid
        log.info("supervisor: worker started (pid %d)", pid)
        while True:
            try:
                _, status = os.waitpid(pid, 0)
                break
            except InterruptedError:
                continue
        child["pid"] = 0
        if os.WIFEXITED(status):
            code = os.WEXITSTATUS(status)
            if stopping["flag"] or code == 0:
                log.info("supervisor: worker exited (%d)", code)
                return code
            if time.time() - started < MIN_UPTIME_FOR_RESTART:
                # fast nonzero exit = fatal startup error, not a crash
                log.error("supervisor: worker failed at startup "
                          "(exit %d) — not restarting", code)
                return code
            reason = f"exit code {code}"
        else:
            if stopping["flag"]:
                return 0
            reason = f"signal {os.WTERMSIG(status)}"
        restarts += 1
        if max_restarts is not None and restarts > max_restarts:
            log.error("supervisor: giving up after %d restarts", restarts - 1)
            return 1
        delay = min(RESTART_BACKOFF_CAP,
                    RESTART_BACKOFF_BASE * (2 ** min(restarts - 1, 6)))
        log.warning("supervisor: worker died (%s); restart #%d in %.1fs",
                    reason, restarts, delay)
        deadline = time.time() + delay
        while time.time() < deadline and not stopping["flag"]:
            time.sleep(0.1)
        if stopping["flag"]:
            return 1
