"""in_kubernetes_events — ingest Kubernetes cluster Events.

Reference: plugins/in_kubernetes_events (polls/watches the
/api/v1/events endpoint with the pod service-account token, dedups by
uid + resourceVersion, one record per Event object). This build polls
the list endpoint on an interval over the shared HTTP client path
(TLS + bearer token), tracks the highest resourceVersion, and emits
each new Event as a structured record timestamped from
lastTimestamp/eventTime.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, List, Optional

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from ..core.upstream import close_quietly

log = logging.getLogger("flb.k8s_events")


def _event_ts(ev: dict):
    """Best event timestamp: lastTimestamp | eventTime | firstTimestamp
    (RFC3339) → EventTime; fall back to receive time."""
    import calendar
    import re

    for key in ("lastTimestamp", "eventTime", "firstTimestamp"):
        v = ev.get(key)
        if not isinstance(v, str) or not v:
            continue
        m = re.match(
            r"(\d{4})-(\d{2})-(\d{2})[Tt](\d{2}):(\d{2}):(\d{2})"
            r"(?:\.(\d+))?(?:[Zz]|([+-]\d{2}):?(\d{2}))?", v)
        if not m:
            continue
        y, mo, d, h, mi, s = (int(m.group(i)) for i in range(1, 7))
        frac = m.group(7) or ""
        nsec = int((frac + "000000000")[:9]) if frac else 0
        epoch = calendar.timegm((y, mo, d, h, mi, s, 0, 0, 0))
        if m.group(8) is not None:
            # sign from the STRING: int("-00") == 0 would mis-sign a
            # negative-zero-hour offset like -00:30
            sign = -1 if m.group(8).startswith("-") else 1
            offs = sign * (abs(int(m.group(8))) * 3600
                           + int(m.group(9)) * 60)
            epoch -= offs
        from ..codec.msgpack import EventTime

        return EventTime(epoch, nsec)
    return now_event_time()


@registry.register
class KubernetesEventsInput(InputPlugin):
    name = "kubernetes_events"
    description = "Kubernetes cluster Events (API poll)"
    config_map = [
        ConfigMapEntry("kube_url", "str",
                       default="https://kubernetes.default.svc"),
        ConfigMapEntry("kube_token_file", "str",
                       default="/var/run/secrets/kubernetes.io/"
                               "serviceaccount/token"),
        ConfigMapEntry("kube_namespace", "str", default="",
                       desc="restrict to one namespace (default: all)"),
        ConfigMapEntry("interval_sec", "time", default="5"),
        ConfigMapEntry("kube_request_limit", "int", default=500),
    ]

    def init(self, instance, engine) -> None:
        from urllib.parse import urlsplit

        self.collect_interval = float(self.interval_sec or 5)
        u = urlsplit(self.kube_url)
        self._host = u.hostname or "kubernetes.default.svc"
        self._port = u.port or (443 if u.scheme == "https" else 80)
        if u.scheme == "https" and "tls" not in instance.properties:
            instance.set("tls", "on")
        self._token: Optional[str] = None
        try:
            with open(self.kube_token_file) as f:
                self._token = f.read().strip()
        except OSError:
            pass  # token is optional against unauthenticated test APIs
        # dedup state: uid → last seen resourceVersion
        self._seen: Dict[str, str] = {}

    def _path(self, continue_token: str = "") -> str:
        base = (f"/api/v1/namespaces/{self.kube_namespace}/events"
                if self.kube_namespace else "/api/v1/events")
        path = f"{base}?limit={self.kube_request_limit}"
        if continue_token:
            from urllib.parse import quote

            path += f"&continue={quote(continue_token)}"
        return path

    async def _fetch(self, continue_token: str = "") -> Optional[dict]:
        from ..core.tls import open_connection

        writer = None
        try:
            reader, writer = await open_connection(
                self.instance, self._host, self._port, timeout=10.0)
            headers = [f"GET {self._path(continue_token)} HTTP/1.1",
                       f"Host: {self._host}",
                       "Accept: application/json",
                       "Connection: close"]
            if self._token:
                headers.append(f"Authorization: Bearer {self._token}")
            writer.write(("\r\n".join(headers) + "\r\n\r\n").encode())
            await writer.drain()
            status_line = await asyncio.wait_for(reader.readline(), 15.0)
            parts = status_line.split()
            if len(parts) < 2 or parts[1] != b"200":
                log.debug("kubernetes_events: status %r", status_line)
                return None
            length = None
            chunked = False
            while True:
                line = await asyncio.wait_for(reader.readline(), 15.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                low = line.lower()
                if low.startswith(b"content-length:"):
                    length = int(line.split(b":", 1)[1])
                elif low.startswith(b"transfer-encoding:") and \
                        b"chunked" in low:
                    chunked = True
            if chunked:
                body = bytearray()
                while True:
                    size_line = await asyncio.wait_for(
                        reader.readline(), 15.0)
                    size = int(size_line.strip() or b"0", 16)
                    if size == 0:
                        break
                    body += await asyncio.wait_for(
                        reader.readexactly(size + 2), 15.0)
                    del body[-2:]
                body = bytes(body)
            elif length is not None:
                body = await asyncio.wait_for(
                    reader.readexactly(length), 15.0)
            else:
                body = await asyncio.wait_for(reader.read(), 15.0)
            return json.loads(body)
        except (OSError, ConnectionError, ValueError,
                asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
            log.debug("kubernetes_events: fetch failed: %r", e)
            return None
        finally:
            if writer is not None:
                close_quietly(writer)

    def _emit(self, engine, events: List[dict]) -> None:
        buf = bytearray()
        n = 0
        for ev in events:
            meta = ev.get("metadata") or {}
            uid = meta.get("uid") or meta.get("name") or ""
            rv = str(meta.get("resourceVersion") or "")
            if self._seen.get(uid) == rv:
                continue
            self._seen[uid] = rv
            if len(self._seen) > 8192:  # bound the dedup table
                for k in list(self._seen)[:4096]:
                    del self._seen[k]
            buf += encode_event(ev, _event_ts(ev))
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(buf), n)

    def collect(self, engine) -> None:
        """Driven by the engine's collector; the fetch runs on the
        engine loop when available, inline otherwise."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            task = asyncio.ensure_future(self._collect_async(engine))
            # errors surface via the collector's exception logging
            task.add_done_callback(lambda t: t.exception())
        else:
            asyncio.run(self._collect_async(engine))

    async def _collect_async(self, engine) -> None:
        """Fetch every page (the API caps a list at `limit` items and
        hands back metadata.continue for the rest)."""
        token = ""
        for _page in range(64):  # hard bound against a looping server
            payload = await self._fetch(token)
            if not payload:
                return
            items = payload.get("items") or []
            if items:
                self._emit(engine, items)
            token = (payload.get("metadata") or {}).get("continue") or ""
            if not token:
                return
