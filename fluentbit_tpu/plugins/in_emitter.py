"""in_emitter — internal record re-ingestion input.

Reference: plugins/in_emitter/emitter.c. A passive input with no
collector: other plugins (filter_rewrite_tag, filter_log_to_metrics,
chunk traces) push records into it via ``add_record``, and the records
re-enter the FULL pipeline (routing + filters) under their new tag via
the engine's normal ingest path. Each consumer creates its own hidden
instance (``emitter_for_<name>`` alias, rewrite_tag.c:245-260).
"""

from __future__ import annotations

from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry


@registry.register
class EmitterInput(InputPlugin):
    name = "emitter"
    description = "internal re-ingestion channel"
    config_map = [
        ConfigMapEntry("ring_buffer_size", "int", default=0,
                       desc="accepted for parity; ingest is direct"),
    ]

    def init(self, instance, engine) -> None:
        self._engine = engine

    def add_record(self, tag: str, data: bytes, n_records: int = 1) -> int:
        """in_emitter_add_record: append encoded log events under ``tag``.
        Returns records written or -1 on backpressure."""
        return self._engine.input_log_append(
            self.instance, tag, data, n_records
        )

    def add_event(self, tag: str, data: bytes, event_type: str,
                  n_records: int = 1) -> int:
        """Typed (metrics/traces) re-ingestion — log_to_metrics' emitter
        path (flb_input_metrics_append)."""
        return self._engine.input_event_append(
            self.instance, tag, data, event_type, n_records
        )
