"""Basic outputs: stdout, null, lib, file, counter, flowcounter, exit, retry.

Reference: plugins/out_stdout, out_null (bench sink), out_lib (embedding
capture), out_file, out_counter, out_flowcounter, out_exit, out_retry
(test plugin that always returns FLB_RETRY).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Optional

from ..codec.events import decode_events
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..codec.chunk import (
    EVENT_TYPE_LOGS,
    EVENT_TYPE_METRICS,
    EVENT_TYPE_TRACES,
)


def _metrics_payloads(data: bytes):
    """Decode a METRICS-type chunk: a sequence of metrics snapshots
    (one per emitter append). Empty list when it is log events."""
    from ..codec.msgpack import Unpacker
    from ..core.metrics import is_metrics_payload

    out = []
    try:
        for obj in Unpacker(data):
            if not is_metrics_payload(obj):
                return []
            out.append(obj)
    except Exception:
        return []
    return out


def _json_default(o):
    if isinstance(o, EventTime):
        return float(o)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)


def format_json_lines(data: bytes, with_ts: bool = True, date_key: str = "date") -> str:
    lines = []
    for ev in decode_events(data):
        if with_ts:
            lines.append(json.dumps(
                {date_key: round(ev.ts_float, 9), **ev.body}, default=_json_default,
                separators=(",", ":"),
            ))
        else:
            lines.append(json.dumps(ev.body, default=_json_default,
                                    separators=(",", ":")))
    return "\n".join(lines)


@registry.register
class StdoutOutput(OutputPlugin):
    """plugins/out_stdout: prints records; formats json_lines / json / msgpack."""

    name = "stdout"
    event_types = (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES)
    config_map = [
        ConfigMapEntry("format", "str", default="print"),
        ConfigMapEntry("json_date_key", "str", default="date"),
    ]

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        fmt = (self.format or "print").lower()
        out = sys.stdout
        payloads = _metrics_payloads(data)
        if payloads:
            from ..core.metrics import payload_to_prometheus

            # snapshots are cumulative per source registry: merge in
            # order so each metric's latest snapshot wins
            merged = {}
            for p in payloads:
                for m in p.get("metrics", []):
                    merged[m.get("name", "")] = m
            out.write(payload_to_prometheus(
                {"meta": {}, "metrics": list(merged.values())}
            ))
            out.flush()
            return FlushResult.OK
        if fmt == "msgpack":
            out.buffer.write(data)
        elif fmt in ("json", "json_lines", "json_stream"):
            text = format_json_lines(data, date_key=self.json_date_key or "date")
            if fmt == "json":
                text = "[" + text.replace("\n", ",") + "]"
            out.write(text + "\n")
        else:
            # classic fluent-bit print: [idx] tag: [ts, {record}]
            for i, ev in enumerate(decode_events(data)):
                body = json.dumps(ev.body, default=_json_default)
                out.write(f"[{i}] {tag}: [{ev.ts_float:.9f}, {body}]\n")
        out.flush()
        return FlushResult.OK


@registry.register
class NullOutput(OutputPlugin):
    """plugins/out_null: discard everything (bench sink)."""

    name = "null"
    event_types = (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES)

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        return FlushResult.OK


@registry.register
class LibOutput(OutputPlugin):
    """plugins/out_lib: hand each flush to a user callback.

    The callback receives (data: bytes, tag: str) and is the runtime-test
    assertion hook (tests/runtime/filter_grep.c:17-54 pattern).
    """

    name = "lib"
    event_types = (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES)
    config_map = [ConfigMapEntry("callback", "raw")]

    def init(self, instance, engine) -> None:
        if self.callback is not None and not callable(self.callback):
            raise TypeError("out_lib callback must be callable")

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        if self.callback is not None:
            self.callback(data, tag)
        return FlushResult.OK


@registry.register
class FileOutput(OutputPlugin):
    """plugins/out_file: append records to <path>/<file or tag>."""

    name = "file"
    config_map = [
        ConfigMapEntry("path", "str", default="."),
        ConfigMapEntry("file", "str"),
        ConfigMapEntry("format", "str", default="out_file"),
        ConfigMapEntry("mkdir", "bool", default="false"),
    ]

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        fname = self.file or tag
        path = os.path.join(self.path or ".", fname)
        try:
            if self.mkdir:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                fmt = (self.format or "out_file").lower()
                for ev in decode_events(data):
                    body = json.dumps(ev.body, default=_json_default)
                    if fmt == "plain":
                        f.write(body + "\n")
                    elif fmt == "json_lines":
                        f.write(json.dumps({"date": ev.ts_float, **ev.body},
                                           default=_json_default) + "\n")
                    else:  # out_file classic: tag: [ts, record]
                        f.write(f"{tag}: [{ev.ts_float:.9f}, {body}]\n")
        except OSError:
            return FlushResult.RETRY
        return FlushResult.OK


@registry.register
class CounterOutput(OutputPlugin):
    """plugins/out_counter: prints cumulative record count per flush."""

    name = "counter"

    def init(self, instance, engine) -> None:
        self.total = 0

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        self.total += len(decode_events(data))
        sys.stdout.write(f"{time.time():.9f},{self.total} (total = {self.total})\n")
        return FlushResult.OK


@registry.register
class FlowCounterOutput(OutputPlugin):
    """plugins/out_flowcounter: per-tag rate counting at an interval."""

    name = "flowcounter"
    config_map = [ConfigMapEntry("unit", "str", default="minute")]

    def init(self, instance, engine) -> None:
        self.counts = {}

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        n = len(decode_events(data))
        cnt = self.counts.setdefault(tag, [0, 0])
        cnt[0] += n
        cnt[1] += len(data)
        return FlushResult.OK


@registry.register
class ExitOutput(OutputPlugin):
    """plugins/out_exit: stop the engine after N flushes (test plugin)."""

    name = "exit"
    config_map = [ConfigMapEntry("flush_count", "int", default=1)]

    def init(self, instance, engine) -> None:
        self._seen = 0

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        self._seen += 1
        if self._seen >= self.flush_count:
            engine.request_stop()
        return FlushResult.OK


@registry.register
class PrometheusExporterOutput(OutputPlugin):
    """plugins/out_prometheus_exporter: aggregate metrics-type chunks and
    expose them as Prometheus text. ``render()`` returns the current
    exposition (served over HTTP by the admin server / a listener when
    host/port configured; BASELINE config 4 sink)."""

    name = "prometheus_exporter"
    event_types = (EVENT_TYPE_METRICS,)
    config_map = [
        ConfigMapEntry("add_label", "slist", multiple=True, slist_max_split=1),
    ]

    def init(self, instance, engine) -> None:
        self._payloads = {}  # metric fqname -> latest metric entry
        self._extra = []
        for pair in self.add_label or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                self._extra.append((parts[0], parts[1]))

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        payloads = _metrics_payloads(data)
        if not payloads:
            return FlushResult.ERROR
        # snapshots are cumulative PER SOURCE registry; a chunk may carry
        # snapshots from several filters — merge every one in order so
        # the last snapshot of EACH metric name wins
        for payload in payloads:
            for m in payload.get("metrics", []):
                entry = dict(m)
                if self._extra:
                    extra_keys = [k for k, _ in self._extra]
                    extra_vals = [v for _, v in self._extra]
                    entry["labels"] = list(m.get("labels", [])) + extra_keys
                    entry["values"] = [
                        {"labels": list(s.get("labels", [])) + extra_vals,
                         "value": s.get("value")}
                        for s in m.get("values", [])
                    ]
                    entry["hist"] = [
                        {**h,
                         "labels": list(h.get("labels", [])) + extra_vals}
                        for h in m.get("hist", [])
                    ]
                self._payloads[m.get("name", "")] = entry
        return FlushResult.OK

    def render(self) -> str:
        from ..core.metrics import payload_to_prometheus

        return payload_to_prometheus(
            {"meta": {}, "metrics": list(self._payloads.values())}
        )


@registry.register
class RetryOutput(OutputPlugin):
    """plugins/out_retry: always ask for a retry (exercises the scheduler)."""

    name = "retry"

    def init(self, instance, engine) -> None:
        self.attempts = 0

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        self.attempts += 1
        return FlushResult.RETRY
