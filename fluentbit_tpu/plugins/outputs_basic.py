"""Basic outputs: stdout, null, lib, file, counter, flowcounter, exit, retry.

Reference: plugins/out_stdout, out_null (bench sink), out_lib (embedding
capture), out_file, out_counter, out_flowcounter, out_exit, out_retry
(test plugin that always returns FLB_RETRY).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Optional

from ..codec.events import decode_events
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..codec.chunk import (
    EVENT_TYPE_LOGS,
    EVENT_TYPE_METRICS,
    EVENT_TYPE_TRACES,
)


def _json_default(o):
    if isinstance(o, EventTime):
        return float(o)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)


def format_json_lines(data: bytes, with_ts: bool = True, date_key: str = "date") -> str:
    lines = []
    for ev in decode_events(data):
        if with_ts:
            lines.append(json.dumps(
                {date_key: round(ev.ts_float, 9), **ev.body}, default=_json_default,
                separators=(",", ":"),
            ))
        else:
            lines.append(json.dumps(ev.body, default=_json_default,
                                    separators=(",", ":")))
    return "\n".join(lines)


@registry.register
class StdoutOutput(OutputPlugin):
    """plugins/out_stdout: prints records; formats json_lines / json / msgpack."""

    name = "stdout"
    event_types = (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES)
    config_map = [
        ConfigMapEntry("format", "str", default="print"),
        ConfigMapEntry("json_date_key", "str", default="date"),
    ]

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        fmt = (self.format or "print").lower()
        out = sys.stdout
        if fmt == "msgpack":
            out.buffer.write(data)
        elif fmt in ("json", "json_lines", "json_stream"):
            text = format_json_lines(data, date_key=self.json_date_key or "date")
            if fmt == "json":
                text = "[" + text.replace("\n", ",") + "]"
            out.write(text + "\n")
        else:
            # classic fluent-bit print: [idx] tag: [ts, {record}]
            for i, ev in enumerate(decode_events(data)):
                body = json.dumps(ev.body, default=_json_default)
                out.write(f"[{i}] {tag}: [{ev.ts_float:.9f}, {body}]\n")
        out.flush()
        return FlushResult.OK


@registry.register
class NullOutput(OutputPlugin):
    """plugins/out_null: discard everything (bench sink)."""

    name = "null"
    event_types = (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES)

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        return FlushResult.OK


@registry.register
class LibOutput(OutputPlugin):
    """plugins/out_lib: hand each flush to a user callback.

    The callback receives (data: bytes, tag: str) and is the runtime-test
    assertion hook (tests/runtime/filter_grep.c:17-54 pattern).
    """

    name = "lib"
    config_map = [ConfigMapEntry("callback", "raw")]

    def init(self, instance, engine) -> None:
        if self.callback is not None and not callable(self.callback):
            raise TypeError("out_lib callback must be callable")

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        if self.callback is not None:
            self.callback(data, tag)
        return FlushResult.OK


@registry.register
class FileOutput(OutputPlugin):
    """plugins/out_file: append records to <path>/<file or tag>."""

    name = "file"
    config_map = [
        ConfigMapEntry("path", "str", default="."),
        ConfigMapEntry("file", "str"),
        ConfigMapEntry("format", "str", default="out_file"),
        ConfigMapEntry("mkdir", "bool", default="false"),
    ]

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        fname = self.file or tag
        path = os.path.join(self.path or ".", fname)
        try:
            if self.mkdir:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                fmt = (self.format or "out_file").lower()
                for ev in decode_events(data):
                    body = json.dumps(ev.body, default=_json_default)
                    if fmt == "plain":
                        f.write(body + "\n")
                    elif fmt == "json_lines":
                        f.write(json.dumps({"date": ev.ts_float, **ev.body},
                                           default=_json_default) + "\n")
                    else:  # out_file classic: tag: [ts, record]
                        f.write(f"{tag}: [{ev.ts_float:.9f}, {body}]\n")
        except OSError:
            return FlushResult.RETRY
        return FlushResult.OK


@registry.register
class CounterOutput(OutputPlugin):
    """plugins/out_counter: prints cumulative record count per flush."""

    name = "counter"

    def init(self, instance, engine) -> None:
        self.total = 0

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        self.total += len(decode_events(data))
        sys.stdout.write(f"{time.time():.9f},{self.total} (total = {self.total})\n")
        return FlushResult.OK


@registry.register
class FlowCounterOutput(OutputPlugin):
    """plugins/out_flowcounter: per-tag rate counting at an interval."""

    name = "flowcounter"
    config_map = [ConfigMapEntry("unit", "str", default="minute")]

    def init(self, instance, engine) -> None:
        self.counts = {}

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        n = len(decode_events(data))
        cnt = self.counts.setdefault(tag, [0, 0])
        cnt[0] += n
        cnt[1] += len(data)
        return FlushResult.OK


@registry.register
class ExitOutput(OutputPlugin):
    """plugins/out_exit: stop the engine after N flushes (test plugin)."""

    name = "exit"
    config_map = [ConfigMapEntry("flush_count", "int", default=1)]

    def init(self, instance, engine) -> None:
        self._seen = 0

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        self._seen += 1
        if self._seen >= self.flush_count:
            engine.request_stop()
        return FlushResult.OK


@registry.register
class RetryOutput(OutputPlugin):
    """plugins/out_retry: always ask for a retry (exercises the scheduler)."""

    name = "retry"

    def init(self, instance, engine) -> None:
        self.attempts = 0

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        self.attempts += 1
        return FlushResult.RETRY
