"""Extension-runtime filters: script (Python), lua + wasm gates.

Reference layer 9 (SURVEY §1): the reference embeds out-of-language
filter runtimes — LuaJIT (plugins/filter_lua, src/flb_lua.c) and WAMR
(plugins/filter_wasm, src/wasm/flb_wasm.c). In this build Python IS the
embedding language, so the idiomatic equivalent is a user-supplied
Python callback with the filter_lua contract:

    def cb_filter(tag, timestamp, record):
        return code, timestamp, record

    code -1 → drop the record
          0 → keep unmodified
          1 → record AND timestamp were modified
          2 → record modified, original timestamp kept
    (the filter_lua return contract, plugins/filter_lua/lua.c:659-705)

``wasm`` is registered as an explicit gate (WAMR is not vendored in
this image); ``lua`` is real — the from-scratch Lua runtime in
``fluentbit_tpu.luart`` (plugins/filter_lua.py).
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry

log = logging.getLogger("flb.script")


@registry.register
class ScriptFilter(FilterPlugin):
    name = "script"
    description = "user Python callback filter (filter_lua contract)"
    config_map = [
        ConfigMapEntry("script", "str", desc="path to the Python file"),
        ConfigMapEntry("call", "str", default="cb_filter",
                       desc="function name inside the script"),
        ConfigMapEntry("code", "str",
                       desc="inline script body (alternative to script)"),
        ConfigMapEntry("protected_mode", "bool", default=True,
                       desc="exceptions keep the record instead of "
                            "failing the chain"),
    ]

    def init(self, instance, engine) -> None:
        if not self.script and not self.code:
            raise ValueError("script filter requires 'script' or 'code'")
        source = self.code
        filename = "<inline>"
        if self.script:
            filename = self.script
            with open(self.script, "r", encoding="utf-8") as f:
                source = f.read()
        namespace: dict = {}
        exec(compile(source, filename, "exec"), namespace)  # noqa: S102
        fn = namespace.get(self.call or "cb_filter")
        if not callable(fn):
            raise ValueError(
                f"script filter: function {self.call!r} not found in "
                f"{filename}"
            )
        self._fn: Callable = fn

    def filter(self, events: list, tag: str, engine) -> tuple:
        out: List[LogEvent] = []
        modified = False
        for ev in events:
            try:
                code, ts, record = self._fn(tag, ev.ts_float, ev.body)
                if code == -1:
                    modified = True
                    continue
                if code == 0:
                    out.append(ev)
                    continue
                # code 1: returned timestamp; code 2: original kept
                new_ts = ts if code == 1 else ev.timestamp
                if isinstance(record, list):
                    # split: one input record → several outputs (the
                    # filter_lua array return form)
                    new_evs = [LogEvent(new_ts, dict(r), ev.metadata,
                                        raw=None) for r in record]
                else:
                    new_evs = [LogEvent(new_ts, dict(record), ev.metadata,
                                        raw=None)]
            except Exception:
                # protected mode covers the whole per-record handling —
                # a bad return shape must not revert the batch
                if not self.protected_mode:
                    raise
                log.exception("script filter callback failed")
                out.append(ev)
                continue
            modified = True
            out.extend(new_evs)
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)


# filter_lua (plugins/filter_lua.py, luart runtime) and filter_wasm
# (plugins/filter_wasm.py, wasmrt interpreter) are real — no gates left
# in the extension-runtime family except exec_wasi's WASI surface.
