"""out_kafka — native Kafka producer (no librdkafka).

Reference: plugins/out_kafka/kafka.c (librdkafka producer; config map
kafka.c:1412-1480). This build speaks the broker protocol directly via
utils/kafka_protocol: Metadata v1 discovers partition leaders, records
pack into magic-v2 RecordBatches, Produce v3 delivers with configurable
acks. Record semantics mirror the reference: ``format`` json (default)
/ msgpack / raw, ``topic_key`` routes per record when ``dynamic_topic``
is on, ``message_key``/``message_key_field`` pick the kafka key,
``timestamp_key`` injects the event time (kafka.c:244-280).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..codec.events import decode_events
from ..codec.msgpack import EventTime, packb
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..utils import kafka_protocol as kp

log = logging.getLogger("flb.out_kafka")

# retryable broker error codes: leadership moved / metadata stale /
# topic still propagating (3 = UNKNOWN_TOPIC_OR_PARTITION is transient
# during creation)
_RETRYABLE = {3, 5, 6, 7, 9, 10, 14, 18, 19}


def _json_default(o):
    if isinstance(o, EventTime):
        return float(o)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)


@registry.register
class KafkaOutput(OutputPlugin):
    name = "kafka"
    description = "Kafka producer (native wire protocol)"
    config_map = [
        ConfigMapEntry("brokers", "str", default="127.0.0.1:9092"),
        ConfigMapEntry("topics", "str", default="fluent-bit"),
        ConfigMapEntry("topic_key", "str"),
        ConfigMapEntry("dynamic_topic", "bool", default=False),
        ConfigMapEntry("format", "str", default="json"),
        ConfigMapEntry("message_key", "str"),
        ConfigMapEntry("message_key_field", "str"),
        ConfigMapEntry("timestamp_key", "str", default="@timestamp"),
        ConfigMapEntry("timestamp_format", "str", default="double"),
        ConfigMapEntry("required_acks", "int", default=1,
                       desc="rdkafka request.required.acks"),
        ConfigMapEntry("client_id", "str", default="fluentbit-tpu"),
    ]

    CONNECT_TIMEOUT = 10.0
    IO_TIMEOUT = 30.0

    def init(self, instance, engine) -> None:
        self._brokers: List[Tuple[str, int]] = []
        for item in (self.brokers or "").split(","):
            item = item.strip()
            if not item:
                continue
            host, _, port = item.partition(":")
            self._brokers.append((host, int(port or 9092)))
        if not self._brokers:
            raise ValueError("kafka: no brokers configured")
        self._topics = [t.strip() for t in (self.topics or "").split(",")
                        if t.strip()]
        if not self._topics:
            raise ValueError("kafka: no topics configured")
        self._corr = 0
        self._pools: Dict[Tuple[str, int], object] = {}
        # metadata cache: topic -> {partition: leader}, node -> addr
        self._meta_topics: Dict[str, Dict[int, int]] = {}
        self._meta_nodes: Dict[int, Tuple[str, int]] = {}
        self._rr = 0

    # ------------------------------------------------------------ io

    def _pool(self, addr: Tuple[str, int]):
        """Keepalive pool per broker (the shared core.upstream layer —
        no per-flush TCP churn, same as the HTTP delivery base)."""
        from ..core.upstream import Upstream

        pool = self._pools.get(addr)
        if pool is None:
            self._pools[addr] = pool = Upstream(
                self.instance, addr[0], addr[1],
                connect_timeout=self.CONNECT_TIMEOUT)
        return pool

    async def _rpc(self, addr: Tuple[str, int], api: int, version: int,
                   body: bytes, expect_response: bool = True) -> bytes:
        self._corr += 1
        corr = self._corr
        pool = self._pool(addr)
        reader, writer, _reused, uses = await pool.get()
        try:
            writer.write(kp.request(api, version, corr,
                                    self.client_id or "fbtpu", body))
            await asyncio.wait_for(writer.drain(), self.IO_TIMEOUT)
            if not expect_response:
                # acks=0: the broker sends nothing back (fire and
                # forget — librdkafka's request.required.acks=0)
                pool.release(reader, writer, reusable=True,
                             use_count=uses)
                return b""
            raw_len = await asyncio.wait_for(reader.readexactly(4),
                                             self.IO_TIMEOUT)
            n = int.from_bytes(raw_len, "big")
            if n < 4 or n > 64 * 1024 * 1024:
                raise kp.KafkaProtocolError(f"bad response length {n}")
            payload = await asyncio.wait_for(reader.readexactly(n),
                                             self.IO_TIMEOUT)
        except BaseException:
            pool.release(reader, writer, reusable=False)
            raise
        pool.release(reader, writer, reusable=True, use_count=uses)
        got_corr, rest = kp.parse_response_header(payload)
        if got_corr != corr:
            raise kp.KafkaProtocolError("correlation id mismatch")
        return rest

    def exit(self) -> None:
        for pool in self._pools.values():
            pool.close()
        self._pools.clear()

    async def _refresh_metadata(self, topics: List[str]) -> None:
        last: Exception = kp.KafkaProtocolError("no brokers reachable")
        for addr in self._brokers:
            try:
                rest = await self._rpc(addr, kp.API_METADATA, 1,
                                       kp.metadata_request(topics))
                nodes, tops, errors = kp.parse_metadata_response(rest)
                self._meta_nodes.update(nodes)
                self._meta_topics.update(tops)
                for t, err in errors.items():
                    log.warning("kafka metadata error %d for topic %s",
                                err, t)
                return
            except (OSError, asyncio.TimeoutError,
                    kp.KafkaProtocolError) as e:
                last = e
        raise last

    def _leader_addr(self, topic: str, partition: int) -> Tuple[str, int]:
        leader = self._meta_topics.get(topic, {}).get(partition)
        addr = self._meta_nodes.get(leader) if leader is not None else None
        if addr is None:
            return self._brokers[0]
        # brokers may advertise a hostname the test/stub env can't
        # resolve; the configured broker list wins for localhost setups
        return addr

    # ------------------------------------------------------- format

    def _record_value(self, ev) -> bytes:
        body = dict(ev.body) if isinstance(ev.body, dict) else {
            "message": ev.body}
        tk = self.timestamp_key
        if tk:
            if (self.timestamp_format or "double") == "iso8601":
                t = time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.gmtime(ev.ts_float))
                body[tk] = t + f".{int(ev.ts_float % 1 * 1000):03d}Z"
            else:
                body[tk] = ev.ts_float
        fmt = (self.format or "json").lower()
        if fmt == "msgpack":
            return packb(body)
        if fmt == "raw":
            v = body.get(self.message_key_field or "log", "")
            return v if isinstance(v, bytes) else str(v).encode()
        return json.dumps(body, default=_json_default,
                          separators=(",", ":")).encode()

    def _record_key(self, ev) -> Optional[bytes]:
        if self.message_key_field and isinstance(ev.body, dict):
            v = ev.body.get(self.message_key_field)
            if isinstance(v, str):
                return v.encode()
        if self.message_key:
            return self.message_key.encode()
        return None

    def _record_topic(self, ev) -> str:
        if self.dynamic_topic and self.topic_key \
                and isinstance(ev.body, dict):
            v = ev.body.get(self.topic_key)
            if isinstance(v, str) and v:
                return v
        return self._topics[0]

    def _partition_of(self, topic: str, key: Optional[bytes]) -> int:
        parts = sorted(self._meta_topics.get(topic, {0: 0}))
        if not parts:
            parts = [0]
        if key is not None:
            return parts[zlib.crc32(key) % len(parts)]
        self._rr += 1
        return parts[self._rr % len(parts)]

    # -------------------------------------------------------- flush

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        events = [ev for ev in decode_events(data)
                  if not (ev.is_group_start() or ev.is_group_end())]
        if not events:
            return FlushResult.OK
        topics_needed = sorted({self._record_topic(ev) for ev in events})
        try:
            if any(t not in self._meta_topics for t in topics_needed):
                await self._refresh_metadata(topics_needed)
        except (OSError, asyncio.TimeoutError, kp.KafkaProtocolError):
            return FlushResult.RETRY
        # group records per (topic, partition)
        grouped: Dict[Tuple[str, int], List] = {}
        for ev in events:
            topic = self._record_topic(ev)
            key = self._record_key(ev)
            pid = self._partition_of(topic, key)
            grouped.setdefault((topic, pid), []).append(
                (key, self._record_value(ev)))
        # one produce per leader
        by_addr: Dict[Tuple[str, int], Dict[str, Dict[int, bytes]]] = {}
        now_ms = int(time.time() * 1000)
        for (topic, pid), records in grouped.items():
            batch = kp.encode_record_batch(records, now_ms)
            addr = self._leader_addr(topic, pid)
            by_addr.setdefault(addr, {}).setdefault(topic, {})[pid] = batch
        acks = self.required_acks if self.required_acks is not None else 1
        for addr, topic_batches in by_addr.items():
            try:
                rest = await self._rpc(
                    addr, kp.API_PRODUCE, 3,
                    kp.produce_request(topic_batches, acks=acks),
                    expect_response=acks != 0)
            except (OSError, asyncio.TimeoutError,
                    kp.KafkaProtocolError):
                self._meta_topics.clear()  # leaders may have moved
                return FlushResult.RETRY
            if acks != 0:
                for topic, pid, err, _off in \
                        kp.parse_produce_response(rest):
                    if err == 0:
                        continue
                    log.warning("kafka produce error %d on %s[%d]",
                                err, topic, pid)
                    if err in _RETRYABLE:
                        self._meta_topics.clear()
                        return FlushResult.RETRY
                    return FlushResult.ERROR
        return FlushResult.OK
