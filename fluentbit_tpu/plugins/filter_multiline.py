"""filter_multiline — concatenate split log records.

Reference: plugins/filter_multiline (ml.c): a list of multiline parsers
(``multiline.parser``, tried per stream), ``key_content`` selecting the
concatenated field, buffered mode holding partial groups and flushing
them after ``flush_ms`` through a hidden emitter; the filter recognises
its own emitter's records and passes them through untouched (the
i_ins == ctx->ins_emitter check) to avoid re-buffering.

Per-tag streams: records from different tags never concatenate.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..codec.events import LogEvent, reencode_event
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..multiline import create_stream


@registry.register
class MultilineFilter(FilterPlugin):
    name = "multiline"
    description = "concatenate multiline/split records"
    config_map = [
        ConfigMapEntry("multiline.parser", "clist"),
        ConfigMapEntry("multiline.key_content", "str", default="log"),
        ConfigMapEntry("flush_ms", "int", default=2000),
        ConfigMapEntry("mode", "str", default="parser"),
        ConfigMapEntry("emitter_name", "str"),
        ConfigMapEntry("emitter_mem_buf_limit", "str", default="10M"),
    ]

    def init(self, instance, engine) -> None:
        if not self.multiline_parser:
            raise ValueError("multiline: multiline.parser is required")
        self._engine = engine
        self.key = self.multiline_key_content or "log"
        self._streams: Dict[str, object] = {}  # tag → stream
        self._sink: List[LogEvent] = []
        self.emitter = None
        self.emitter_instance = None
        if engine is not None:
            # validate the whole parser list up front
            create_stream(self.multiline_parser, engine.ml_parsers,
                          lambda *_: None, self.flush_ms)
            name = self.emitter_name or f"emitter_for_{instance.display_name}"
            ins = engine.hidden_input(
                "emitter", owner=instance, alias=name,
                mem_buf_limit=self.emitter_mem_buf_limit,
            )
            self.emitter = ins.plugin
            self.emitter_instance = ins
            # timeout flush rides the emitter's collector (the
            # reference's flush_ms timer)
            ins.plugin.collect_interval = max(0.25, self.flush_ms / 1000.0)
            ins.plugin.collect = lambda _engine: self.flush_timed_out()

    # -- stream plumbing --

    def _stream_for(self, tag: str):
        st = self._streams.get(tag)
        if st is None:
            st = create_stream(
                self.multiline_parser,
                self._engine.ml_parsers if self._engine else None,
                lambda text, ctx: self._sink.append(
                    self._build_event(text, ctx)
                ),
                self.flush_ms,
            )
            self._streams[tag] = st
        return st

    def _build_event(self, text: str, ctx) -> LogEvent:
        if ctx is None:
            return LogEvent(timestamp=0, body={self.key: text})
        body = dict(ctx.body)
        body[self.key] = text
        return LogEvent(timestamp=ctx.timestamp, body=body,
                        metadata=ctx.metadata, raw=None)

    # -- the filter --

    def filter(self, events: list, tag: str, engine) -> tuple:
        if (
            engine is not None
            and self.emitter_instance is not None
            and engine._ingest_src is self.emitter_instance
        ):
            # our own emitter's timeout flush: pass through untouched
            return (FilterResult.NOTOUCH, events)
        stream = self._stream_for(tag)
        out: List[LogEvent] = []
        self._sink = out  # stream emits synchronously → order preserved
        for ev in events:
            content = ev.body.get(self.key) if isinstance(ev.body, dict) else None
            if not isinstance(content, str):
                stream.flush()
                out.append(ev)
                continue
            stream.feed(content, ev)
        self._sink = []
        return (FilterResult.MODIFIED, out)

    def drain(self, engine) -> None:
        """Engine shutdown: flush every pending group through the
        emitter so buffered records are not lost."""
        if self._engine is None:
            return
        with self._engine._ingest_lock:
            for tag, stream in list(self._streams.items()):
                done: List[LogEvent] = []
                self._sink = done
                stream.flush()
                self._sink = []
                for ev in done:
                    if self.emitter is not None:
                        self.emitter.add_record(tag, reencode_event(ev), 1)

    def flush_timed_out(self) -> None:
        """Emit groups that waited past flush_ms (timer-driven; the
        records re-enter the pipeline via the emitter and are passed
        through untouched above). Serialized against filter() by the
        engine's ingest lock."""
        if self._engine is None:
            return
        with self._engine._ingest_lock:
            for tag, stream in list(self._streams.items()):
                if not stream.timed_out():
                    continue
                done: List[LogEvent] = []
                self._sink = done
                stream.flush()
                self._sink = []
                for ev in done:
                    if self.emitter is not None:
                        self.emitter.add_record(
                            tag, reencode_event(ev), 1
                        )
