"""out_websocket — deliver records over an RFC 6455 websocket.

Reference: plugins/out_websocket (websocket.c): HTTP/1.1 upgrade
handshake once per connection, then each flush's formatted payload goes
out as one websocket message (text frames for json/json_lines formats,
binary for msgpack), client-masked as the RFC requires. A failed
send reconnects and retries the chunk.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import os
import struct
from typing import List, Optional

from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..core.upstream import close_quietly
from .outputs_basic import format_json_lines

log = logging.getLogger("flb.websocket")

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def ws_frame(opcode: int, payload: bytes, mask: bool = True) -> bytes:
    """One FIN frame, client-masked (RFC 6455 §5.2-5.3)."""
    head = bytearray([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head.append(mask_bit | n)
    elif n < (1 << 16):
        head.append(mask_bit | 126)
        head += struct.pack("!H", n)
    else:
        head.append(mask_bit | 127)
        head += struct.pack("!Q", n)
    if not mask:
        return bytes(head) + payload
    key = os.urandom(4)
    head += key
    masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return bytes(head) + masked


def ws_accept_key(client_key: str) -> str:
    return base64.b64encode(hashlib.sha1(
        (client_key + _WS_GUID).encode()).digest()).decode()


@registry.register
class WebsocketOutput(OutputPlugin):
    name = "websocket"
    description = "websocket (RFC 6455) client output"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=80),
        ConfigMapEntry("uri", "str", default="/"),
        ConfigMapEntry("format", "str", default="msgpack",
                       desc="msgpack | json | json_lines"),
    ]

    def init(self, instance, engine) -> None:
        self._reader = None
        self._writer = None

    async def _connect(self) -> None:
        from ..core.tls import open_connection

        reader, writer = await open_connection(
            self.instance, self.host, self.port, timeout=10.0)
        key = base64.b64encode(os.urandom(16)).decode()
        writer.write((
            f"GET {self.uri or '/'} HTTP/1.1\r\n"
            f"Host: {self.host}:{self.port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n\r\n"
        ).encode())
        await io_deadline(writer.drain(), 10.0)
        status = await asyncio.wait_for(reader.readline(), 10.0)
        if b" 101 " not in status:
            writer.close()
            raise ConnectionError(f"upgrade refused: {status!r}")
        accept = None
        while True:
            line = await asyncio.wait_for(reader.readline(), 10.0)
            if line in (b"\r\n", b"\n", b""):
                break
            if line.lower().startswith(b"sec-websocket-accept:"):
                accept = line.split(b":", 1)[1].strip().decode()
        if accept != ws_accept_key(key):
            writer.close()
            raise ConnectionError("bad Sec-WebSocket-Accept")
        self._reader, self._writer = reader, writer

    def _payload(self, data: bytes, tag: str):
        fmt = (self.format or "msgpack").lower()
        if fmt == "json_lines":
            return OP_TEXT, format_json_lines(data).encode()
        if fmt == "json":
            import json

            from ..codec.events import decode_events
            from .outputs_basic import _json_default

            arr = [{"date": ev.ts_float, **ev.body}
                   for ev in decode_events(data)]
            return OP_TEXT, json.dumps(
                arr, default=_json_default).encode()
        return OP_BINARY, data  # msgpack passthrough

    async def _service_incoming(self) -> None:
        """Drain any server frames queued since the last flush: answer
        Ping with Pong, honor Close (raises so the caller reconnects) —
        a half-closed socket must not swallow the next chunk as 'OK'."""
        while True:
            try:
                head = await asyncio.wait_for(
                    self._reader.readexactly(2), 0.01)
            except asyncio.TimeoutError:
                return  # nothing pending
            opcode = head[0] & 0x0F
            n = head[1] & 0x7F
            if n == 126:
                n = struct.unpack(
                    "!H", await io_deadline(
                        self._reader.readexactly(2), 10.0))[0]
            elif n == 127:
                n = struct.unpack(
                    "!Q", await io_deadline(
                        self._reader.readexactly(8), 10.0))[0]
            payload = await io_deadline(
                self._reader.readexactly(n), 10.0) if n else b""
            if opcode == OP_PING:
                self._writer.write(ws_frame(OP_PONG, payload))
                await io_deadline(self._writer.drain(), 10.0)
            elif opcode == OP_CLOSE:
                raise ConnectionError("server sent Close")

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        opcode, payload = self._payload(data, tag)
        for attempt in (0, 1):  # one reconnect per flush
            try:
                if self._writer is None:
                    await self._connect()
                await self._service_incoming()
                self._writer.write(ws_frame(opcode, payload))
                await asyncio.wait_for(self._writer.drain(), 30.0)
                return FlushResult.OK
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError):
                if self._writer is not None:
                    close_quietly(self._writer)
                self._reader = self._writer = None
        return FlushResult.RETRY

    def exit(self) -> None:
        if self._writer is not None:
            try:
                self._writer.write(ws_frame(OP_CLOSE, b""))
                self._writer.close()
            except (OSError, RuntimeError):
                pass  # peer gone / loop closed at exit
            self._writer = None
