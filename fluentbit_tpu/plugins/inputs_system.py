"""System-telemetry inputs: cpu, mem, disk, netif, proc, thermal, health.

Reference: plugins/in_cpu (per-core /proc/stat deltas), plugins/in_mem
(/proc/meminfo), plugins/in_disk (/proc/diskstats deltas),
plugins/in_netif (/proc/net/dev deltas), plugins/in_proc (pid
liveness + /proc/<pid> stats), plugins/in_thermal
(/sys/class/thermal), plugins/in_health (TCP connect probe). All are
interval collectors emitting one record per tick.
"""

from __future__ import annotations

import os
import re
import socket
import time
from typing import Dict, Optional

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry


class _IntervalInput(InputPlugin):
    config_map = [
        ConfigMapEntry("interval_sec", "time", default="1"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.interval_sec or 1)

    def _emit(self, engine, body: dict) -> None:
        engine.input_log_append(
            self.instance, self.instance.tag,
            encode_event(body, now_event_time()), 1,
        )


@registry.register
class CpuInput(_IntervalInput):
    name = "cpu"
    description = "CPU utilization from /proc/stat deltas"
    collect_interval = 1.0

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        self._prev: Optional[Dict[str, tuple]] = None

    @staticmethod
    def _read() -> Dict[str, tuple]:
        out = {}
        with open("/proc/stat") as f:
            for line in f:
                if not line.startswith("cpu"):
                    break
                parts = line.split()
                vals = tuple(int(x) for x in parts[1:9])
                out[parts[0]] = vals
        return out

    def collect(self, engine) -> None:
        cur = self._read()
        prev, self._prev = self._prev, cur
        if prev is None:
            return
        body: Dict[str, float] = {}
        for name, vals in cur.items():
            pv = prev.get(name)
            if pv is None:
                continue
            deltas = [c - p for c, p in zip(vals, pv)]
            total = sum(deltas) or 1
            user, nice, system, idle = deltas[0], deltas[1], deltas[2], deltas[3]
            key = "cpu" if name == "cpu" else name
            body[f"{key}_p"] = round(100.0 * (total - idle) / total, 2)
            body[f"{key}.user_p" if key != "cpu" else "user_p"] = round(
                100.0 * (user + nice) / total, 2)
            body[f"{key}.system_p" if key != "cpu" else "system_p"] = round(
                100.0 * system / total, 2)
        self._emit(engine, body)


@registry.register
class MemInput(_IntervalInput):
    name = "mem"
    description = "memory usage from /proc/meminfo"
    collect_interval = 1.0

    def collect(self, engine) -> None:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                info[k] = int(rest.split()[0])
        total = info.get("MemTotal", 0)
        free = info.get("MemAvailable", info.get("MemFree", 0))
        st = info.get("SwapTotal", 0)
        sf = info.get("SwapFree", 0)
        self._emit(engine, {
            "Mem.total": total, "Mem.used": total - free, "Mem.free": free,
            "Swap.total": st, "Swap.used": st - sf, "Swap.free": sf,
        })


@registry.register
class DiskInput(_IntervalInput):
    name = "disk"
    description = "disk throughput from /proc/diskstats deltas"
    collect_interval = 1.0
    config_map = _IntervalInput.config_map + [
        ConfigMapEntry("dev_name", "str"),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        self._prev = None

    # whole disks only: sda yes, sda1 no; nvme0n1 yes, nvme0n1p1 no —
    # the kernel double-accounts sectors in partition AND parent rows
    _WHOLE_DISK = re.compile(
        r"^(?:sd[a-z]+|vd[a-z]+|xvd[a-z]+|nvme\d+n\d+)$"
    )

    def _read(self):
        rd = wr = 0
        with open("/proc/diskstats") as f:
            for line in f:
                parts = line.split()
                name = parts[2]
                if self.dev_name:
                    if name != self.dev_name:
                        continue
                elif not DiskInput._WHOLE_DISK.match(name):
                    continue
                rd += int(parts[5]) * 512
                wr += int(parts[9]) * 512
        return rd, wr

    def collect(self, engine) -> None:
        cur = self._read()
        prev, self._prev = self._prev, cur
        if prev is None:
            return
        self._emit(engine, {"read_size": cur[0] - prev[0],
                            "write_size": cur[1] - prev[1]})


@registry.register
class NetifInput(_IntervalInput):
    name = "netif"
    description = "interface throughput from /proc/net/dev deltas"
    collect_interval = 1.0
    config_map = _IntervalInput.config_map + [
        ConfigMapEntry("interface", "str"),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        self._prev = None

    def _read(self):
        out = {}
        with open("/proc/net/dev") as f:
            for line in f.readlines()[2:]:
                name, _, rest = line.partition(":")
                name = name.strip()
                if self.interface and name != self.interface:
                    continue
                parts = rest.split()
                out[name] = (int(parts[0]), int(parts[8]))
        return out

    def collect(self, engine) -> None:
        cur = self._read()
        prev, self._prev = self._prev, cur
        if prev is None:
            return
        body = {}
        for name, (rx, tx) in cur.items():
            pv = prev.get(name)
            if pv is None:
                continue
            body[f"{name}.rx.bytes"] = rx - pv[0]
            body[f"{name}.tx.bytes"] = tx - pv[1]
        if body:
            self._emit(engine, body)


@registry.register
class ProcInput(_IntervalInput):
    name = "proc"
    description = "process liveness + /proc/<pid> stats"
    collect_interval = 1.0
    config_map = _IntervalInput.config_map + [
        ConfigMapEntry("proc_name", "str"),
        ConfigMapEntry("alert", "bool", default=False),
        ConfigMapEntry("mem", "bool", default=True),
        ConfigMapEntry("fd", "bool", default=True),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not self.proc_name:
            raise ValueError("proc: proc_name is required")

    def _find_pid(self) -> Optional[int]:
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/comm") as f:
                    if f.read().strip() == self.proc_name:
                        return int(pid)
            except OSError:
                continue
        return None

    def collect(self, engine) -> None:
        pid = self._find_pid()
        alive = pid is not None
        if self.alert and alive:
            return  # alert mode: only emit when the process is gone
        body: Dict[str, object] = {"proc_name": self.proc_name,
                                   "alive": alive}
        if alive:
            body["pid"] = pid
            if self.mem:
                try:
                    with open(f"/proc/{pid}/status") as f:
                        for line in f:
                            if line.startswith(("VmRSS", "VmSize")):
                                k, _, rest = line.partition(":")
                                body[f"mem.{k}"] = int(rest.split()[0])
                except OSError:
                    pass
            if self.fd:
                try:
                    body["fd"] = len(os.listdir(f"/proc/{pid}/fd"))
                except OSError:
                    pass
        self._emit(engine, body)


@registry.register
class ThermalInput(_IntervalInput):
    name = "thermal"
    description = "temperatures from /sys/class/thermal"
    collect_interval = 1.0

    def collect(self, engine) -> None:
        base = "/sys/class/thermal"
        try:
            zones = sorted(z for z in os.listdir(base)
                           if z.startswith("thermal_zone"))
        except OSError:
            return
        for z in zones:
            try:
                with open(f"{base}/{z}/temp") as f:
                    temp = int(f.read().strip()) / 1000.0
                with open(f"{base}/{z}/type") as f:
                    ztype = f.read().strip()
            except OSError:
                continue
            self._emit(engine, {"name": z, "type": ztype, "temp": temp})


@registry.register
class HealthInput(_IntervalInput):
    name = "health"
    description = "TCP connect health probe"
    collect_interval = 1.0
    config_map = _IntervalInput.config_map + [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=80),
        ConfigMapEntry("alert", "bool", default=False),
        ConfigMapEntry("add_host", "bool", default=False),
        ConfigMapEntry("add_port", "bool", default=False),
    ]

    def collect(self, engine) -> None:
        """Collectors run ON the engine loop — the probe must not block
        it, so schedule an async connect when a loop is running (tests
        may call collect() synchronously, where blocking is fine)."""
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            self._probe_blocking(engine)
            return
        # hold a strong reference: the loop keeps only weak refs and a
        # GC pass could collect an in-flight probe
        tasks = getattr(self, "_probe_tasks", None)
        if tasks is None:
            tasks = self._probe_tasks = set()
        t = asyncio.ensure_future(self._probe_async(engine))
        tasks.add(t)
        t.add_done_callback(tasks.discard)

    async def _probe_async(self, engine) -> None:
        t0 = time.perf_counter()
        try:
            import asyncio

            _r, w = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port), 2.0
            )
            w.close()
            alive = True
        except Exception:
            alive = False
        self._emit_probe(engine, alive, t0)

    def _probe_blocking(self, engine) -> None:
        t0 = time.perf_counter()
        try:
            s = socket.create_connection((self.host, self.port), timeout=2)
            s.close()
            alive = True
        except OSError:
            alive = False
        self._emit_probe(engine, alive, t0)

    def _emit_probe(self, engine, alive: bool, t0: float) -> None:
        if self.alert and alive:
            return
        body: Dict[str, object] = {"alive": alive}
        if alive:
            body["check_time_ms"] = round(
                (time.perf_counter() - t0) * 1000, 3)
        if self.add_host:
            body["hostname"] = self.host
        if self.add_port:
            body["port"] = self.port
        self._emit(engine, body)
