"""Cloud outputs: azure (Log Analytics), kinesis_streams,
kinesis_firehose, stackdriver, bigquery.

Reference: plugins/out_azure (Log Analytics HTTP Data Collector API —
HMAC-SHA256 SharedKey signature, azure.c), plugins/out_kinesis_streams
+ out_kinesis_firehose (SigV4 JSON APIs PutRecords/PutRecordBatch),
plugins/out_stackdriver (6287 LoC, google service-account JWT →
oauth2 token → entries.write) and plugins/out_bigquery (insertAll).
The Google pair signs RS256 JWTs with the `cryptography` OpenSSL
binding (the reference uses flb_oauth2 + openssl).
"""

from __future__ import annotations

import asyncio
import base64
import datetime
import hashlib
import hmac
import json
import time
from typing import Any, Dict, List, Optional

from ..codec.events import decode_events
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..utils import aws as _aws
from .outputs_aws import _http_request
from .outputs_http_based import _HttpDeliveryOutput, _dumps


@registry.register
class AzureOutput(_HttpDeliveryOutput):
    """plugins/out_azure: Log Analytics Data Collector API."""

    name = "azure"
    config_map = [
        ConfigMapEntry("customer_id", "str"),
        ConfigMapEntry("shared_key", "str"),
        ConfigMapEntry("log_type", "str", default="fluentbit"),
        ConfigMapEntry("host", "str"),
        ConfigMapEntry("port", "int", default=443),
        ConfigMapEntry("time_key", "str", default="@timestamp"),
    ]

    def init(self, instance, engine) -> None:
        if not self.customer_id or not self.shared_key:
            raise ValueError("azure: customer_id + shared_key required")
        if not self.host:
            self.host = f"{self.customer_id}.ods.opinsights.azure.com"

    def _uri(self) -> str:
        return "/api/logs?api-version=2016-04-01"

    def _signature(self, date: str, length: int) -> str:
        to_sign = (f"POST\n{length}\napplication/json\n"
                   f"x-ms-date:{date}\n/api/logs")
        digest = hmac.new(base64.b64decode(self.shared_key),
                          to_sign.encode(), hashlib.sha256).digest()
        return (f"SharedKey {self.customer_id}:"
                f"{base64.b64encode(digest).decode()}")

    def format(self, data: bytes, tag: str) -> bytes:
        out = []
        for ev in decode_events(data):
            entry = dict(ev.body) if isinstance(ev.body, dict) else {}
            entry[self.time_key] = datetime.datetime.fromtimestamp(
                ev.ts_float, datetime.timezone.utc
            ).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
            out.append(entry)
        return _dumps(out).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        body = self.format(data, tag)
        date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT")
        return await self._post(body, extra_headers=[
            f"Log-Type: {self.log_type}",
            f"x-ms-date: {date}",
            f"Authorization: {self._signature(date, len(body))}",
        ])


class _KinesisBase(OutputPlugin):
    service = "kinesis"
    target: str = ""

    def init(self, instance, engine) -> None:
        self._creds = _aws.get_credentials() or _aws.Credentials("", "")

    def _endpoint(self):
        ep = self.endpoint or \
            f"{self.service_host}.{self.region}.amazonaws.com"
        ep = ep.replace("http://", "").replace("https://", "")
        host, _, port = ep.partition(":")
        return host, int(port or 80)

    def _records(self, data: bytes) -> List[dict]:  # pragma: no cover
        raise NotImplementedError

    def _body(self, data: bytes) -> dict:  # pragma: no cover
        raise NotImplementedError

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        body = _dumps(self._body(data)).encode()
        host, port = self._endpoint()
        url = f"http://{host}:{port}/"
        extra = {"X-Amz-Target": self.target,
                 "Content-Type": "application/x-amz-json-1.1"}
        headers = _aws.sigv4_headers("POST", url, self.region,
                                     self.service, body, self._creds,
                                     headers=extra)
        headers.update(extra)
        try:
            status, _h, _b = await _http_request(self.instance, host, port,
                                             "POST", "/", headers, body)
        except (OSError, asyncio.TimeoutError, ValueError, IndexError):
            return FlushResult.RETRY
        if 200 <= status < 300:
            return FlushResult.OK
        return FlushResult.RETRY if status >= 500 else FlushResult.ERROR


@registry.register
class KinesisStreamsOutput(_KinesisBase):
    name = "kinesis_streams"
    description = "Amazon Kinesis Data Streams (PutRecords)"
    service = "kinesis"
    service_host = "kinesis"
    target = "Kinesis_20131202.PutRecords"
    config_map = [
        ConfigMapEntry("stream", "str"),
        ConfigMapEntry("region", "str", default="us-east-1"),
        ConfigMapEntry("endpoint", "str"),
        ConfigMapEntry("partition_key", "str"),
        # per-record codec (reference flb_aws_compress: gzip|zstd|snappy,
        # out_kinesis_streams/kinesis.c "compression" option)
        ConfigMapEntry("compression", "str"),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not self.stream:
            raise ValueError("kinesis_streams: stream is required")
        algo = (self.compression or "").lower()
        if algo and algo not in ("gzip", "zstd", "snappy"):
            raise ValueError(
                f"kinesis_streams: unknown compression {self.compression!r}")
        if algo:
            from ..utils import compression_available
            if not compression_available(algo):
                raise ValueError(
                    f"kinesis_streams: {algo} codec unavailable on "
                    "this host")

    def _encode_record(self, blob: bytes) -> bytes:
        algo = (self.compression or "").lower()
        if algo:
            from ..utils import compress
            blob = compress(algo, blob)
        return blob

    def _body(self, data: bytes) -> dict:
        records = []
        for i, ev in enumerate(decode_events(data)):
            pk = "0"
            if self.partition_key and isinstance(ev.body, dict):
                pk = str(ev.body.get(self.partition_key, i))
            records.append({
                "Data": base64.b64encode(self._encode_record(
                    (_dumps(ev.body) + "\n").encode())).decode(),
                "PartitionKey": pk,
            })
        return {"StreamName": self.stream, "Records": records}


@registry.register
class KinesisFirehoseOutput(_KinesisBase):
    name = "kinesis_firehose"
    description = "Amazon Kinesis Firehose (PutRecordBatch)"
    service = "firehose"
    service_host = "firehose"
    target = "Firehose_20150804.PutRecordBatch"
    config_map = [
        ConfigMapEntry("delivery_stream", "str"),
        ConfigMapEntry("region", "str", default="us-east-1"),
        ConfigMapEntry("endpoint", "str"),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not self.delivery_stream:
            raise ValueError("kinesis_firehose: delivery_stream is required")

    def _body(self, data: bytes) -> dict:
        return {
            "DeliveryStreamName": self.delivery_stream,
            "Records": [
                {"Data": base64.b64encode(
                    (_dumps(ev.body) + "\n").encode()).decode()}
                for ev in decode_events(data)
            ],
        }


# --------------------------------------------------------------- google

def _rs256_jwt(sa: dict, scope: str, now: Optional[float] = None) -> str:
    """Service-account assertion (flb_oauth2 + flb_jwt equivalent) —
    RS256 via the cryptography OpenSSL binding."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    def b64(obj) -> bytes:
        raw = obj if isinstance(obj, bytes) else \
            json.dumps(obj, separators=(",", ":")).encode()
        return base64.urlsafe_b64encode(raw).rstrip(b"=")

    now = int(now or time.time())
    header = {"alg": "RS256", "typ": "JWT"}
    claims = {"iss": sa["client_email"], "scope": scope,
              "aud": sa.get("token_uri",
                            "https://oauth2.googleapis.com/token"),
              "iat": now, "exp": now + 3600}
    signing_input = b64(header) + b"." + b64(claims)
    key = serialization.load_pem_private_key(
        sa["private_key"].encode(), password=None)
    sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    return (signing_input + b"." + b64(sig)).decode()


class _GoogleOutput(OutputPlugin):
    """Shared service-account auth: exchange the RS256 assertion for a
    bearer token at token_uri (plain HTTP in tests via endpoint)."""

    scope = "https://www.googleapis.com/auth/cloud-platform"

    def init(self, instance, engine) -> None:
        if not self.google_service_credentials:
            raise ValueError(
                f"{self.name}: google_service_credentials is required"
            )
        with open(self.google_service_credentials) as f:
            self._sa = json.load(f)
        self._token: Optional[str] = None
        self._token_exp = 0.0

    @staticmethod
    def _split_url(url: str):
        """(host, port, path, use_tls) — https implies 443 + TLS; a
        bare host:port (test/dev endpoints) stays plain HTTP."""
        scheme, _, rest = url.partition("://")
        if not rest:
            scheme, rest = "http", url
        hostport, _, path = rest.partition("/")
        host, _, port = hostport.partition(":")
        tls = scheme == "https"
        return host, int(port or (443 if tls else 80)), "/" + path, tls

    async def _bearer(self) -> Optional[str]:
        if self._token and time.time() < self._token_exp - 60:
            return self._token
        assertion = _rs256_jwt(self._sa, self.scope)
        token_uri = self._sa.get("token_uri",
                                 "https://oauth2.googleapis.com/token")
        host, port, path, tls = self._split_url(token_uri)
        body = ("grant_type=urn%3Aietf%3Aparams%3Aoauth%3A"
                "grant-type%3Ajwt-bearer&assertion=" + assertion).encode()
        try:
            status, _head, resp = await _http_request(
                self.instance, host, port, "POST", path,
                {"Content-Type": "application/x-www-form-urlencoded"},
                body, quote_path=False, use_tls=tls,
            )
            if status != 200:
                return None
            tok = json.loads(resp)
            self._token = tok["access_token"]
            self._token_exp = time.time() + float(tok.get("expires_in",
                                                          3600))
            return self._token
        except (OSError, ValueError, KeyError, asyncio.TimeoutError):
            return None

    async def _post_json(self, host: str, port: int, path: str,
                         payload: dict, use_tls: bool) -> FlushResult:
        token = await self._bearer()
        if token is None:
            return FlushResult.RETRY
        body = _dumps(payload).encode()
        headers = {"Content-Type": "application/json",
                   "Authorization": f"Bearer {token}"}
        try:
            status, _h, _b = await _http_request(
                self.instance, host, port, "POST", path, headers, body,
                quote_path=False, use_tls=use_tls,
            )
        except (OSError, asyncio.TimeoutError, ValueError, IndexError):
            return FlushResult.RETRY
        if 200 <= status < 300:
            return FlushResult.OK
        return FlushResult.RETRY if status >= 500 else FlushResult.ERROR


@registry.register
class StackdriverOutput(_GoogleOutput):
    name = "stackdriver"
    description = "Google Cloud Logging (entries.write)"
    scope = "https://www.googleapis.com/auth/logging.write"
    config_map = [
        ConfigMapEntry("google_service_credentials", "str"),
        ConfigMapEntry("resource", "str", default="global"),
        ConfigMapEntry("endpoint", "str"),
        ConfigMapEntry("severity_key", "str", default="severity"),
    ]

    def format(self, data: bytes, tag: str) -> dict:
        entries = []
        for ev in decode_events(data):
            body = dict(ev.body) if isinstance(ev.body, dict) else {}
            sev = str(body.pop(self.severity_key or "severity",
                               "DEFAULT")).upper()
            ts = datetime.datetime.fromtimestamp(
                ev.ts_float, datetime.timezone.utc
            ).strftime("%Y-%m-%dT%H:%M:%S.%fZ")
            entries.append({
                "logName": f"projects/"
                           f"{self._sa.get('project_id', 'project')}"
                           f"/logs/{tag}",
                "resource": {"type": self.resource},
                "timestamp": ts,
                "severity": sev,
                "jsonPayload": body,
            })
        return {"entries": entries}

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        host, port, _p, tls = self._split_url(
            self.endpoint or "https://logging.googleapis.com"
        )
        return await self._post_json(host, port, "/v2/entries:write",
                                     self.format(data, tag), tls)


@registry.register
class BigqueryOutput(_GoogleOutput):
    name = "bigquery"
    description = "Google BigQuery (tabledata.insertAll)"
    scope = "https://www.googleapis.com/auth/bigquery.insertdata"
    config_map = [
        ConfigMapEntry("google_service_credentials", "str"),
        ConfigMapEntry("project_id", "str"),
        ConfigMapEntry("dataset_id", "str"),
        ConfigMapEntry("table_id", "str"),
        ConfigMapEntry("endpoint", "str"),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not (self.dataset_id and self.table_id):
            raise ValueError("bigquery: dataset_id + table_id required")

    def format(self, data: bytes, tag: str) -> dict:
        return {"rows": [{"json": ev.body} for ev in decode_events(data)
                         if isinstance(ev.body, dict)]}

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        project = self.project_id or self._sa.get("project_id", "project")
        path = (f"/bigquery/v2/projects/{project}/datasets/"
                f"{self.dataset_id}/tables/{self.table_id}/insertAll")
        host, port, _p, tls = self._split_url(
            self.endpoint or "https://bigquery.googleapis.com"
        )
        return await self._post_json(host, port, path,
                                     self.format(data, tag), tls)


@registry.register
class AzureBlobOutput(_HttpDeliveryOutput):
    """plugins/out_azure_blob (6834 LoC): Blob Storage delivery with the
    Storage SharedKey signature scheme. ``blob_type blockblob`` puts one
    blob per chunk (tag/timestamp-named); ``appendblob`` creates the
    blob once per tag then appends each chunk (the reference's two
    modes). Container auto-create is attempted once."""

    name = "azure_blob"
    config_map = [
        ConfigMapEntry("account_name", "str"),
        ConfigMapEntry("shared_key", "str"),
        ConfigMapEntry("container_name", "str", default="fluentbit"),
        ConfigMapEntry("blob_type", "str", default="appendblob"),
        ConfigMapEntry("path", "str", default=""),
        ConfigMapEntry("auto_create_container", "bool", default=True),
        ConfigMapEntry("host", "str"),
        ConfigMapEntry("port", "int", default=443),
        ConfigMapEntry("emulator_mode", "bool", default=False,
                       desc="no TLS default + host:port endpoints"),
    ]

    def init(self, instance, engine) -> None:
        if not self.account_name or not self.shared_key:
            raise ValueError(
                "azure_blob: account_name + shared_key required")
        if not self.host:
            self.host = f"{self.account_name}.blob.core.windows.net"
        if not self.emulator_mode and "tls" not in instance.properties:
            instance.set("tls", "on")  # reference hardcodes FLB_IO_TLS
        self._container_ready = False
        self._append_blobs = set()

    # -- SharedKey (Storage flavor: canonical headers + resource) --

    def _auth(self, verb: str, path: str, length: int,
              ms_headers: Dict[str, str], query: Dict[str, str]) -> str:
        canon_headers = "".join(
            f"{k}:{v}\n" for k, v in sorted(ms_headers.items()))
        canon_resource = f"/{self.account_name}{path}"
        for k in sorted(query):
            canon_resource += f"\n{k}:{query[k]}"
        to_sign = (f"{verb}\n\n\n{length if length else ''}\n\n"
                   f"application/octet-stream\n\n\n\n\n\n\n"
                   f"{canon_headers}{canon_resource}")
        digest = hmac.new(base64.b64decode(self.shared_key),
                          to_sign.encode(), hashlib.sha256).digest()
        return (f"SharedKey {self.account_name}:"
                f"{base64.b64encode(digest).decode()}")

    def _content_type(self) -> str:
        return "application/octet-stream"

    async def _req(self, verb: str, path: str, query: Dict[str, str],
                   body: bytes) -> FlushResult:
        date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT")
        ms = {"x-ms-date": date, "x-ms-version": "2021-08-06"}
        if verb == "PUT" and query.get("comp") is None:
            ms["x-ms-blob-type"] = (
                "AppendBlob" if (self.blob_type or "").lower()
                == "appendblob" else "BlockBlob")
        uri = path
        if query:
            uri += "?" + "&".join(f"{k}={v}" for k, v in query.items())
        headers = [f"{k}: {v}" for k, v in ms.items()]
        headers.append(
            f"Authorization: "
            f"{self._auth(verb, path, len(body), ms, query)}")
        # the shared delivery transport handles PUT via the verb
        # override; 409 (container/blob already exists) is success
        return await self._post(body, extra_headers=headers, uri=uri,
                                verb=verb, ok_statuses=(409,))

    def _blob_path(self, tag: str) -> str:
        prefix = (self.path or "").strip("/")
        name = tag.replace("*", "_")
        if (self.blob_type or "").lower() != "appendblob":
            # ms timestamp + per-instance sequence: two flushes of one
            # tag in the same millisecond must not overwrite each other.
            # itertools.count.__next__ is atomic — with `workers N`
            # flushes run on parallel OS threads and a bare
            # read-modify-write could mint duplicate names
            counter = getattr(self, "_seq_counter", None)
            if counter is None:
                import itertools
                counter = self.__dict__.setdefault(
                    "_seq_counter", itertools.count(1))
            name += f".{int(time.time() * 1000)}.{next(counter)}"
        parts = [self.container_name] + \
            ([prefix] if prefix else []) + [name + ".log"]
        base = "/" + "/".join(parts)
        # Azurite/emulator uses path-style addressing: the account name
        # leads the path (http://host:port/{account}/{container}/...)
        if self.emulator_mode:
            return f"/{self.account_name}{base}"
        return base

    def format(self, data: bytes, tag: str) -> bytes:
        from .outputs_basic import format_json_lines

        return format_json_lines(data).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        body = self.format(data, tag)
        if self.auto_create_container and not self._container_ready:
            cpath = f"/{self.container_name}"
            if self.emulator_mode:  # path-style addressing
                cpath = f"/{self.account_name}{cpath}"
            r = await self._req("PUT", cpath,
                                {"restype": "container"}, b"")
            if r == FlushResult.RETRY:
                return r
            self._container_ready = True
        path = self._blob_path(tag)
        if (self.blob_type or "").lower() == "appendblob":
            if path not in self._append_blobs:
                r = await self._req("PUT", path, {}, b"")
                if r != FlushResult.OK:
                    return r
                self._append_blobs.add(path)
            return await self._req("PUT", path, {"comp": "appendblock"},
                                   body)
        return await self._req("PUT", path, {}, body)
