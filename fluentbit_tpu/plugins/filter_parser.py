"""filter_parser — apply a named parser to a record field.

Reference: plugins/filter_parser/filter_parser.c. For each record, look
up ``key_name`` (or a record-accessor path when it starts with ``$``,
:122-126), run the configured parsers in order on its string value
(:268-303); on first success the parsed map replaces the body,
``reserve_data`` appends the other original fields (:237),
``preserve_key`` keeps the parsed source key (:238-240); a parsed
non-zero time overrides the record timestamp; on failure the record
passes through untouched. With an RA path, the reference keeps ALL
original fields under reserve_data (the matched kv is not identified in
that branch) — mirrored here.

Divergence note: the reference appends reserved originals after the
parsed fields in the msgpack map, allowing duplicate keys (first wins on
record-accessor lookups). Python dicts cannot hold duplicates, so on key
collision the parsed value wins — the same value a reference RA lookup
would return.

Device path: with a single DFA-expressible regex parser and a large
append, the match decision runs vectorized on device
(fluentbit_tpu.ops.grep) and capture extraction runs only for matching
records (match-then-extract two-pass).
"""

from __future__ import annotations

from typing import List, Optional

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor


def _to_str(v) -> Optional[str]:
    if isinstance(v, str):
        return v
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return None  # msgpackobj2char: only string/bin values are parseable


@registry.register
class ParserFilter(FilterPlugin):
    name = "parser"
    description = "parse a field with a named parser"
    config_map = [
        ConfigMapEntry("key_name", "str", desc="field to parse"),
        ConfigMapEntry("parser", "str", multiple=True,
                       desc="parser name (may repeat; tried in order)"),
        ConfigMapEntry("reserve_data", "bool", default=False,
                       desc="keep the other original fields"),
        ConfigMapEntry("preserve_key", "bool", default=False,
                       desc="keep the parsed source key"),
        ConfigMapEntry("tpu.enable", "bool", default=True,
                       desc="device match prefilter when the parser allows"),
        ConfigMapEntry("tpu_batch_records", "int", default=64),
        ConfigMapEntry("tpu_max_record_len", "int", default=512),
    ]

    def init(self, instance, engine) -> None:
        if not self.key_name:
            raise ValueError("parser filter requires Key_Name")
        if not self.parser:
            raise ValueError("parser filter requires at least one Parser")
        self.parsers = []
        for pname in self.parser:
            p = (engine.parsers if engine is not None else {}).get(pname)
            if p is None:
                raise ValueError(f"parser filter: unknown parser {pname!r}")
            self.parsers.append(p)
        self.ra: Optional[RecordAccessor] = None
        if self.key_name.startswith("$"):
            self.ra = RecordAccessor(self.key_name)
        # device prefilter: single regex parser with a compiled DFA
        self._prefilter = None
        if (
            self.tpu_enable
            and len(self.parsers) == 1
            and self.parsers[0].fmt == "regex"
            and self.parsers[0].regex.dfa is not None
        ):
            try:
                from ..ops import device
                from ..ops.grep import program_for

                self._prefilter = program_for(
                    (self.parsers[0].regex.pattern,), self.tpu_max_record_len
                )
                device.wait()  # bounded; CPU path serves until attached
                self._prefilter.try_ready()
            except Exception:
                self._prefilter = None

    # -- per-record semantics --

    def _get_value(self, body: dict) -> Optional[str]:
        if self.ra is not None:
            return _to_str(self.ra.get(body))
        v = body.get(self.key_name) if isinstance(body, dict) else None
        return _to_str(v)

    def _apply(self, ev: LogEvent, value: str) -> Optional[LogEvent]:
        """Try the parsers in order; build the replacement event."""
        for p in self.parsers:
            got = p.do(value)
            if got is None:
                continue
            fields, ts = got
            body = dict(fields)
            if self.reserve_data:
                for k, v in ev.body.items():
                    if (
                        self.ra is None
                        and k == self.key_name
                        and not self.preserve_key
                    ):
                        continue
                    body.setdefault(k, v)
            elif self.preserve_key and self.ra is None:
                body.setdefault(self.key_name, ev.body.get(self.key_name))
            new_ts = ev.timestamp if (ts is None or ts == 0) else ts
            return LogEvent(
                timestamp=new_ts, body=body, metadata=ev.metadata, raw=None
            )
        return None

    def _device_match_mask(self, values: List[Optional[str]]):
        """Vectorized match prefilter; None → row handled on CPU."""
        import numpy as np

        from ..ops.batch import assemble, bucket_size

        vals = [
            v.encode("utf-8") if isinstance(v, str) else None for v in values
        ]
        staged = assemble(vals, self.tpu_max_record_len, bucket_size(len(vals)))
        batch = np.stack([staged.batch])
        lengths = np.stack([staged.lengths])
        mask = np.array(self._prefilter.match(batch, lengths)[0, : len(vals)])
        rx = self.parsers[0].regex
        for i in staged.overflow:
            mask[i] = rx.match(vals[i])
        return mask

    def filter(self, events: list, tag: str, engine) -> tuple:
        values = [
            self._get_value(ev.body) if isinstance(ev.body, dict) else None
            for ev in events
        ]
        from ..ops import device

        mask = None
        # platform gate first (as in filter_grep/rewrite_tag): the
        # prefilter kernel only pays for itself on a real accelerator
        if (self._prefilter is not None
                and len(events) >= self.tpu_batch_records
                and device.platform() not in (None, "cpu")
                and self._prefilter.try_ready()):
            mask = self._device_match_mask(values)
        out: List[LogEvent] = []
        modified = False
        for i, ev in enumerate(events):
            v = values[i]
            if v is None or (mask is not None and not mask[i]):
                out.append(ev)
                continue
            new_ev = self._apply(ev, v)
            if new_ev is None:
                out.append(ev)
            else:
                out.append(new_ev)
                modified = True
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)
