"""filter_parser — apply a named parser to a record field.

Reference: plugins/filter_parser/filter_parser.c. For each record, look
up ``key_name`` (or a record-accessor path when it starts with ``$``,
:122-126), run the configured parsers in order on its string value
(:268-303); on first success the parsed map replaces the body,
``reserve_data`` appends the other original fields (:237),
``preserve_key`` keeps the parsed source key (:238-240); a parsed
non-zero time overrides the record timestamp; on failure the record
passes through untouched. With an RA path, the reference keeps ALL
original fields under reserve_data (the matched kv is not identified in
that branch) — mirrored here.

Divergence note: the reference appends reserved originals after the
parsed fields in the msgpack map, allowing duplicate keys (first wins on
record-accessor lookups). Python dicts cannot hold duplicates, so on key
collision the parsed value wins — the same value a reference RA lookup
would return.

Device path: with a single DFA-expressible regex parser and a large
append, the match decision runs vectorized on device
(fluentbit_tpu.ops.grep) and capture extraction runs only for matching
records (match-then-extract two-pass).

Batched fast path (``process_batch``): on the engine's raw ingest path
whole chunks bypass per-record Python entirely —

- json parser (plain key, defaults): the fbtpu_codec C extension
  transcodes each record's JSON field straight to msgpack
  (``parser_json_batch``), byte-exact with json.loads → pack_event;
- regex parser: the native one-pass DFA (fluentbit_tpu.native) computes
  the match mask off chunk bytes and capture extraction runs only for
  matching records.

Exotic options (reserve_data, preserve_key, time_format, record-
accessor keys, multiple parsers, types) decline to the per-record path
— identical output either way, just slower.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from .. import failpoints as _fp
from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor


log = logging.getLogger("flb")


def _to_str(v) -> Optional[str]:
    if isinstance(v, str):
        return v
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return None  # msgpackobj2char: only string/bin values are parseable


@registry.register
class ParserFilter(FilterPlugin):
    name = "parser"
    description = "parse a field with a named parser"
    # the batched path is pure (parsers immutable after init, no
    # cross-record state): chains of these may ingest in parallel
    # under per-input locks
    thread_safe_raw = True
    config_map = [
        ConfigMapEntry("key_name", "str", desc="field to parse"),
        ConfigMapEntry("parser", "str", multiple=True,
                       desc="parser name (may repeat; tried in order)"),
        ConfigMapEntry("reserve_data", "bool", default=False,
                       desc="keep the other original fields"),
        ConfigMapEntry("preserve_key", "bool", default=False,
                       desc="keep the parsed source key"),
        ConfigMapEntry("tpu.enable", "bool", default=True,
                       desc="device match prefilter when the parser allows"),
        ConfigMapEntry("tpu_batch_records", "int", default=64),
        ConfigMapEntry("tpu_max_record_len", "int", default=512),
        ConfigMapEntry("tpu_approx", "bool", default=False,
                       desc="approximate (reduced) DFA for the batched "
                            "match mask; the Python regex recheck "
                            "keeps output byte-identical (also "
                            "FBTPU_DFA_APPROX)"),
        ConfigMapEntry("tpu_approx_states", "int", default=64),
    ]

    def init(self, instance, engine) -> None:
        if not self.key_name:
            raise ValueError("parser filter requires Key_Name")
        if not self.parser:
            raise ValueError("parser filter requires at least one Parser")
        self.parsers = []
        for pname in self.parser:
            p = (engine.parsers if engine is not None else {}).get(pname)
            if p is None:
                raise ValueError(f"parser filter: unknown parser {pname!r}")
            self.parsers.append(p)
        self.ra: Optional[RecordAccessor] = None
        if self.key_name.startswith("$"):
            self.ra = RecordAccessor(self.key_name)
        # device prefilter: single regex parser with a compiled DFA
        self._prefilter = None
        if (
            self.tpu_enable
            and len(self.parsers) == 1
            and self.parsers[0].fmt == "regex"
            and self.parsers[0].regex.dfa is not None
        ):
            try:
                from ..ops import device
                from ..ops.grep import program_for

                self._prefilter = program_for(
                    (self.parsers[0].regex.pattern,), self.tpu_max_record_len
                )
                device.wait()  # bounded; CPU path serves until attached
                self._prefilter.try_ready()
            except Exception:
                log.debug("parser device prefilter unavailable; "
                          "host path serves", exc_info=True)
                self._prefilter = None

        # batched raw-path mode (process_batch): "json" = whole-chunk C
        # transcode, "regex" = native DFA mask + captures for matches
        # only. Option combinations outside these shapes keep the
        # per-record path (bit-exact, just slower).
        self._batch_mode = None
        self._batch_key = None
        self._batch_tables = None
        p0 = self.parsers[0]
        if self.ra is None and len(self.parsers) == 1 and self.key_name:
            key = self.key_name.encode("utf-8")
            if (
                p0.fmt == "json"
                and p0.time_format is None
                and not self.reserve_data
                and not self.preserve_key
            ):
                from ..codec import _native_codec

                mod = _native_codec.load()
                if mod is not None and hasattr(mod, "parser_json_batch"):
                    self._batch_mode = "json"
                    self._batch_key = key
            elif p0.fmt == "regex" and p0.regex.dfa is not None:
                from .. import native as _native

                if _native.available():
                    try:
                        # fbtpu-shrink approximate mode: the batched
                        # mask is ALREADY a mask→exact-recheck shape
                        # (the Python regex with captures runs only on
                        # admitted records, and a failed parse passes
                        # the record through untouched — identical to
                        # a mask miss), so an over-approximated mask
                        # DFA is a drop-in: smaller hot table, byte-
                        # identical output
                        mask_dfa = p0.regex.dfa
                        from ..regex.dfa import (approx_env_states,
                                                 approx_reduce)

                        env_target = approx_env_states(
                            self.tpu_approx_states)
                        if self.tpu_approx or env_target is not None:
                            target = env_target if env_target is not None \
                                else self.tpu_approx_states
                            reduced = approx_reduce(mask_dfa, target)
                            if reduced is not None:
                                log.info(
                                    "parser approx mask: S %d -> %d "
                                    "(depth %d)", mask_dfa.n_states,
                                    reduced.n_states,
                                    reduced.shrink.approx_depth)
                                mask_dfa = reduced
                        self._batch_tables = _native.GrepTables(
                            [(key, mask_dfa)])
                        self._batch_mode = "regex"
                        self._batch_key = key
                    except Exception:
                        log.warning(
                            "parser native table build failed; batched "
                            "regex fast path disabled", exc_info=True)
                        self._batch_tables = None

    # -- per-record semantics --

    def _get_value(self, body: dict) -> Optional[str]:
        if self.ra is not None:
            return _to_str(self.ra.get(body))
        v = body.get(self.key_name) if isinstance(body, dict) else None
        return _to_str(v)

    def _apply(self, ev: LogEvent, value: str) -> Optional[LogEvent]:
        """Try the parsers in order; build the replacement event."""
        for p in self.parsers:
            got = p.do(value)
            if got is None:
                continue
            fields, ts = got
            body = dict(fields)
            if self.reserve_data:
                for k, v in ev.body.items():
                    if (
                        self.ra is None
                        and k == self.key_name
                        and not self.preserve_key
                    ):
                        continue
                    body.setdefault(k, v)
            elif self.preserve_key and self.ra is None:
                body.setdefault(self.key_name, ev.body.get(self.key_name))
            new_ts = ev.timestamp if (ts is None or ts == 0) else ts
            return LogEvent(
                timestamp=new_ts, body=body, metadata=ev.metadata, raw=None
            )
        return None

    def _device_match_mask(self, values: List[Optional[str]]):
        """Vectorized match prefilter; None → row handled on CPU."""
        import numpy as np

        from ..ops.batch import assemble, bucket_size

        vals = [
            v.encode("utf-8") if isinstance(v, str) else None for v in values
        ]
        staged = assemble(
            vals, self.tpu_max_record_len,
            bucket_size(len(vals), max_len=self.tpu_max_record_len))
        batch = np.stack([staged.batch])
        lengths = np.stack([staged.lengths])
        mask = np.array(self._prefilter.match(batch, lengths)[0, : len(vals)])
        rx = self.parsers[0].regex
        for i in staged.overflow:
            mask[i] = rx.match(vals[i])
        return mask

    # -- batched raw-chunk execution (engine process_batch hook) --

    def can_process_batch(self) -> bool:
        return self._batch_mode is not None

    def process_batch(self, chunk):
        if self._batch_mode == "json":
            return self._process_batch_json(chunk)
        return self._process_batch_regex(chunk)

    def _process_batch_json(self, chunk):
        """Whole-chunk JSON→msgpack transcode in C — byte-exact with
        json.loads → dict → pack_event per record (differentially
        fuzzed; tests/test_batch_filters.py). FallbackError means some
        record is outside the fast set (legacy framing, bin values,
        bigints, invalid UTF-8): decline and let the per-record path
        produce the identical-or-defined behavior."""
        from ..codec import _native_codec

        if _fp.ACTIVE:
            try:
                _fp.fire("codec.fallback")
            except _fp.FailpointError:
                # forced decline: the per-record path takes over — the
                # contract says output stays bit-exact and the decline
                # shows in fluentbit_filter_batch_declines_total
                return None
        mod = _native_codec.load()
        if mod is None:
            return None
        data = chunk.as_bytes()
        try:
            out, n, parsed = mod.parser_json_batch(data, self._batch_key)
        except mod.FallbackError:
            return None
        if parsed == 0:
            return (n, data, n)  # nothing parseable: zero-copy
        return (n, out, n)

    def _process_batch_regex(self, chunk):
        """Native one-pass DFA mask over chunk bytes; the regex (with
        captures) runs only for records the mask admits — mask-false
        records skip the Python regex entirely (the DFA is the
        bit-exact twin of the fallback engine, same contract as
        filter_grep's raw path)."""
        from .. import native
        from ..codec.events import decode_events, reencode_event

        data = chunk.as_bytes()
        got = native.grep_match(data, self._batch_tables, n_hint=chunk.n)
        if got is None:
            return None
        mask, _offsets, n = got
        row = mask[0]
        try:
            events = decode_events(data)
        except ValueError:
            return None
        if len(events) != n:
            return None  # native/codec walk disagreement: decline
        out = bytearray()
        modified = False
        for i, ev in enumerate(events):
            v = None
            body = ev.body
            if isinstance(body, dict):
                raw_v = body.get(self.key_name)
                if isinstance(raw_v, bytes):
                    # bytes values never stage into the native mask —
                    # they decode (errors="replace") and always parse
                    v = raw_v.decode("utf-8", "replace")
                elif isinstance(raw_v, str) and row[i]:
                    v = raw_v
            new_ev = self._apply(ev, v) if v is not None else None
            if new_ev is None:
                out += ev.raw if ev.raw is not None \
                    else reencode_event(ev)
            else:
                out += reencode_event(new_ev)
                modified = True
        if not modified:
            return (n, data, n)
        return (n, bytes(out), n)

    def filter(self, events: list, tag: str, engine) -> tuple:
        values = [
            self._get_value(ev.body) if isinstance(ev.body, dict) else None
            for ev in events
        ]
        from ..ops import device

        mask = None
        # platform gate first (as in filter_grep/rewrite_tag): the
        # prefilter kernel only pays for itself on a real accelerator
        if (self._prefilter is not None
                and len(events) >= self.tpu_batch_records
                and device.platform() not in (None, "cpu")
                and self._prefilter.try_ready()):
            mask = self._device_match_mask(values)
        out: List[LogEvent] = []
        modified = False
        for i, ev in enumerate(events):
            v = values[i]
            if v is None or (mask is not None and not mask[i]):
                out.append(ev)
                continue
            new_ev = self._apply(ev, v)
            if new_ev is None:
                out.append(ev)
            else:
                out.append(new_ev)
                modified = True
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)
