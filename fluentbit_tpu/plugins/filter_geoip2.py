"""filter_geoip2 on the from-scratch MMDB reader (utils/mmdb.py).

Reference: plugins/filter_geoip2/geoip2.c (libmaxminddb). Properties:
``database`` (mmdb path), ``lookup_key`` (multiple — record keys whose
string values are IPs), ``record`` "KEY LOOKUP_KEY %{dot.path}"
(multiple — geoip2.c:85-108). Every configured record key is appended
to EVERY record; lookup misses, absent paths, and map/array results
append null (geoip2.c:226-276) so the output shape is stable.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..utils.mmdb import MMDBError, MMDBReader

log = logging.getLogger("flb.geoip2")


@registry.register
class Geoip2Filter(FilterPlugin):
    name = "geoip2"
    description = "GeoIP2 enrichment from a MaxMind DB file"
    config_map = [
        ConfigMapEntry("database", "str"),
        ConfigMapEntry("lookup_key", "slist", multiple=True),
        ConfigMapEntry("record", "slist", multiple=True,
                       slist_max_split=2),
    ]

    def init(self, instance, engine) -> None:
        if not self.database:
            raise ValueError("geoip2 filter requires 'database'")
        try:
            self._db = MMDBReader(self.database)
        except (OSError, MMDBError) as e:
            raise ValueError(f"geoip2: cannot open {self.database}: {e}")
        self._lookup_keys: List[str] = []
        for item in self.lookup_key or []:
            for k in (item if isinstance(item, list) else [item]):
                self._lookup_keys.append(k)
        if not self._lookup_keys:
            raise ValueError("at least one lookup_key is required")
        # record = KEY LOOKUP_KEY %{path.inside.mmdb}; each configured
        # occurrence arrives either pre-split by the config_map (a
        # [key, lookup, value] triple) or as full strings (kwargs list)
        self._records: List[Tuple[str, str, List[str]]] = []
        flat: List[object] = []
        for item in self.record or []:
            if isinstance(item, list) and not (
                    len(item) == 3 and " " not in str(item[0])
                    and " " not in str(item[1])):
                flat.extend(item)
            else:
                flat.append(item)
        for item in flat:
            parts = item if isinstance(item, list) \
                else str(item).split(None, 2)
            if len(parts) != 3:
                log.error("invalid record parameter %r — expects "
                          "'KEY LOOKUP_KEY VALUE'", item)
                continue
            key, lkey, val = parts
            path = val[2:-1] if val.startswith("%{") and val.endswith("}") \
                else val
            self._records.append((key, lkey, path.split(".")))

    def _ip_of(self, body: dict, lkey: str) -> Optional[str]:
        v = body.get(lkey)
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        return v if isinstance(v, str) else None

    def filter(self, events: list, tag: str, engine) -> tuple:
        if not self._records:
            return (FilterResult.NOTOUCH, events)
        out = []
        for ev in events:
            if ev.is_group_start() or ev.is_group_end():
                out.append(ev)
                continue
            body = dict(ev.body)
            for key, lkey, path in self._records:
                value = None
                ip = self._ip_of(ev.body, lkey)
                if ip:
                    try:
                        value = self._db.get_path(ip, path)
                    except MMDBError:
                        value = None
                if isinstance(value, (dict, list)):
                    log.warning("Not supported MAP and ARRAY")
                    value = None
                body[key] = value
            out.append(LogEvent(ev.timestamp, body, ev.metadata, raw=None))
        return (FilterResult.MODIFIED, out)
