"""Round-3 output tail: plot, vivo_exporter, skywalking, chronicle,
azure_kusto, azure_logs_ingestion, oracle_log_analytics.

Reference plugins: out_plot (gnuplot-consumable "<ts> <value>" file),
out_vivo_exporter (in-process HTTP endpoint serving recent event
streams), out_skywalking (log collector /v3/logs JSON), out_chronicle
(Google Chronicle unstructuredlogentries:batchCreate with
service-account OAuth), out_azure_kusto (ADX streaming ingest with AAD
client-credentials auth), out_azure_logs_ingestion (DCR/DCE ingestion,
same AAD flow), out_oracle_log_analytics (OCI Logging Analytics with
the OCI request-signature scheme).
"""

from __future__ import annotations

import asyncio
import base64
import datetime
import hashlib
import json
import logging
import time
from collections import deque
from typing import Dict, List, Optional

from ..codec.events import decode_events
from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import FlushResult, InputPlugin, OutputPlugin, registry
from ..core.upstream import close_quietly
from .outputs_cloud import _GoogleOutput
from .outputs_http_based import _HttpDeliveryOutput, _dumps

log = logging.getLogger("flb.cloud_extra")


@registry.register
class PlotOutput(OutputPlugin):
    """plugins/out_plot: append "<timestamp> <value>" rows to a file
    for gnuplot; `key` selects the numeric field."""

    name = "plot"
    config_map = [
        ConfigMapEntry("file", "str"),
        ConfigMapEntry("key", "str", default="value"),
    ]

    def init(self, instance, engine) -> None:
        if not self.file:
            raise ValueError("plot: file is required")

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        rows = []
        for ev in decode_events(data):
            v = ev.body.get(self.key) if isinstance(ev.body, dict) else None
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            rows.append(f"{ev.ts_float:.9f} {v}\n")
        if rows:
            try:
                with open(self.file, "a") as f:
                    f.writelines(rows)
            except OSError:
                return FlushResult.RETRY
        return FlushResult.OK


@registry.register
class VivoExporterOutput(OutputPlugin):
    """plugins/out_vivo_exporter: buffer recent events per stream and
    serve them over an HTTP GET endpoint (/logs, /metrics, /traces)."""

    name = "vivo_exporter"
    event_types = ("logs", "metrics", "traces")
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=2025),
        ConfigMapEntry("buffer_max_records", "int", default=1000),
    ]

    def init(self, instance, engine) -> None:
        self._streams: Dict[str, deque] = {
            "logs": deque(maxlen=self.buffer_max_records),
            "metrics": deque(maxlen=self.buffer_max_records),
            "traces": deque(maxlen=self.buffer_max_records),
        }
        self.bound_port: Optional[int] = None
        self._server_task = None

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        if self._server_task is None:
            self._server_task = asyncio.ensure_future(self._serve())
        from ..codec.msgpack import Unpacker
        from ..codec.telemetry import is_traces_payload
        from ..core.metrics import is_metrics_payload

        try:
            objs = list(Unpacker(data))
        except Exception:
            objs = []
        if objs and all(is_metrics_payload(o) for o in objs):
            self._streams["metrics"].extend(
                json.dumps(o, default=str) for o in objs)
        elif objs and all(is_traces_payload(o) for o in objs):
            self._streams["traces"].extend(
                json.dumps(o, default=str) for o in objs)
        else:
            for ev in decode_events(data):
                self._streams["logs"].append(json.dumps(
                    [ev.ts_float, tag, ev.body], default=str))
        return FlushResult.OK

    async def _serve(self) -> None:
        from .net_http import http_response, read_http_request

        async def handle(reader, writer):
            try:
                req = await read_http_request(reader)
                if req is not None:
                    _method, uri, _hdrs, _body = req
                    stream = uri.split("?")[0].strip("/") or "logs"
                    items = self._streams.get(stream)
                    body = ("\n".join(items) + "\n").encode() \
                        if items else b""
                    writer.write(http_response(
                        200 if items is not None else 404, body,
                        "application/x-ndjson"))
                    await io_deadline(writer.drain(), 10.0)
            except (OSError, asyncio.IncompleteReadError):
                # OSError covers both peer resets and io_deadline's
                # TimeoutError (a stalled scraper): drop the connection
                pass
            finally:
                close_quietly(writer)

        server = await asyncio.start_server(handle, self.listen, self.port)
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()

    def exit(self) -> None:
        if self._server_task is not None:
            self._server_task.cancel()


@registry.register
class SkywalkingOutput(_HttpDeliveryOutput):
    """plugins/out_skywalking: OAP log collector /v3/logs JSON."""

    name = "skywalking"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=12800),
        ConfigMapEntry("svc_name", "str", default="fluent-bit"),
        ConfigMapEntry("svc_inst_name", "str", default="fluent-bit"),
        ConfigMapEntry("auth_token", "str"),
    ]

    def _uri(self) -> str:
        return "/v3/logs"

    def _headers(self) -> List[str]:
        return ([f"Authentication: {self.auth_token}"]
                if self.auth_token else [])

    def format(self, data: bytes, tag: str) -> bytes:
        out = []
        for ev in decode_events(data):
            out.append({
                "timestamp": int(ev.ts_float * 1000),
                "service": self.svc_name,
                "serviceInstance": self.svc_inst_name,
                "body": {"json": {"json": _dumps(ev.body)}},
            })
        return _dumps(out).encode()


@registry.register
class ChronicleOutput(_GoogleOutput):
    """plugins/out_chronicle: Google SecOps (Chronicle)
    unstructuredlogentries:batchCreate with service-account OAuth."""

    name = "chronicle"
    scope = "https://www.googleapis.com/auth/malachite-ingestion"
    config_map = [
        ConfigMapEntry("google_service_credentials", "str"),
        ConfigMapEntry("customer_id", "str"),
        ConfigMapEntry("log_type", "str", default="GENERIC_EVENT"),
        ConfigMapEntry("region", "str", default=""),
        ConfigMapEntry("endpoint", "str",
                       desc="override (test/dev); default is the "
                            "regional malachite endpoint"),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not self.customer_id:
            raise ValueError("chronicle: customer_id is required")

    def _endpoint(self) -> str:
        if self.endpoint:
            return self.endpoint
        region = f"{self.region}-" if self.region else ""
        return (f"https://{region}malachiteingestion-pa.googleapis.com"
                f"/v2/unstructuredlogentries:batchCreate")

    def _payload(self, data: bytes, tag: str) -> dict:
        entries = [{
            "logText": _dumps(ev.body),
            "timestamp": datetime.datetime.fromtimestamp(
                ev.ts_float, datetime.timezone.utc).strftime(
                    "%Y-%m-%dT%H:%M:%S.%fZ"),
        } for ev in decode_events(data)]
        return {
            "customerId": self.customer_id,
            "logType": self.log_type,
            "entries": entries,
        }

    def format(self, data: bytes, tag: str) -> bytes:
        return _dumps(self._payload(data, tag)).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        host, port, path, tls = self._split_url(self._endpoint())
        token = await self._bearer()
        if token is None:
            return FlushResult.RETRY
        return await self._post_json(host, port, path,
                                     self._payload(data, tag), tls)


class _AadOutput(_HttpDeliveryOutput):
    """Shared AAD client-credentials token flow (login.microsoftonline
    .com/{tenant}/oauth2/v2.0/token) for the Azure data-plane outputs."""

    aad_scope = ""

    def init(self, instance, engine) -> None:
        for opt in ("tenant_id", "client_id", "client_secret"):
            if not getattr(self, opt, None):
                raise ValueError(f"{self.name}: {opt} is required")
        self._token: Optional[str] = None
        self._token_exp = 0.0

    async def _aad_token(self) -> Optional[str]:
        if self._token and time.time() < self._token_exp - 60:
            return self._token
        from urllib.parse import quote

        login = self.oauth_endpoint or \
            f"https://login.microsoftonline.com"
        host, port, path, tls = _GoogleOutput._split_url(login)
        if path in ("", "/"):
            path = f"/{self.tenant_id}/oauth2/v2.0/token"
        body = ("grant_type=client_credentials"
                f"&client_id={quote(self.client_id)}"
                f"&client_secret={quote(self.client_secret)}"
                f"&scope={quote(self.aad_scope)}").encode()
        from .outputs_aws import _http_request

        try:
            status, _head, resp = await _http_request(
                self.instance, host, port, "POST", path,
                {"Content-Type": "application/x-www-form-urlencoded"},
                body, quote_path=False, use_tls=tls,
            )
            if status != 200:
                return None
            tok = json.loads(resp)
            self._token = tok["access_token"]
            self._token_exp = time.time() + float(
                tok.get("expires_in", 3600))
            return self._token
        except (OSError, ValueError, KeyError, asyncio.TimeoutError):
            return None

    async def _post_bearer(self, body: bytes, uri: str) -> FlushResult:
        token = await self._aad_token()
        if token is None:
            return FlushResult.RETRY
        return await self._post(
            body, extra_headers=[f"Authorization: Bearer {token}"],
            uri=uri)


@registry.register
class AzureKustoOutput(_AadOutput):
    """plugins/out_azure_kusto: ADX streaming ingest
    (/v1/rest/ingest/{db}/{table}?streamFormat=MultiJSON)."""

    name = "azure_kusto"
    aad_scope = "https://kusto.kusto.windows.net/.default"
    config_map = [
        ConfigMapEntry("tenant_id", "str"),
        ConfigMapEntry("client_id", "str"),
        ConfigMapEntry("client_secret", "str"),
        ConfigMapEntry("ingestion_endpoint", "str",
                       desc="https://ingest-<cluster>.<region>.kusto."
                            "windows.net (host[:port] for tests)"),
        ConfigMapEntry("database_name", "str"),
        ConfigMapEntry("table_name", "str"),
        ConfigMapEntry("time_key", "str", default="timestamp"),
        ConfigMapEntry("tag_key", "str", default="tag"),
        ConfigMapEntry("include_tag_key", "bool", default=True),
        ConfigMapEntry("oauth_endpoint", "str",
                       desc="AAD override for tests"),
        ConfigMapEntry("host", "str"),
        ConfigMapEntry("port", "int", default=443),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not (self.ingestion_endpoint and self.database_name
                and self.table_name):
            raise ValueError("azure_kusto: ingestion_endpoint + "
                             "database_name + table_name are required")
        host, port, _, tls = _GoogleOutput._split_url(
            self.ingestion_endpoint)
        self.host, self.port = host, port
        if tls and "tls" not in instance.properties:
            instance.set("tls", "on")

    def _uri(self) -> str:
        return (f"/v1/rest/ingest/{self.database_name}/"
                f"{self.table_name}?streamFormat=MultiJSON")

    def format(self, data: bytes, tag: str) -> bytes:
        rows = []
        for ev in decode_events(data):
            row = dict(ev.body) if isinstance(ev.body, dict) else {}
            row[self.time_key] = ev.ts_float
            if self.include_tag_key:
                row[self.tag_key] = tag
            rows.append(_dumps(row))
        return "\n".join(rows).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        return await self._post_bearer(self.format(data, tag),
                                       self._uri())


@registry.register
class AzureLogsIngestionOutput(_AadOutput):
    """plugins/out_azure_logs_ingestion: DCR-based Logs Ingestion API
    (POST {dce}/dataCollectionRules/{dcr}/streams/{stream})."""

    name = "azure_logs_ingestion"
    aad_scope = "https://monitor.azure.com/.default"
    config_map = [
        ConfigMapEntry("tenant_id", "str"),
        ConfigMapEntry("client_id", "str"),
        ConfigMapEntry("client_secret", "str"),
        ConfigMapEntry("dce_url", "str"),
        ConfigMapEntry("dcr_id", "str"),
        ConfigMapEntry("table_name", "str"),
        ConfigMapEntry("time_generated", "bool", default=True),
        ConfigMapEntry("oauth_endpoint", "str"),
        ConfigMapEntry("host", "str"),
        ConfigMapEntry("port", "int", default=443),
    ]

    def init(self, instance, engine) -> None:
        super().init(instance, engine)
        if not (self.dce_url and self.dcr_id and self.table_name):
            raise ValueError("azure_logs_ingestion: dce_url + dcr_id + "
                             "table_name are required")
        host, port, _, tls = _GoogleOutput._split_url(self.dce_url)
        self.host, self.port = host, port
        if tls and "tls" not in instance.properties:
            instance.set("tls", "on")

    def _uri(self) -> str:
        return (f"/dataCollectionRules/{self.dcr_id}/streams/"
                f"Custom-{self.table_name}?api-version=2023-01-01")

    def format(self, data: bytes, tag: str) -> bytes:
        rows = []
        for ev in decode_events(data):
            row = dict(ev.body) if isinstance(ev.body, dict) else {}
            if self.time_generated:
                row["TimeGenerated"] = datetime.datetime.fromtimestamp(
                    ev.ts_float, datetime.timezone.utc).isoformat()
            rows.append(row)
        return _dumps(rows).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        return await self._post_bearer(self.format(data, tag),
                                       self._uri())


@registry.register
class OracleLogAnalyticsOutput(_HttpDeliveryOutput):
    """plugins/out_oracle_log_analytics: OCI Logging Analytics upload
    with the OCI HTTP signature scheme (RSA-SHA256 over date/(request-
    target)/host/content headers; `cryptography` provides the RSA as it
    does for the Google outputs)."""

    name = "oracle_log_analytics"
    config_map = [
        ConfigMapEntry("namespace", "str"),
        ConfigMapEntry("config_file_location", "str",
                       desc="OCI config: user/fingerprint/tenancy/"
                            "region/key_file"),
        ConfigMapEntry("profile_name", "str", default="DEFAULT"),
        ConfigMapEntry("oci_la_log_group_id", "str"),
        ConfigMapEntry("oci_la_log_source_name", "str"),
        ConfigMapEntry("host", "str"),
        ConfigMapEntry("port", "int", default=443),
    ]

    def init(self, instance, engine) -> None:
        if not (self.namespace and self.config_file_location):
            raise ValueError("oracle_log_analytics: namespace + "
                             "config_file_location are required")
        import configparser

        cp = configparser.ConfigParser()
        cp.read(self.config_file_location)
        prof = cp[self.profile_name or "DEFAULT"]
        self._tenancy = prof.get("tenancy", "")
        self._user = prof.get("user", "")
        self._fingerprint = prof.get("fingerprint", "")
        self._region = prof.get("region", "us-ashburn-1")
        key_file = prof.get("key_file", "")
        from cryptography.hazmat.primitives.serialization import \
            load_pem_private_key

        with open(key_file, "rb") as f:
            self._key = load_pem_private_key(f.read(), password=None)
        if not self.host:
            self.host = (f"loganalytics.{self._region}.oci."
                         f"oraclecloud.com")
            instance.set("tls", "on")

    def _uri(self) -> str:
        return (f"/20200601/namespaces/{self.namespace}/actions/"
                f"uploadLogEventsFile?logGroupId="
                f"{self.oci_la_log_group_id}")

    def format(self, data: bytes, tag: str) -> bytes:
        recs = [dict(ev.body) if isinstance(ev.body, dict) else
                {"message": str(ev.body)} for ev in decode_events(data)]
        return _dumps({"metadata": {
            "logSourceName": self.oci_la_log_source_name or tag,
        }, "logRecords": recs}).encode()

    def _signed_headers(self, body: bytes) -> List[str]:
        from cryptography.hazmat.primitives.asymmetric import padding
        from cryptography.hazmat.primitives import hashes

        date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT")
        sha = base64.b64encode(hashlib.sha256(body).digest()).decode()
        # sign EXACTLY what the transport sends: Host carries the port
        # (outputs_http_based builds "Host: {host}:{port}") and the
        # request-target keeps its case (OCIDs are case-sensitive)
        signing = (f"date: {date}\n"
                   f"(request-target): post {self._uri()}\n"
                   f"host: {self.host}:{self.port}\n"
                   f"x-content-sha256: {sha}\n"
                   f"content-type: application/octet-stream\n"
                   f"content-length: {len(body)}")
        sig = base64.b64encode(self._key.sign(
            signing.encode(), padding.PKCS1v15(),
            hashes.SHA256())).decode()
        key_id = f"{self._tenancy}/{self._user}/{self._fingerprint}"
        auth = ('Signature version="1",keyId="{}",algorithm='
                '"rsa-sha256",headers="date (request-target) host '
                'x-content-sha256 content-type content-length",'
                'signature="{}"').format(key_id, sig)
        return [f"date: {date}", f"x-content-sha256: {sha}",
                f"Authorization: {auth}"]

    def _content_type(self) -> str:
        return "application/octet-stream"

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        body = self.format(data, tag)
        return await self._post(body,
                                extra_headers=self._signed_headers(body))
