"""HTTP input (server) + HTTP output (client).

Reference: plugins/in_http (HTTP/1.1 JSON server: POST bodies as a map,
an array of maps, or NDJSON; the URI path becomes the tag) and
plugins/out_http (POST formatted records with configurable format and
headers; 2xx = OK, retryable errors = FLB_RETRY). Minimal HTTP/1.1
framing over asyncio streams — enough for loopback pipelines and tests;
no TLS/HTTP2 (the reference uses openssl/nghttp2).
"""

from __future__ import annotations

import asyncio
import json
import logging
from struct import error as struct_error
from typing import Dict, List, Optional, Tuple

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, InputPlugin, OutputPlugin, registry
from ..core.upstream import close_quietly
from .outputs_basic import format_json_lines
from .outputs_http_based import _HttpDeliveryOutput

log = logging.getLogger("flb.http")


async def read_http_request(reader) -> Optional[Tuple[str, str, Dict[str, str], bytes]]:
    """Parse one HTTP/1.1 request; returns (method, uri, headers, body)."""
    line = await reader.readline()
    if not line:
        return None
    try:
        method, uri, _version = line.decode("latin-1").split(None, 2)
    except ValueError:
        return None
    headers: Dict[str, str] = {}
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        if b":" in h:
            k, v = h.decode("latin-1").split(":", 1)
            headers[k.strip().lower()] = v.strip()
    body = b""
    n = int(headers.get("content-length", 0) or 0)
    if n > 0:
        body = await reader.readexactly(n)
    elif headers.get("transfer-encoding", "").lower() == "chunked":
        while True:
            size_line = await reader.readline()
            size = int(size_line.strip() or b"0", 16)
            if size == 0:
                await reader.readline()
                break
            body += await reader.readexactly(size)
            await reader.readline()
    return method, uri, headers, body


def http_response(status: int, body: bytes = b"",
                  content_type: str = "text/plain",
                  extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    reason = {200: "OK", 201: "Created", 204: "No Content",
              400: "Bad Request", 404: "Not Found",
              500: "Internal Server Error"}.get(status, "OK")
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Length: {len(body)}",
            f"Content-Type: {content_type}"]
    for k, v in (extra_headers or {}).items():
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


def _parse_json_bodies(body: bytes) -> Optional[List[dict]]:
    """in_http body handling: map | array of maps | NDJSON."""
    text = body.decode("utf-8", "replace").strip()
    if not text:
        return []
    try:
        obj = json.loads(text)
        if isinstance(obj, dict):
            return [obj]
        if isinstance(obj, list) and all(isinstance(o, dict) for o in obj):
            return obj
        return None
    except ValueError:
        pass
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            o = json.loads(line)
        except ValueError:
            return None
        if not isinstance(o, dict):
            return None
        out.append(o)
    return out


class HttpServerInputBase(InputPlugin):
    """Shared HTTP server skeleton for server-type inputs (http, splunk
    HEC, elasticsearch bulk, opentelemetry). Subclasses implement
    ``handle_request(engine, method, path, headers, body) → (status,
    resp_bytes)``; the base runs the accept loop, TLS, keep-alive
    (Connection: close honored), HEAD body suppression, and error
    isolation (a raising handler answers 500 instead of dropping the
    connection)."""

    server_task_needed = True
    content_type = "application/json"

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    def handle_request(self, engine, method, path, headers,
                       body):  # pragma: no cover
        raise NotImplementedError

    # subclasses that own their Content-Encoding handling (prometheus
    # remote-write's mandatory snappy) opt out of base decoding
    decode_content = True

    def _decode_content(self, headers, body):
        """Transparent request-body decompression (reference in_http
        rides flb_http_server's gzip/zstd/snappy handling). Returns
        the decoded body or None for an undecodable payload."""
        if not self.decode_content:
            return body
        algo = headers.get("content-encoding", "").lower()
        if not algo or not body:
            return body
        if algo == "identity":
            return body
        if algo not in ("gzip", "zstd", "snappy", "deflate"):
            # an unknown encoding handed through would be parsed as if
            # it were plaintext, minting garbage records — reject (400)
            # like the reference's http server does for unsupported
            # encodings
            return None
        from ..utils import decompress
        try:
            return decompress(algo, body)
        except Exception:
            # zlib.error/EOFError/CompressionError on attacker-
            # controlled bytes: any undecodable body answers 400 BY
            # DESIGN, never a dropped connection or a task error
            return None  # fbtpu-lint: allow(decline-swallow)

    async def start_server(self, engine) -> None:
        from ..core.tls import server_context

        async def h2_handler(method, path, headers, body):
            body = self._decode_content(headers, body)
            if body is None:
                return 400, b"bad content encoding\n", self.content_type
            try:
                status, resp = self.handle_request(
                    engine, method, path.split("?")[0], headers, body)
            except Exception:
                log.exception("%s h2 request handler failed", self.name)
                status, resp = 500, b"{}"
            if method == "HEAD":
                resp = b""  # RFC 9110: HEAD carries no body
            return status, resp, self.content_type

        async def handle(reader, writer):
            try:
                while True:
                    req = await read_http_request(reader)
                    if req is None:
                        break
                    method, uri, headers, body = req
                    if method == "PRI" and uri == "*":
                        # h2c prior-knowledge preface: its first line
                        # parses as a request; consume the trailing
                        # "SM\r\n\r\n" and switch the connection to the
                        # HTTP/2 engine (reference in_http speaks both
                        # via nghttp2 upgrade detection)
                        rest = await reader.readexactly(6)
                        if rest != b"SM\r\n\r\n":
                            break
                        from ..core.http2 import serve_h2c

                        try:
                            await serve_h2c(reader, writer, h2_handler,
                                            preface_consumed=True)
                        except (ValueError, IndexError, struct_error):
                            # malformed frames/HPACK from the client:
                            # drop the connection like a bad HTTP/1
                            # request, never an unhandled task error
                            log.debug("h2c connection error",
                                      exc_info=True)
                        break
                    decoded = self._decode_content(headers, body)
                    if decoded is None:
                        status, resp = 400, b"bad content encoding\n"
                    else:
                        try:
                            status, resp = self.handle_request(
                                engine, method, uri.split("?")[0],
                                headers, decoded,
                            )
                        except Exception:
                            log.exception("%s request handler failed",
                                          self.name)
                            status, resp = 500, b"{}"
                    if method == "HEAD":
                        resp = b""  # RFC 9110: HEAD carries no body
                    writer.write(http_response(status, resp,
                                               self.content_type))
                    await writer.drain()
                    if headers.get("connection", "").lower() == "close":
                        break
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                close_quietly(writer)

        server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()


@registry.register
class HttpInput(HttpServerInputBase):
    name = "http"
    description = "HTTP server input (JSON/NDJSON bodies)"
    content_type = "text/plain"
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=9880),
        ConfigMapEntry("tag_key", "str"),
        ConfigMapEntry("successful_response_code", "int", default=201),
    ]

    def handle_request(self, engine, method, path, headers, body):
        if method != "POST":
            return 400, b"POST only\n"
        bodies = _parse_json_bodies(body)
        if bodies is None:
            return 400, b"bad body\n"
        uri_tag = path.lstrip("/").replace("/", ".") or self.instance.tag
        # tag_key resolves PER RECORD: group by tag, one append per
        # group so mixed-tag bodies route right
        groups: Dict[str, bytearray] = {}
        counts: Dict[str, int] = {}
        for b in bodies:
            tag = uri_tag
            if self.tag_key and isinstance(b.get(self.tag_key), str):
                tag = b[self.tag_key]
            groups.setdefault(tag, bytearray())
            groups[tag] += encode_event(b, now_event_time())
            counts[tag] = counts.get(tag, 0) + 1
        for tag, buf in groups.items():
            engine.input_log_append(self.instance, tag, bytes(buf),
                                    counts[tag])
        return (self.successful_response_code or 201), b""


@registry.register
class HttpOutput(_HttpDeliveryOutput):
    """Rides the shared delivery base: keepalive pools (core.upstream),
    retry classification, TLS, and `http2 on`."""

    name = "http"
    description = "HTTP client output"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=80),
        ConfigMapEntry("uri", "str", default="/"),
        ConfigMapEntry("format", "str", default="json"),
        ConfigMapEntry("json_date_key", "str", default="date"),
        ConfigMapEntry("header", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("compress", "str"),
    ]

    def init(self, instance, engine) -> None:
        algo = (self.compress or "").lower()
        if algo in ("gzip", "snappy", "zstd"):
            from ..utils import compression_available
            if not compression_available(algo):
                raise ValueError(f"http: {algo} codec unavailable on "
                                 "this host")

    def _fmt(self) -> str:
        # the `format` OPTION collides with the wire-builder method
        # required by the delivery base, so it reads from properties
        return str(self.instance.properties.get("format")
                   or "json").lower()

    def _content_type(self) -> str:
        return {"msgpack": "application/msgpack",
                "json": "application/json"}.get(
                    self._fmt(), "application/x-ndjson")

    def _headers(self) -> list:
        out = []
        algo = (self.compress or "").lower()
        if algo in ("gzip", "snappy", "zstd"):
            # reference out_http supports all three (http.c:147-167)
            out.append(f"Content-Encoding: {algo}")
        for pair in self.header or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                out.append(f"{parts[0]}: {parts[1]}")
        return out

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        # the `format` config option always shadows any method of that
        # name on the instance (config defaults are setattr'd), so the
        # wire builder lives under _build
        return await self._post(self._build(data, tag))

    def _build(self, data: bytes, tag: str) -> bytes:
        fmt = self._fmt()
        if fmt == "msgpack":
            body = data
        else:
            text = format_json_lines(
                data, date_key=self.json_date_key or "date")
            if fmt == "json":
                body = ("[" + text.replace("\n", ",") + "]").encode()
            else:
                body = (text + "\n").encode()
        algo = (self.compress or "").lower()
        if algo in ("gzip", "snappy", "zstd"):
            from ..utils import compress as _compress

            body = _compress(algo, body)
        return body
