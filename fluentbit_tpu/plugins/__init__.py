"""Plugin registry population. Importing this package registers all
built-in plugins (the cmake/plugins_options.cmake equivalent is: they are
all on)."""

from . import inputs_basic  # noqa: F401
from . import in_emitter  # noqa: F401
from . import in_tail  # noqa: F401
from . import in_syslog  # noqa: F401
from . import net_tcp_udp  # noqa: F401
from . import net_http  # noqa: F401
from . import net_forward  # noqa: F401
from . import inputs_system  # noqa: F401
from . import outputs_basic  # noqa: F401
from . import outputs_http_based  # noqa: F401
from . import filter_grep  # noqa: F401
from . import filter_parser  # noqa: F401
from . import filter_rewrite_tag  # noqa: F401
from . import filter_log_to_metrics  # noqa: F401
from . import filter_multiline  # noqa: F401
from . import filter_kubernetes  # noqa: F401
from . import filters_basic  # noqa: F401
from . import filters_extra  # noqa: F401
from . import filter_script  # noqa: F401
from . import filter_lua  # noqa: F401
from . import filter_wasm  # noqa: F401
from . import processors  # noqa: F401
from . import telemetry_extra  # noqa: F401
from . import outputs_aws  # noqa: F401
from . import outputs_cloud  # noqa: F401
from . import outputs_cloud_extra  # noqa: F401
from . import outputs_webhooks  # noqa: F401
from . import opentelemetry  # noqa: F401
from . import misc_plugins  # noqa: F401
from . import processor_sampling  # noqa: F401
from . import in_servers_extra  # noqa: F401
from . import enrichment_extra  # noqa: F401
from . import inputs_net_extra  # noqa: F401
from . import inputs_exporters  # noqa: F401
from . import in_kubernetes_events  # noqa: F401
from . import out_websocket  # noqa: F401
from . import out_pgsql  # noqa: F401
from . import misc_tail3  # noqa: F401
from . import prometheus_remote_write  # noqa: F401
from . import in_mqtt  # noqa: F401
from . import filter_geoip2  # noqa: F401
from . import inputs_system_extra  # noqa: F401
from . import out_kafka  # noqa: F401
from . import in_kafka  # noqa: F401
from . import filter_nightfall  # noqa: F401
from . import in_serial  # noqa: F401
from . import calyptia  # noqa: F401
from . import in_exec_wasi  # noqa: F401
from . import filter_tensorflow  # noqa: F401
from . import in_systemd  # noqa: F401
from . import gated  # noqa: F401
from ..flux import plugin as _flux_plugin  # noqa: F401  (filter "flux")
