"""Gated plugins — components whose vendored runtime is absent.

Reference plugins that embed a library this image does not provide
(librdkafka, WAMR, libmaxminddb, TF-Lite, libbpf). They register under
their reference names and fail AT INIT with a clear message naming the
missing runtime — configs referencing them error loudly instead of
silently dropping data (the same stance as the snappy/zstd compression
gates in utils).
"""

from __future__ import annotations

from ..core.plugin import (
    FilterPlugin,
    InputPlugin,
    OutputPlugin,
    registry,
)


def _gate(kind, plugin_name: str, runtime: str, hint: str = ""):
    class Gated(kind):
        name = plugin_name
        description = f"gated: {runtime} not vendored in this build"

        def init(self, instance, engine) -> None:
            msg = (f"{plugin_name}: the {runtime} runtime is not vendored "
                   f"in this build")
            if hint:
                msg += f" — {hint}"
            raise RuntimeError(msg)

    Gated.__name__ = f"Gated_{plugin_name}"
    return registry.register(Gated)


_gate(InputPlugin, "ebpf", "libbpf CO-RE")
_gate(InputPlugin, "winlog", "the Windows Event Log API")
_gate(InputPlugin, "winevtlog", "the Windows Event Log API")
_gate(InputPlugin, "winstat", "the Windows performance counter API")
_gate(InputPlugin, "windows_exporter_metrics",
      "the Windows WMI/perflib APIs")
_gate(InputPlugin, "etw", "Event Tracing for Windows")
# in_stream_processor is not gated: CREATE STREAM results re-ingest
# through the hidden emitter already (stream_processor/__init__.py)
_gate(OutputPlugin, "zig_demo", "the Zig native-plugin ABI demo")
