"""Gated plugins — components whose vendored runtime is absent.

Reference plugins that embed a library this image does not provide
(librdkafka, WAMR, libmaxminddb, TF-Lite, libbpf). They register under
their reference names and fail AT INIT with a clear message naming the
missing runtime — configs referencing them error loudly instead of
silently dropping data (the same stance as the snappy/zstd compression
gates in utils).
"""

from __future__ import annotations

from ..core.plugin import (
    CustomPlugin,
    FilterPlugin,
    InputPlugin,
    OutputPlugin,
    registry,
)


def _gate(kind, plugin_name: str, runtime: str, hint: str = ""):
    class Gated(kind):
        name = plugin_name
        description = f"gated: {runtime} not vendored in this build"

        def init(self, instance, engine) -> None:
            msg = (f"{plugin_name}: the {runtime} runtime is not vendored "
                   f"in this build")
            if hint:
                msg += f" — {hint}"
            raise RuntimeError(msg)

    Gated.__name__ = f"Gated_{plugin_name}"
    return registry.register(Gated)


_gate(InputPlugin, "exec_wasi", "WASI (filesystem/clock imports; the "
      "wasmrt interpreter runs only self-contained modules)",
      "the 'exec' input runs native commands")
_gate(FilterPlugin, "tensorflow", "TensorFlow Lite")
_gate(FilterPlugin, "nightfall", "the Nightfall DLP API (network)")
_gate(InputPlugin, "ebpf", "libbpf CO-RE")
_gate(InputPlugin, "systemd", "libsystemd (journald)")
_gate(InputPlugin, "winlog", "the Windows Event Log API")
_gate(InputPlugin, "winevtlog", "the Windows Event Log API")
_gate(InputPlugin, "winstat", "the Windows performance counter API")
_gate(InputPlugin, "windows_exporter_metrics",
      "the Windows WMI/perflib APIs")
_gate(InputPlugin, "etw", "Event Tracing for Windows")
# in_stream_processor is not gated: CREATE STREAM results re-ingest
# through the hidden emitter already (stream_processor/__init__.py)
_gate(OutputPlugin, "calyptia", "the Calyptia Cloud ingestion API")
_gate(OutputPlugin, "zig_demo", "the Zig native-plugin ABI demo")

_gate(CustomPlugin, "calyptia",
      "the Calyptia Cloud control plane (remote fleet management API)",
      "the custom-plugin machinery itself is live: see "
      "tests/test_misc_tail3.py for a programmatic custom")
_gate(InputPlugin, "serial", "a serial port (termios device access)")
_gate(InputPlugin, "calyptia_fleet",
      "the Calyptia Cloud control plane")
