"""More network inputs: unix_socket, prometheus_scrape,
nginx_exporter_metrics.

Reference: plugins/in_unix_socket (stream/dgram unix server, same
framing as in_tcp), plugins/in_prometheus_scrape (pull a /metrics
endpoint on an interval and re-emit the samples as metrics),
plugins/in_nginx_exporter_metrics (nginx stub_status → metrics).
"""

from __future__ import annotations

import asyncio
import logging
import re
import time
from typing import Dict, List, Optional, Tuple

from ..codec.chunk import EVENT_TYPE_METRICS
from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from .net_tcp_udp import _LineServerInput

log = logging.getLogger("flb.net_extra")


@registry.register
class UnixSocketInput(_LineServerInput):
    name = "unix_socket"
    description = "unix-domain socket listener (JSON / raw lines)"
    config_map = [
        ConfigMapEntry("path", "str"),
        ConfigMapEntry("mode", "str", default="stream"),
        ConfigMapEntry("format", "str", default="json"),
        ConfigMapEntry("separator", "str"),
        ConfigMapEntry("source_key", "str", default="log"),
        ConfigMapEntry("unix_perm", "str"),
        ConfigMapEntry("chunk_size", "size", default="32k"),
    ]

    def init(self, instance, engine) -> None:
        if not self.path:
            raise ValueError("unix_socket: path is required")
        self.ready = False

    def _prepare_path(self) -> None:
        import os

        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _apply_perm(self) -> None:
        if self.unix_perm:
            import os

            try:
                os.chmod(self.path, int(str(self.unix_perm), 8))
            except (OSError, ValueError):
                log.warning("unix_socket: cannot apply unix_perm %r",
                            self.unix_perm)

    async def start_server(self, engine) -> None:
        mode = (self.mode or "stream").lower()
        self._prepare_path()
        if mode == "dgram":
            import socket as _socket

            sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_DGRAM)
            sock.bind(self.path)
            sock.setblocking(False)
            self._apply_perm()
            loop = asyncio.get_running_loop()
            transport, _ = await loop.create_datagram_endpoint(
                self._datagram_protocol(engine), sock=sock
            )
            self.ready = True
            try:
                await asyncio.Event().wait()
            finally:
                transport.close()
            return

        async def handle(reader, writer):
            await self._handle_stream(reader, writer, engine)

        server = await asyncio.start_unix_server(handle, path=self.path)
        self._apply_perm()
        self.ready = True
        async with server:
            await server.serve_forever()


# ------------------------------------------------- prometheus text parser

_SAMPLE_RE = re.compile(
    r"""^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)
        (?:\{(?P<labels>[^}]*)\})?
        \s+(?P<value>[^\s]+)(?:\s+(?P<ts>-?\d+))?$""",
    re.VERBOSE,
)
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)


def _unescape_label(v: str) -> str:
    r"""Exposition-format label escapes: \\, \" and \n (a real
    newline) — never strip the backslash generically."""
    return re.sub(r"\\(.)",
                  lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)


def parse_prometheus_text(text: str) -> List[dict]:
    """Prometheus exposition text → metrics payload entries (the
    reverse of core.metrics.payload_to_prometheus; the reference uses
    the cmt_decode_prometheus flex/bison grammar)."""
    metrics: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = []
        if m.group("labels"):
            labels = [(k, _unescape_label(v))
                      for k, v in _LABEL_RE.findall(m.group("labels"))]
        # histogram/summary series fold back into their base family name
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                base = name[: -len(suffix)]
                break
        # entries key on (name, sorted label names): Prometheus label
        # ORDER is unspecified, so samples must be realigned to the
        # entry's key order, and differing label SETS get own entries
        lmap = dict(labels)
        key = (name, tuple(sorted(lmap)))
        entry = metrics.setdefault(key, {
            "name": name,
            "type": types.get(base, types.get(name, "untyped")),
            "desc": helps.get(base, helps.get(name, "")),
            "labels": [k for k, _ in labels],
            "values": [],
        })
        entry["values"].append({
            "labels": [lmap[k] for k in entry["labels"]],
            "value": value,
        })
    return list(metrics.values())


class _AsyncScrapeInput(InputPlugin):
    """Interval scrapers run ON the engine loop: the fetch must be
    async (a blocking 3s connect would stall every collector, flush
    timer, and server). collect() dispatches an async task; a strong
    reference keeps it from being GC'd mid-flight."""

    #: overall per-scrape deadline (the per-read timeout inside the
    #: fetch resets each chunk; a drip-feeding endpoint must not keep a
    #: scrape alive forever)
    SCRAPE_DEADLINE = 15.0

    def collect(self, engine) -> None:
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # unit tests drive collect() synchronously
            asyncio.run(self._scrape(engine))
            return
        inflight = getattr(self, "_inflight", None)
        if inflight is not None and not inflight.done():
            return  # previous scrape still running: skip this tick

        async def bounded():
            try:
                await asyncio.wait_for(self._scrape(engine),
                                       self.SCRAPE_DEADLINE)
            except asyncio.TimeoutError:
                log.warning("%s: scrape exceeded %.0fs deadline",
                            self.name, self.SCRAPE_DEADLINE)

        self._inflight = asyncio.ensure_future(bounded())

    async def _scrape(self, engine) -> None:  # pragma: no cover
        raise NotImplementedError


@registry.register
class PrometheusScrapeInput(_AsyncScrapeInput):
    name = "prometheus_scrape"
    description = "scrape a Prometheus /metrics endpoint"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=9100),
        ConfigMapEntry("metrics_path", "str", default="/metrics"),
        ConfigMapEntry("scrape_interval", "time", default="10"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.scrape_interval or 10)

    async def _scrape(self, engine) -> None:
        from ..utils import async_plain_http_request

        got = await async_plain_http_request(
            self.host, self.port, "GET", self.metrics_path or "/metrics"
        )
        if got is None or got[0] != 200:
            log.debug("prometheus_scrape: scrape failed")
            return
        entries = parse_prometheus_text(got[1].decode("utf-8", "replace"))
        if not entries:
            return
        payload = {"meta": {"ts": time.time()}, "metrics": entries}
        engine.input_event_append(
            self.instance, self.instance.tag, packb(payload),
            EVENT_TYPE_METRICS, n_records=len(entries),
        )


@registry.register
class NginxExporterMetricsInput(_AsyncScrapeInput):
    name = "nginx_exporter_metrics"
    description = "nginx stub_status → metrics"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=80),
        ConfigMapEntry("status_url", "str", default="/status"),
        ConfigMapEntry("scrape_interval", "time", default="5"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.scrape_interval or 5)

    async def _scrape(self, engine) -> None:
        from ..utils import async_plain_http_request

        got = await async_plain_http_request(
            self.host, self.port, "GET", self.status_url or "/status"
        )
        up = 1.0 if got is not None and got[0] == 200 else 0.0
        entries = [{"name": "nginx_up", "type": "gauge",
                    "desc": "nginx reachable", "labels": [],
                    "values": [{"labels": [], "value": up}]}]
        if up:
            text = got[1].decode("utf-8", "replace")
            m = re.search(r"Active connections:\s*(\d+)", text)
            counters = re.search(
                r"^\s*(\d+)\s+(\d+)\s+(\d+)\s*$", text, re.MULTILINE)
            rw = re.search(
                r"Reading:\s*(\d+)\s+Writing:\s*(\d+)\s+Waiting:\s*(\d+)",
                text)
            def gauge(name, desc, v):
                return {"name": f"nginx_{name}", "type": "gauge",
                        "desc": desc, "labels": [],
                        "values": [{"labels": [], "value": float(v)}]}
            if m:
                entries.append(gauge("connections_active",
                                     "active connections", m.group(1)))
            if counters:
                entries.append({
                    "name": "nginx_connections_accepted", "type": "counter",
                    "desc": "accepted connections", "labels": [],
                    "values": [{"labels": [],
                                "value": float(counters.group(1))}]})
                entries.append({
                    "name": "nginx_http_requests_total", "type": "counter",
                    "desc": "handled requests", "labels": [],
                    "values": [{"labels": [],
                                "value": float(counters.group(3))}]})
            if rw:
                entries.append(gauge("connections_reading", "reading",
                                     rw.group(1)))
                entries.append(gauge("connections_writing", "writing",
                                     rw.group(2)))
                entries.append(gauge("connections_waiting", "waiting",
                                     rw.group(3)))
        payload = {"meta": {"ts": time.time()}, "metrics": entries}
        engine.input_event_append(
            self.instance, self.instance.tag, packb(payload),
            EVENT_TYPE_METRICS, n_records=len(entries),
        )
