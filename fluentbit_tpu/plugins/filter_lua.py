"""filter_lua on the from-scratch Lua runtime (fluentbit_tpu.luart).

Reference: plugins/filter_lua/lua.c + src/flb_lua.c (LuaJIT embed).
Contract (lua.c:440-705): per record call

    function <call>(tag, timestamp, record)
        return code, timestamp, record
    end

code -1 → skip the record; 0 → keep as-is; 1 → modified, use returned
timestamp + record; 2 → modified, keep ORIGINAL timestamp. A returned
ARRAY of tables splits into one record each (lua.c pack loop). With
``time_as_table on`` the timestamp travels as {sec=, nsec=} both ways
(flb_lua_pushtimetable). ``protected_mode`` (default on) keeps the
original record and logs when the script errors (lua_pcall stance).
``type_int_key`` lists keys whose returned values are forced to
integers (flb_lua dual int/double packing).
"""

from __future__ import annotations

import logging
import math
from typing import List

from ..codec.events import LogEvent
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..luart import LuaError, LuaRuntime, LuaTable, lua_to_py, py_to_lua

log = logging.getLogger("flb.lua")


@registry.register
class LuaFilter(FilterPlugin):
    name = "lua"
    description = "Lua script filter (from-scratch Lua 5.1 runtime)"
    config_map = [
        ConfigMapEntry("script", "str", desc="path of the Lua script"),
        ConfigMapEntry("code", "str", desc="inline Lua source"),
        ConfigMapEntry("call", "str",
                       desc="Lua function name to invoke per record"),
        ConfigMapEntry("protected_mode", "bool", default=True),
        ConfigMapEntry("time_as_table", "bool", default=False),
        ConfigMapEntry("type_int_key", "slist", multiple=True,
                       desc="keys whose values are packed as integers"),
    ]

    def init(self, instance, engine) -> None:
        if not self.script and not self.code:
            raise ValueError("lua filter requires 'script' or 'code'")
        if not self.call:
            raise ValueError("lua filter requires 'call'")
        source = self.code or ""
        name = "<inline>"
        if self.script:
            name = self.script
            with open(self.script, "r", encoding="utf-8") as f:
                source = f.read()
        self._rt = LuaRuntime()
        self._rt.load(source, name)
        fn = self._rt.globals.vars.get(self.call)
        if fn is None:
            raise ValueError(
                f"lua filter: function {self.call!r} not found in {name}")
        self._int_keys = set()
        for item in self.type_int_key or []:
            for k in (item if isinstance(item, list) else [item]):
                self._int_keys.add(k)

    # ------------------------------------------------------ time repr

    def _push_time(self, ts_float: float):
        if not self.time_as_table:
            return ts_float
        t = LuaTable()
        sec = math.floor(ts_float)
        t.set("sec", float(sec))
        t.set("nsec", float(round((ts_float - sec) * 1e9)))
        return t

    def _pop_time(self, v, fallback: float) -> float:
        if isinstance(v, LuaTable):
            sec = v.get("sec")
            nsec = v.get("nsec")
            if sec is not None:
                return float(sec) + float(nsec or 0.0) / 1e9
            return fallback
        if isinstance(v, float):
            return v
        return fallback

    # -------------------------------------------------------- filter

    def _coerce(self, rec: dict) -> dict:
        if not self._int_keys or not isinstance(rec, dict):
            return rec
        for k in list(rec.keys()):
            if k in self._int_keys:
                try:
                    rec[k] = int(float(rec[k]))
                except (TypeError, ValueError):
                    pass
        return rec

    def filter(self, events: list, tag: str, engine) -> tuple:
        out: List[LogEvent] = []
        modified = False
        for ev in events:
            if ev.is_group_start() or ev.is_group_end():
                out.append(ev)
                continue
            try:
                rets = self._rt.call(
                    self.call,
                    [tag, self._push_time(ev.ts_float),
                     py_to_lua(ev.body)])
            except (LuaError, RecursionError, ZeroDivisionError,
                    TypeError, ValueError, OverflowError,
                    AttributeError, IndexError, KeyError) as e:
                # stdlib calls can surface raw Python exceptions (e.g.
                # string.char out of range) — protection is per record
                if not self.protected_mode:
                    raise
                log.error("lua filter %r failed: %s", self.call, e)
                out.append(ev)
                continue
            code = rets[0] if len(rets) > 0 else None
            l_ts = rets[1] if len(rets) > 1 else None
            l_rec = rets[2] if len(rets) > 2 else None
            code = int(code) if isinstance(code, float) else code
            if code == -1:
                modified = True
                continue
            if code == 0 or code not in (1, 2):
                if code not in (-1, 0, 1, 2):
                    log.warning(
                        "unexpected Lua script return code %r, original "
                        "record will be kept", code)
                out.append(ev)
                continue
            # code 1: returned timestamp; code 2: original timestamp
            if code == 1:
                new_ts = EventTime.from_float(
                    self._pop_time(l_ts, ev.ts_float))
            else:
                new_ts = ev.timestamp
            py_rec = lua_to_py(l_rec)
            if isinstance(py_rec, list):
                # array return → one record per table (lua.c pack loop)
                recs = [r for r in py_rec if isinstance(r, dict)]
            elif isinstance(py_rec, dict):
                recs = [py_rec]
            else:
                log.warning("invalid record type returned by the Lua "
                            "script; keeping the original")
                out.append(ev)
                continue
            out.extend(
                LogEvent(new_ts, self._coerce(r), ev.metadata, raw=None)
                for r in recs)
            modified = True
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)
