"""in_syslog — syslog server (rfc3164 / rfc5424 over udp/tcp/unix).

Reference: plugins/in_syslog (syslog.c, syslog_conn.c, syslog_server.c):
modes udp/tcp/unix_udp/unix_tcp, messages parsed by a named parser
(default the rfc3164 pattern from conf/parsers.conf). TCP messages are
newline-framed (octet-counted framing is not implemented — documented
gap, matching the reference's default behavior).

The two standard syslog parsers are registered on demand as built-ins
when the engine has no parser of that name (own regexes for the
well-known RFC formats).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry

log = logging.getLogger("flb.syslog")

RFC3164_REGEX = (
    r"^\<(?<pri>[0-9]+)\>(?<time>[A-Z][a-z][a-z] +\d+ \d+:\d+:\d+) "
    r"(?<host>[^ ]*) (?<ident>[a-zA-Z0-9_/.\-]*)"
    r"(?:\[(?<pid>[0-9]+)\])?[^:]*: *(?<message>.*)$"
)
RFC5424_REGEX = (
    r"^\<(?<pri>[0-9]{1,5})\>1 (?<time>[^ ]+) (?<host>[^ ]+) "
    r"(?<ident>[^ ]+) (?<pid>[-0-9]+) (?<msgid>[^ ]+) "
    r"(?<extradata>\[.*\]|-) (?<message>.+)$"
)


def ensure_syslog_parsers(engine) -> None:
    """Register the built-in rfc3164/rfc5424 parsers if absent."""
    if "syslog-rfc3164" not in engine.parsers:
        engine.parser("syslog-rfc3164", Format="regex", Regex=RFC3164_REGEX,
                      Time_Key="time", Time_Format="%b %d %H:%M:%S",
                      Time_Keep="true")
    if "syslog-rfc5424" not in engine.parsers:
        engine.parser("syslog-rfc5424", Format="regex", Regex=RFC5424_REGEX,
                      Time_Key="time",
                      Time_Format="%Y-%m-%dT%H:%M:%S.%L%z",
                      Time_Keep="true")


@registry.register
class SyslogInput(InputPlugin):
    name = "syslog"
    description = "syslog server (rfc3164/rfc5424)"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("mode", "str", default="unix_udp"),
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=5140),
        ConfigMapEntry("path", "str"),
        ConfigMapEntry("parser", "str", default="syslog-rfc3164"),
        ConfigMapEntry("unix_perm", "str"),
        ConfigMapEntry("raw_message_key", "str"),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None
        if engine is not None:
            ensure_syslog_parsers(engine)
            self._parser = engine.parsers.get(self.parser)
            if self._parser is None:
                raise ValueError(f"syslog: unknown parser {self.parser!r}")

    def _emit(self, engine, payload: bytes) -> None:
        out = bytearray()
        n = 0
        for raw in payload.split(b"\n"):
            line = raw.rstrip(b"\r").decode("utf-8", "replace")
            if not line:
                continue
            got = self._parser.do(line)
            if got is None:
                log.debug("syslog: unparseable message dropped")
                continue
            body, ts = got
            if self.raw_message_key:
                body[self.raw_message_key] = line
            out += encode_event(body, ts if ts not in (None, 0)
                                else now_event_time())
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)

    async def start_server(self, engine) -> None:
        from ..core.tls import server_context

        mode = (self.mode or "unix_udp").lower()
        plugin = self
        tls_ctx = server_context(self.instance)
        if mode in ("udp", "unix_udp"):
            if tls_ctx is not None:
                # never downgrade silently: TLS has no datagram mode here
                raise ValueError(
                    f"syslog: tls is not supported in {mode} mode"
                )
            class Proto(asyncio.DatagramProtocol):
                def datagram_received(self, data, addr):
                    plugin._emit(engine, data)

            loop = asyncio.get_running_loop()
            if mode == "udp":
                transport, _ = await loop.create_datagram_endpoint(
                    Proto, local_addr=(self.listen, self.port)
                )
                self.bound_port = transport.get_extra_info("sockname")[1]
            else:
                import socket as _socket

                self._unlink_stale()
                sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_DGRAM)
                sock.bind(self.path)
                sock.setblocking(False)
                self._apply_perm()
                transport, _ = await loop.create_datagram_endpoint(
                    Proto, sock=sock
                )
            try:
                await asyncio.Event().wait()
            finally:
                transport.close()
            return

        async def handle(reader, writer):
            pending = b""
            try:
                while True:
                    data = await reader.read(65536)
                    if not data:
                        break
                    pending += data
                    if b"\n" in pending:
                        head, _, pending = pending.rpartition(b"\n")
                        self._emit(engine, head)
            finally:
                if pending.strip():
                    self._emit(engine, pending)
                writer.close()

        if mode == "tcp":
            server = await asyncio.start_server(handle, self.listen,
                                                self.port, ssl=tls_ctx)
            self.bound_port = server.sockets[0].getsockname()[1]
        else:  # unix_tcp
            self._unlink_stale()
            server = await asyncio.start_unix_server(handle, path=self.path,
                                                     ssl=tls_ctx)
            self._apply_perm()
        async with server:
            await server.serve_forever()

    def _unlink_stale(self) -> None:
        """A previous run's socket file blocks bind (EADDRINUSE)."""
        import os

        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _apply_perm(self) -> None:
        if self.unix_perm:
            import os

            try:
                os.chmod(self.path, int(str(self.unix_perm), 8))
            except (OSError, ValueError):
                log.warning("syslog: cannot apply unix_perm %r", self.unix_perm)
