"""Self-telemetry + protocol extras: in_fluentbit_metrics,
in_fluentbit_logs, in_statsd, out_syslog, processor_template,
processor cumulative_to_delta.

Reference: plugins/in_fluentbit_metrics (internal cmetrics → the
metrics pipeline), plugins/in_fluentbit_logs (the agent's own logs
self-ingested, flb_log_pipeline_enable src/flb_engine.c:922-924),
plugins/in_statsd (UDP statsd datagrams), plugins/out_syslog (rfc5424
framing over tcp/udp), plugins/processor_template,
plugins/processor_cumulative_to_delta (counter → delta conversion).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import time
from typing import Dict, List, Optional, Tuple

from ..codec.chunk import EVENT_TYPE_METRICS
from ..codec.events import encode_event, iter_events, now_event_time
from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.plugin import (
    FlushResult,
    InputPlugin,
    OutputPlugin,
    ProcessorPlugin,
    registry,
)
from ..core.record_accessor import Template
from ..core.upstream import close_quietly

log = logging.getLogger("flb")


@registry.register
class FluentbitMetricsInput(InputPlugin):
    """Internal metrics flow AS DATA through the pipeline."""

    name = "fluentbit_metrics"
    description = "scrape the engine's internal metrics into the pipeline"
    config_map = [
        ConfigMapEntry("scrape_interval", "time", default="2"),
        ConfigMapEntry("scrape_on_start", "bool", default=False),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.scrape_interval or 2)
        if self.scrape_on_start and engine is not None:
            self.collect(engine)

    def collect(self, engine) -> None:
        payload = packb(engine.metrics.to_msgpack_obj())
        engine.input_event_append(
            self.instance, self.instance.tag, payload, EVENT_TYPE_METRICS,
            n_records=len(list(engine.metrics.metrics())),
        )


class _PipelineLogHandler(logging.Handler):
    def __init__(self, plugin):
        super().__init__()
        self.plugin = plugin
        self.buffer: List[dict] = []

    def emit(self, record: logging.LogRecord) -> None:
        if self.plugin._emitting:
            return  # the ingest path itself may log: no recursion
        try:
            self.buffer.append({
                "message": record.getMessage(),
                "level": record.levelname.lower(),
                "logger": record.name,
            })
        except Exception:  # pragma: no cover
            # stdlib Handler.emit contract: a logging sink must never
            # raise into the logging caller (here: arbitrary __str__
            # failures via record.getMessage())
            pass  # fbtpu-lint: allow(swallowed-error)


@registry.register
class FluentbitLogsInput(InputPlugin):
    """The agent's own log stream, self-ingested."""

    name = "fluentbit_logs"
    description = "self-ingest the engine's own logs"
    collect_interval = 0.5

    def init(self, instance, engine) -> None:
        self._emitting = False
        self._handler = _PipelineLogHandler(self)
        logging.getLogger("flb").addHandler(self._handler)

    def exit(self) -> None:
        logging.getLogger("flb").removeHandler(self._handler)

    def collect(self, engine) -> None:
        buffered, self._handler.buffer = self._handler.buffer, []
        if not buffered:
            return
        self._emitting = True
        try:
            out = bytearray()
            for body in buffered:
                out += encode_event(body, now_event_time())
            engine.input_log_append(
                self.instance, self.instance.tag, bytes(out), len(buffered)
            )
        finally:
            self._emitting = False


@registry.register
class StatsdInput(InputPlugin):
    """UDP statsd datagrams → records."""

    name = "statsd"
    description = "statsd UDP server"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=8125),
        ConfigMapEntry("metrics", "bool", default=False),
    ]

    TYPES = {"c": "counter", "g": "gauge", "ms": "timer", "s": "set",
             "h": "histogram"}

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    def _parse(self, line: str) -> Optional[dict]:
        # name:value|type[|@rate]
        if ":" not in line or "|" not in line:
            return None
        name, _, rest = line.partition(":")
        parts = rest.split("|")
        if len(parts) < 2:
            return None
        tname = self.TYPES.get(parts[1].strip())
        if tname is None:
            return None
        body: Dict[str, object] = {"name": name.strip(), "type": tname}
        try:
            v = parts[0].strip()
            body["value"] = float(v) if tname != "set" else v
        except ValueError:
            return None
        for extra in parts[2:]:
            if extra.startswith("@"):
                try:
                    body["sample_rate"] = float(extra[1:])
                except ValueError:
                    pass
        return body

    def _emit_payload(self, engine, data: bytes) -> None:
        out = bytearray()
        n = 0
        for raw in data.split(b"\n"):
            line = raw.strip().decode("utf-8", "replace")
            if not line:
                continue
            body = self._parse(line)
            if body is None:
                log.debug("statsd: malformed metric %r", line)
                continue
            out += encode_event(body, now_event_time())
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)

    async def start_server(self, engine) -> None:
        plugin = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                plugin._emit_payload(engine, data)

        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=(self.listen, self.port)
        )
        self.bound_port = transport.get_extra_info("sockname")[1]
        try:
            await asyncio.Event().wait()
        finally:
            transport.close()


@registry.register
class SyslogOutput(OutputPlugin):
    """rfc5424 framing to a remote syslog endpoint (tcp/udp)."""

    name = "syslog"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=514),
        ConfigMapEntry("mode", "str", default="udp"),
        ConfigMapEntry("syslog_format", "str", default="rfc5424"),
        ConfigMapEntry("syslog_severity_key", "str"),
        ConfigMapEntry("syslog_hostname_key", "str"),
        ConfigMapEntry("syslog_appname_key", "str"),
        ConfigMapEntry("syslog_message_key", "str", default="log"),
    ]

    SEVERITIES = {"emerg": 0, "alert": 1, "crit": 2, "error": 3, "err": 3,
                  "warning": 4, "warn": 4, "notice": 5, "info": 6,
                  "debug": 7}

    def init(self, instance, engine) -> None:
        self._writer = None

    def format_message(self, ev, tag: str) -> bytes:
        body = ev.body if isinstance(ev.body, dict) else {}
        sev = 6
        if self.syslog_severity_key:
            sev = self.SEVERITIES.get(
                str(body.get(self.syslog_severity_key, "info")).lower(), 6)
        pri = 1 * 8 + sev  # facility user-level
        host = str(body.get(self.syslog_hostname_key or "", "") or "-")
        app = str(body.get(self.syslog_appname_key or "", "") or tag)
        msg = str(body.get(self.syslog_message_key or "log", ""))
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ev.ts_float))
        frac = int((ev.ts_float % 1) * 1e6)
        return (f"<{pri}>1 {ts}.{frac:06d}Z {host} {app} - - - "
                f"{msg}").encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        msgs = [self.format_message(ev, tag) for ev in iter_events(data)]
        mode = (self.mode or "udp").lower()
        try:
            if mode == "udp":
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                for m in msgs:
                    s.sendto(m, (self.host, self.port))
                s.close()
            else:
                if self._writer is None or self._writer.is_closing():
                    _r, self._writer = await asyncio.wait_for(
                        asyncio.open_connection(self.host, self.port), 10
                    )
                for m in msgs:
                    # octet-counted framing (rfc6587)
                    self._writer.write(str(len(m)).encode() + b" " + m)
                await asyncio.wait_for(self._writer.drain(), 30)
        except (OSError, asyncio.TimeoutError):
            if self._writer is not None:
                close_quietly(self._writer)  # never leak the broken socket
            self._writer = None
            return FlushResult.RETRY
        return FlushResult.OK


@registry.register
class TemplateProcessor(ProcessorPlugin):
    """plugins/processor_template: render a new field from a template
    with record-accessor variables."""

    name = "template"
    description = "add a field rendered from a template"
    config_map = [
        ConfigMapEntry("key", "str"),
        ConfigMapEntry("template", "str"),
    ]

    def init(self, instance, engine) -> None:
        if not self.key or self.template is None:
            raise ValueError("template processor requires key + template")
        self._tpl = Template(self.template)

    def process_logs(self, events: list, tag: str, engine) -> list:
        from ..codec.events import LogEvent

        out = []
        for ev in events:
            if not isinstance(ev.body, dict):
                out.append(ev)
                continue
            body = dict(ev.body)
            body[self.key] = self._tpl.render(record=ev.body, tag=tag)
            out.append(LogEvent(ev.timestamp, body, ev.metadata, raw=None))
        return out


@registry.register
class CumulativeToDeltaProcessor(ProcessorPlugin):
    """plugins/processor_cumulative_to_delta: convert counter samples
    from cumulative totals to per-snapshot deltas (monotonic resets
    pass the new value through, the standard delta convention)."""

    name = "cumulative_to_delta"
    description = "convert cumulative counters to deltas"
    config_map = []

    def init(self, instance, engine) -> None:
        self._prev: Dict[Tuple[str, tuple], float] = {}

    def process_metrics(self, payloads: list, tag: str, engine) -> list:
        for payload in payloads:
            for m in payload.get("metrics", []):
                if m.get("type") != "counter":
                    continue
                for s in m.get("values", []):
                    key = (m.get("name", ""), tuple(s.get("labels", [])))
                    cur = float(s.get("value", 0.0))
                    prev = self._prev.get(key)
                    self._prev[key] = cur
                    if prev is None or cur < prev:  # first sample / reset
                        s["value"] = cur
                    else:
                        s["value"] = cur - prev
        return payloads
