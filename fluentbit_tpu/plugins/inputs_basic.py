"""Basic inputs: dummy, lib, random, stdin, head, exec.

Reference: plugins/in_dummy (bench generator with rate/copies/samples,
in_dummy.c:514-548), plugins/in_lib (embedding injection), plugins/in_random,
plugins/in_head, plugins/in_exec, plugins/in_stdin.
"""

from __future__ import annotations

import json
import logging
import os
import random as _random
import subprocess
import sys

from ..codec.events import encode_event, now_event_time
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry

log = logging.getLogger("flb")


@registry.register
class DummyInput(InputPlugin):
    """Generates synthetic records (the benchmark source).

    Reference options (plugins/in_dummy/in_dummy.c:514-548): dummy (JSON
    message), rate (records/sec), copies (records per tick), samples (stop
    after N), start_time_sec/nsec, fixed_timestamp, flush_on_startup.
    """

    name = "dummy"
    config_map = [
        ConfigMapEntry("dummy", "str", default='{"message":"dummy"}'),
        ConfigMapEntry("rate", "int", default=1),
        ConfigMapEntry("copies", "int", default=1),
        ConfigMapEntry("samples", "int", default=0),
        ConfigMapEntry("metadata", "str", default="{}"),
        ConfigMapEntry("start_time_sec", "int", default=-1),
        ConfigMapEntry("start_time_nsec", "int", default=-1),
        ConfigMapEntry("fixed_timestamp", "bool", default="false"),
        ConfigMapEntry("flush_on_startup", "bool", default="false"),
    ]

    def init(self, instance, engine) -> None:
        self._ins = instance
        try:
            self._body = json.loads(self.dummy)
        except json.JSONDecodeError:
            self._body = {"message": "dummy"}
        try:
            self._meta = json.loads(self.metadata) or {}
        except json.JSONDecodeError:
            self._meta = {}
        self._emitted = 0
        # high rates cannot ride the timer (asyncio resolution ~ms, the
        # round-1 load-generation ceiling): cap the tick frequency at
        # 100 Hz and emit rate×interval records per tick in ONE batched
        # append (they share the tick's timestamp, like `copies`)
        if self.rate > 100:
            self.collect_interval = 0.01
            self._per_tick = max(1, int(round(self.rate * 0.01)))
        else:
            self.collect_interval = 1.0 / max(1, self.rate)
            self._per_tick = 1
        if self.start_time_sec >= 0:
            self._fixed_ts = EventTime(self.start_time_sec,
                                       max(0, self.start_time_nsec))
        elif self.fixed_timestamp:
            self._fixed_ts = now_event_time()
        else:
            self._fixed_ts = None
        if self.flush_on_startup:
            self.collect(engine)

    def collect(self, engine) -> None:
        if self.samples and self._emitted >= self.samples:
            return
        ts = self._fixed_ts or now_event_time()
        n = self.copies * self._per_tick
        if self.samples:
            n = min(n, self.samples - self._emitted)
        buf = b"".join(
            encode_event(dict(self._body), ts, dict(self._meta)) for _ in range(n)
        )
        ret = engine.input_log_append(self._ins, self._ins.tag, buf, n)
        if ret >= 0:  # -1 = rejected by backpressure: don't burn the budget
            self._emitted += n


@registry.register
class LibInput(InputPlugin):
    """Embedding-mode injection (plugins/in_lib): records arrive via
    flb_lib_push as JSON text; accepts a JSON object, array of objects, or
    NDJSON lines."""

    name = "lib"

    def init(self, instance, engine) -> None:
        self._ins = instance
        self._engine = engine

    def push(self, data) -> int:
        """flb_lib_push equivalent. Returns records ingested."""
        if isinstance(data, bytes):
            data = data.decode("utf-8", "replace")
        records = []
        data = data.strip()
        if not data:
            return 0
        try:
            obj = json.loads(data)
            if isinstance(obj, list):
                # reference in_lib accepts [ts, map] pairs and arrays of maps
                if len(obj) == 2 and isinstance(obj[0], (int, float)) and isinstance(obj[1], dict):
                    records.append((obj[0], obj[1]))
                else:
                    for item in obj:
                        if isinstance(item, dict):
                            records.append((None, item))
                        elif (
                            isinstance(item, list) and len(item) == 2
                            and isinstance(item[0], (int, float)) and isinstance(item[1], dict)
                        ):
                            records.append((item[0], item[1]))
            elif isinstance(obj, dict):
                records.append((None, obj))
        except json.JSONDecodeError:
            for line in data.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                    if isinstance(obj, dict):
                        records.append((None, obj))
                except json.JSONDecodeError:
                    continue
        if not records:
            return 0
        buf = b"".join(
            encode_event(body, EventTime.from_float(ts) if ts is not None else None)
            for ts, body in records
        )
        ret = self._engine.input_log_append(self._ins, self._ins.tag, buf, len(records))
        return max(0, ret)  # -1 (backpressure) → 0 ingested, like flb_lib_push


@registry.register
class RandomInput(InputPlugin):
    """plugins/in_random: emits {"rand_value": N} at interval."""

    name = "random"
    config_map = [
        ConfigMapEntry("samples", "int", default=-1),
        ConfigMapEntry("interval_sec", "int", default=1),
        ConfigMapEntry("interval_nsec", "int", default=0),
    ]

    def init(self, instance, engine) -> None:
        self._ins = instance
        self._emitted = 0
        self.collect_interval = max(0.001, self.interval_sec + self.interval_nsec / 1e9)

    def collect(self, engine) -> None:
        if self.samples >= 0 and self._emitted >= self.samples:
            return
        buf = encode_event({"rand_value": _random.getrandbits(63)})
        engine.input_log_append(self._ins, self._ins.tag, buf, 1)
        self._emitted += 1


@registry.register
class StdinInput(InputPlugin):
    """plugins/in_stdin: NDJSON/raw lines from stdin (used by CLI mode)."""

    name = "stdin"
    collect_interval = 0.05
    config_map = [
        ConfigMapEntry("parser", "str"),
        ConfigMapEntry("buffer_size", "size", default="16k"),
    ]

    def init(self, instance, engine) -> None:
        self._ins = instance
        self._eof = False
        self._partial = ""  # line fragment straddling two reads
        os.set_blocking(sys.stdin.fileno(), False)

    def collect(self, engine) -> None:
        if self._eof:
            return
        try:
            chunk = sys.stdin.read()
        except (BlockingIOError, ValueError):
            return
        if chunk is None:  # non-blocking stream: no data yet
            return
        if chunk == "":  # EOF — flush any trailing partial line
            self._eof = True
            chunk = "\n" if self._partial else ""
        data = self._partial + chunk
        if data.endswith("\n") or self._eof:
            self._partial = ""
            lines = data.splitlines()
        else:
            parts = data.splitlines(keepends=False)
            self._partial = parts[-1] if parts else ""
            lines = parts[:-1]
        records = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
                if not isinstance(obj, dict):
                    obj = {"log": line}
            except json.JSONDecodeError:
                obj = {"log": line}
            records.append(obj)
        if records:
            buf = b"".join(encode_event(r) for r in records)
            engine.input_log_append(self._ins, self._ins.tag, buf, len(records))


@registry.register
class HeadInput(InputPlugin):
    """plugins/in_head: reads the first N bytes/lines of a file per tick."""

    name = "head"
    config_map = [
        ConfigMapEntry("file", "str"),
        ConfigMapEntry("buf_size", "size", default="256"),
        ConfigMapEntry("interval_sec", "int", default=1),
        ConfigMapEntry("interval_nsec", "int", default=0),
        ConfigMapEntry("split_line", "bool", default="false"),
        ConfigMapEntry("lines", "int", default=0),
        ConfigMapEntry("add_path", "bool", default="false"),
        ConfigMapEntry("key", "str", default="head"),
    ]

    def init(self, instance, engine) -> None:
        self._ins = instance
        self.collect_interval = max(0.001, self.interval_sec + self.interval_nsec / 1e9)

    def collect(self, engine) -> None:
        if not self.file:
            return
        try:
            with open(self.file, "rb") as f:
                if self.lines and self.lines > 0:
                    content_lines = []
                    for _ in range(self.lines):
                        ln = f.readline()
                        if not ln:
                            break
                        content_lines.append(ln.decode("utf-8", "replace").rstrip("\n"))
                    bodies = (
                        [{f"line{i}": ln for i, ln in enumerate(content_lines)}]
                        if not self.split_line
                        else [{self.key: ln} for ln in content_lines]
                    )
                else:
                    data = f.read(self.buf_size).decode("utf-8", "replace")
                    bodies = [{self.key: data}]
        except OSError:
            return
        for body in bodies:
            if self.add_path:
                body["path"] = self.file
            engine.input_log_append(self._ins, self._ins.tag, encode_event(body), 1)


@registry.register
class ExecInput(InputPlugin):
    """plugins/in_exec: runs a command per tick, one record per output line."""

    name = "exec"
    config_map = [
        ConfigMapEntry("command", "str"),
        ConfigMapEntry("interval_sec", "int", default=1),
        ConfigMapEntry("interval_nsec", "int", default=0),
        ConfigMapEntry("oneshot", "bool", default="false"),
        ConfigMapEntry("exit_after_oneshot", "bool", default="false"),
        ConfigMapEntry("key", "str", default="exec"),
    ]

    def init(self, instance, engine) -> None:
        self._ins = instance
        self._ran = False
        self.collect_interval = max(0.001, self.interval_sec + self.interval_nsec / 1e9)

    def collect(self, engine) -> None:
        if not self.command or (self.oneshot and self._ran):
            return
        self._ran = True
        try:
            out = subprocess.run(
                self.command, shell=True, capture_output=True, timeout=30
            ).stdout.decode("utf-8", "replace")
        except Exception:
            # a dead collect tick is recoverable, an invisible one is
            # not: surface why the command produced nothing
            log.warning("in_exec command failed: %r", self.command,
                        exc_info=True)
            return
        records = [{self.key: line} for line in out.splitlines() if line]
        if records:
            buf = b"".join(encode_event(r) for r in records)
            engine.input_log_append(self._ins, self._ins.tag, buf, len(records))
        if self.oneshot and self.exit_after_oneshot:
            engine.request_stop()
