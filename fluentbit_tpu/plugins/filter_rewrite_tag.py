"""filter_rewrite_tag — re-tag records by regex rule and re-emit.

Reference: plugins/filter_rewrite_tag/rewrite_tag.c. Rules are
``Rule <$key> <regex> <new_tag_template> <keep>``; the FIRST matching
rule wins (process_record, :356-385); the new tag is composed by the
record-accessor template with access to regex captures ($0..$9), $TAG,
$TAG[n] and record fields (:393); the record is re-emitted under the new
tag through a per-instance hidden ``emitter`` input (:407, created with
alias ``emitter_for_<name>``, :245-260) and re-enters the full pipeline;
the original is kept or dropped per the rule's keep flag (:375).

Device path: when every rule regex compiles to a DFA and the append is
large, the per-rule match matrix runs vectorized on device
(fluentbit_tpu.ops.grep); capture extraction + tag composition run on
the CPU only for the first matching rule of each matched record.

Batched fast path (``process_batch``): on the engine's raw ingest path
the per-rule match matrix comes from the native one-pass DFA straight
off chunk bytes — no Python decode at all. Records whose winning rule
has a tag-static template (no ``$0..$9`` captures, no record fields)
group into per-tag span gathers (native compact) and re-emit in one
emitter append per tag; only records whose template needs captures or
record fields decode individually. The own-emitter re-entry guard uses
the chunk's source input, so re-emitted records pass through untouched
at chunk granularity.
"""

from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np

from ..codec.events import reencode_event
from ..core.config import ConfigMapEntry, parse_bool
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor, Template
from ..regex import FlbRegex


log = logging.getLogger("flb")


def _to_text(v) -> Optional[str]:
    """String values only — flb_ra_key_regex_match returns no-match for
    non-STR msgpack types (src/flb_ra_key.c:418)."""
    if isinstance(v, str):
        return v
    return None


class RewriteRule:
    __slots__ = ("ra", "regex", "template", "keep")

    def __init__(self, key: str, pattern: str, new_tag: str, keep):
        self.ra = RecordAccessor(key)
        self.regex = FlbRegex(pattern)
        self.template = Template(new_tag)
        self.keep = parse_bool(keep)


@registry.register
class RewriteTagFilter(FilterPlugin):
    name = "rewrite_tag"
    description = "re-tag records by regex and re-emit through the pipeline"
    # process_batch re-emits through the hidden emitter: once it has
    # run, the engine must not restart the raw chain from scratch
    # (decoded-tail continuation instead — engine._ingest_raw)
    stateful_batch = True
    config_map = [
        ConfigMapEntry("rule", "slist", multiple=True, slist_max_split=3,
                       desc="<$key> <regex> <new_tag> <keep>"),
        ConfigMapEntry("emitter_name", "str"),
        ConfigMapEntry("emitter_storage.type", "str", default="memory"),
        ConfigMapEntry("emitter_mem_buf_limit", "str", default="10M"),
        ConfigMapEntry("tpu.enable", "bool", default=True),
        ConfigMapEntry("tpu_batch_records", "int", default=64),
        ConfigMapEntry("tpu_max_record_len", "int", default=512),
    ]

    def init(self, instance, engine) -> None:
        if not self.rule:
            raise ValueError("rewrite_tag requires at least one Rule")
        self.rules: List[RewriteRule] = []
        for parts in self.rule:
            if len(parts) != 4:
                raise ValueError(f"rewrite_tag: invalid rule {parts!r}")
            self.rules.append(RewriteRule(*parts))
        self._engine = engine
        self.emitter = None
        if engine is not None:
            name = self.emitter_name or f"emitter_for_{instance.display_name}"
            ins = engine.hidden_input(
                "emitter",
                owner=instance,
                alias=name,
                mem_buf_limit=self.emitter_mem_buf_limit,
                **{"storage.type": self.emitter_storage_type},
            )
            self.emitter = ins.plugin
        self._program = None
        if (
            self.tpu_enable
            and all(r.regex.dfa is not None for r in self.rules)
        ):
            try:
                from ..ops import device
                from ..ops.grep import program_for

                self._program = program_for(
                    tuple(r.regex.pattern for r in self.rules),
                    self.tpu_max_record_len,
                )
                device.wait()  # bounded; CPU path serves until attached
                self._program.try_ready()
            except Exception:
                log.debug("rewrite_tag device program unavailable; "
                          "host path serves", exc_info=True)
                self._program = None
        # batched raw path: native per-rule DFA matrix off chunk bytes
        # (simple top-level keys only); rules with tag-static templates
        # render once per chunk, the rest decode per matched record
        self._batch_tables = None
        self._batch_static = [r.template.static_for_tag
                              for r in self.rules]
        if self.emitter is not None and all(
            r.regex.dfa is not None and not r.ra.parts
            for r in self.rules
        ):
            from .. import native as _native

            if _native.available():
                try:
                    self._batch_tables = _native.GrepTables(
                        [(r.ra.head.encode("utf-8"), r.regex.dfa)
                         for r in self.rules]
                    )
                except Exception:
                    log.warning(
                        "rewrite_tag native table build failed; "
                        "batched fast path disabled", exc_info=True)
                    self._batch_tables = None
        self._report_shrink(engine)

    def _report_shrink(self, engine) -> None:
        """fluentbit_grep_shrink_* compile-outcome counters for the
        rewrite_tag match matrices — the rule DFAs compile through the
        same reducer as filter_grep's (FlbRegex → compile_dfa), so
        their savings land in the same dashboard family, labelled by
        plugin (PERF.md "shrink"); the table-bytes side is accounted in
        the fbtpu-xray budget report (ANALYSIS.md "fbtpu-xray")."""
        if engine is None or getattr(engine, "m_shrink_states", None) \
                is None:
            return
        label = (self.name,)
        elim_s = elim_c = 0
        for r in self.rules:
            dfa = r.regex.dfa
            st = getattr(dfa, "shrink", None) if dfa is not None \
                else None
            if st is not None:
                elim_s += st.states_eliminated
                elim_c += st.classes_eliminated
        if elim_s:
            engine.m_shrink_states.inc(elim_s, label)
        if elim_c:
            engine.m_shrink_classes.inc(elim_c, label)

    # -- matching --

    def _values_matrix(self, events: list) -> List[List[Optional[str]]]:
        vals: List[List[Optional[str]]] = []
        for rule in self.rules:
            ra = rule.ra
            vals.append([
                _to_text(ra.get(ev.body)) if isinstance(ev.body, dict) else None
                for ev in events
            ])
        return vals

    def _device_match_matrix(self, values) -> np.ndarray:
        """mask[R, B]: rule r's regex matches record b's field value."""
        from ..ops.batch import assemble, bucket_size

        R = len(self.rules)
        B = len(values[0])
        Bp = bucket_size(B, max_len=self.tpu_max_record_len)
        staged = [
            assemble(
                [v.encode("utf-8") if v is not None else None
                 for v in values[r]],
                self.tpu_max_record_len, Bp,
            )
            for r in range(R)
        ]
        batch = np.stack([s.batch for s in staged])
        lengths = np.stack([s.lengths for s in staged])

        def host_twin():
            # bit-exact host fallback (fbtpu-armor DeviceLane): the
            # same per-row regex the overflow fix-up below applies
            out = np.zeros((R, B), dtype=bool)
            for r in range(R):
                rx = self.rules[r].regex
                for i, v in enumerate(values[r]):
                    if v is not None:
                        out[r, i] = rx.match(v)
            return out

        from ..ops import fault

        lane = fault.lane("grep")  # the DFA plane's fault domain
        mask = lane.run(
            lambda: np.asarray(self._program.match(batch, lengths)),
            host_twin,
        )
        mask = np.array(mask[:, :B])
        for r, s in enumerate(staged):
            rx = self.rules[r].regex
            for i in s.overflow:
                mask[r, i] = rx.match(values[r][i])
        return mask

    def _first_match_cpu(self, body):
        """Per-record rule scan, break on first match (process_record)."""
        if not isinstance(body, dict):
            return None, None
        for rule in self.rules:
            v = _to_text(rule.ra.get(body))
            if v is None:
                continue
            caps = rule.regex.search_captures(v)
            if caps is not None:
                return rule, caps
        return None, None

    def _render_tag(self, ev, rule, captures, tag: str):
        """→ rendered new tag, or None when the record cannot be
        re-emitted (failed translation / no emitter) — the caller then
        keeps the original, mirroring the reference's no-match return on
        translation failure."""
        if self.emitter is None:
            return None
        new_tag = rule.template.render(record=ev.body, tag=tag,
                                       captures=captures)
        return new_tag or None

    # -- batched raw-chunk execution (engine process_batch hook) --

    def can_process_batch(self) -> bool:
        return self._batch_tables is not None

    def process_batch(self, chunk):
        from .. import native
        from ..codec.events import decode_events, fast_count_records

        # own-emitter re-entry passes through untouched at chunk
        # granularity (the i_ins == ctx->ins_emitter recursion guard)
        if chunk.src is not None and chunk.src is self.emitter.instance:
            n = chunk.n
            if n is None:
                n = fast_count_records(chunk.as_bytes())
                if n is None:
                    return None
            return (n, chunk.data, n)
        tag = chunk.tag
        data = chunk.as_bytes()
        got = native.grep_match(data, self._batch_tables, n_hint=chunk.n)
        if got is None:
            return None
        mask, offsets, n = got
        if n == 0:
            return (0, data, 0)
        any_match = mask.any(axis=0)
        if not any_match.any():
            return (n, data, n)
        # first matching rule per record (process_record's break)
        first = np.where(any_match, mask.argmax(axis=0), -1)
        keep = np.ones(n, dtype=bool)
        # new_tag → {"mask": members, "drop": non-keep members,
        #            "first": first contributing record index}
        # — groups re-emit in first-seen order, matching the per-record
        # path's pending-dict insertion order
        groups: dict = {}

        def group(new_tag, b):
            ent = groups.get(new_tag)
            if ent is None:
                ent = groups[new_tag] = {
                    "mask": np.zeros(n, dtype=bool),
                    "drop": np.zeros(n, dtype=bool),
                    "first": b,
                }
            ent["first"] = min(ent["first"], b)
            return ent

        need_record: list = []
        for r, rule in enumerate(self.rules):
            idx = np.nonzero(first == r)[0]
            if len(idx) == 0:
                continue
            if not self._batch_static[r]:
                need_record.extend(int(b) for b in idx)
                continue
            new_tag = rule.template.render(tag=tag)
            if not new_tag:
                continue  # untranslatable tag: keep the original
            ent = group(new_tag, int(idx[0]))
            ent["mask"][idx] = True
            if not rule.keep:
                ent["drop"][idx] = True
        # records whose winning rule needs captures or record fields:
        # decode just those spans and run the per-record rule walk
        for b in need_record:
            span = bytes(data[offsets[b]: offsets[b + 1]])
            try:
                ev = decode_events(span)[0]
            except (ValueError, IndexError):
                return None
            rule = captures = None
            for r, rl in enumerate(self.rules):
                if not mask[r, b]:
                    continue
                v = _to_text(rl.ra.get(ev.body)) \
                    if isinstance(ev.body, dict) else None
                if v is None:
                    continue
                captures = rl.regex.search_captures(v)
                if captures is not None:
                    rule = rl
                    break
            if rule is None:
                continue
            new_tag = self._render_tag(ev, rule, captures, tag)
            if new_tag is None:
                continue
            ent = group(new_tag, b)
            ent["mask"][b] = True
            if not rule.keep:
                ent["drop"][b] = True
        emitted = 0
        for new_tag, ent in sorted(groups.items(),
                                   key=lambda kv: kv[1]["first"]):
            m = ent["mask"]
            count = int(m.sum())
            payload = native.compact(data, offsets, m)
            if payload is None:
                payload = b"".join(
                    data[offsets[i]: offsets[i + 1]]
                    for i in np.nonzero(m)[0]
                )
            try:
                rc = self.emitter.add_record(new_tag, payload, count)
            except Exception:
                # earlier groups are already committed: letting this
                # raise would decline the batch and the decoded-tail
                # rerun would re-emit them a second time — degrade a
                # failed group to the backpressure outcome instead
                # (originals kept; fbtpu-lint batch-commit-replay)
                log.exception("rewrite_tag emitter append failed for "
                              "tag %r; originals kept", new_tag)
                rc = -1
            if rc < 0:
                # backpressure: keep the originals (reference keeps the
                # record when in_emitter refuses it) — drop flags for
                # this group are simply never applied
                continue
            emitted += count
            keep &= ~ent["drop"]
        if emitted and chunk.engine is not None:
            chunk.engine.m_filter_emit.inc(
                emitted, (self.instance.display_name,))
        n_keep = int(keep.sum())
        if n_keep == n:
            return (n, data, n)
        if n_keep == 0:
            return (0, b"", n)
        out = native.compact(data, offsets, keep)
        if out is None:
            out = b"".join(
                data[offsets[i]: offsets[i + 1]]
                for i in np.nonzero(keep)[0]
            )
        return (n_keep, out, n)

    def filter(self, events: list, tag: str, engine) -> tuple:
        # records re-entering from our OWN emitter are never re-matched
        # (the i_ins == ctx->ins_emitter check, rewrite_tag.c): without
        # it a rule whose rewritten record still matches — e.g. the new
        # tag also satisfies `match *` — recurses until the stack dies
        if (
            engine is not None
            and self.emitter is not None
            and getattr(engine, "_ingest_src", None)
            is self.emitter.instance
        ):
            return (FilterResult.NOTOUCH, events)
        from ..ops import device

        # platform gate FIRST (same as filter_grep): on a CPU jax
        # backend the batch assemble + kernel launch per chunk costs
        # far more than the host regex scan it replaces
        use_device = (
            self._program is not None
            and len(events) >= self.tpu_batch_records
            and device.platform() not in (None, "cpu")
            and self._program.try_ready()
        )
        if use_device:
            values = self._values_matrix(events)
            mask = self._device_match_matrix(values)
        keep = [True] * len(events)
        # emits BATCH per rendered tag: one emitter append per (tag)
        # group instead of one full pipeline re-entry per record
        # (in_emitter_add_record per record measured ~80µs — the append
        # overhead, not the matching, dominated)
        pending: dict = {}  # new_tag → [(index, raw)]
        for b, ev in enumerate(events):
            if use_device:
                rule = captures = None
                for r in range(len(self.rules)):
                    if mask[r, b]:
                        captures = self.rules[r].regex.search_captures(
                            values[r][b]
                        )
                        if captures is not None:
                            rule = self.rules[r]
                            break
            else:
                rule, captures = self._first_match_cpu(ev.body)
            if rule is None:
                continue
            new_tag = self._render_tag(ev, rule, captures, tag)
            if new_tag is None:
                continue
            raw = ev.raw if ev.raw is not None else reencode_event(ev)
            pending.setdefault(new_tag, []).append((b, raw))
            if not rule.keep:
                keep[b] = False
        emitted = 0
        for new_tag, items in pending.items():
            data = b"".join(raw for _, raw in items)
            if self.emitter.add_record(new_tag, data, len(items)) < 0:
                # backpressure: keep the originals (reference keeps the
                # record when in_emitter refuses it)
                for b, _ in items:
                    keep[b] = True
            else:
                emitted += len(items)
        if emitted and engine is not None:
            engine.m_filter_emit.inc(emitted,
                                     (self.instance.display_name,))
        if all(keep):
            return (FilterResult.NOTOUCH, events)
        kept = [ev for b, ev in enumerate(events) if keep[b]]
        return (FilterResult.MODIFIED, kept)
