"""filter_log_to_metrics — derive metrics (and sketches) from log records.

Reference: plugins/filter_log_to_metrics/log_to_metrics.c. Modes
counter/gauge/histogram (:566-612 bucket setup) with grep-style
pre-filter rules in LEGACY first-rule-decides semantics
(grep_filter_data, :345-372), labels from ``label_field`` record
accessors + static ``add_label`` pairs, optional ``kubernetes_mode``
auto-labels (namespace_name/pod_name/container_name/docker_id/pod_id,
:45-49), required ``tag`` (:726), namespace default "log_metric"
(log_to_metrics.h:54). Metrics are emitted as METRICS-type events
through a hidden emitter input (flb_input_metrics_append, :633) so they
flow the metrics pipeline to any metrics-capable output.

North-star additions (BASELINE.md config 4 — no reference equivalent):
``metric_mode cardinality`` maintains a device HyperLogLog over
``value_field`` and emits the cardinality estimate as a gauge;
``metric_mode frequency`` maintains a device count-min sketch and emits
per-value estimated counts for the hottest observed values. Sketch
updates run as fused jit kernels (hash + scatter) over staged batches
(fluentbit_tpu.ops.sketch); on a device mesh the sketch merge is
pmax/psum over ICI.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from ..codec.chunk import EVENT_TYPE_METRICS
from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.metrics import MetricsRegistry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor
from .filter_grep import legacy_keep, parse_grep_rules

log = logging.getLogger("flb")

K8S_LABELS = ("namespace_name", "pod_name", "container_name",
              "docker_id", "pod_id")


def _stringify(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


@registry.register
class LogToMetricsFilter(FilterPlugin):
    name = "log_to_metrics"
    description = "generate metrics from log records"
    # process_batch bumps counters and emits snapshots: once it has
    # run, the engine must not restart the raw chain from scratch
    # (decoded-tail continuation instead — engine._ingest_raw)
    stateful_batch = True
    config_map = [
        ConfigMapEntry("regex", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("exclude", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("metric_mode", "str", default="counter"),
        ConfigMapEntry("value_field", "str"),
        ConfigMapEntry("metric_name", "str"),
        ConfigMapEntry("metric_namespace", "str", default="log_metric"),
        ConfigMapEntry("metric_subsystem", "str", default=""),
        ConfigMapEntry("metric_description", "str"),
        ConfigMapEntry("kubernetes_mode", "bool", default=False),
        ConfigMapEntry("add_label", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("label_field", "str", multiple=True),
        ConfigMapEntry("bucket", "str", multiple=True),
        ConfigMapEntry("tag", "str"),
        ConfigMapEntry("emitter_name", "str"),
        ConfigMapEntry("emitter_mem_buf_limit", "str", default="10M"),
        ConfigMapEntry("discard_logs", "bool", default=False),
        ConfigMapEntry("flush_interval_sec", "int", default=0),
        ConfigMapEntry("flush_interval_nsec", "int", default=0),
        # sketch modes (north-star additions)
        ConfigMapEntry("sketch_precision", "int", default=14,
                       desc="HLL precision p (2^p registers)"),
        ConfigMapEntry("sketch_depth", "int", default=4),
        ConfigMapEntry("sketch_width", "int", default=16384),
        ConfigMapEntry("frequency_top_k", "int", default=10),
        ConfigMapEntry("tpu_max_record_len", "int", default=256),
    ]

    MODES = ("counter", "gauge", "histogram", "cardinality", "frequency")

    def init(self, instance, engine) -> None:
        if not self.metric_name:
            raise ValueError("log_to_metrics: metric_name is not set")
        if not self.metric_description:
            raise ValueError("log_to_metrics: metric_description is not set")
        if not self.tag:
            raise ValueError("log_to_metrics: Metric tag is not set")
        self.mode = (self.metric_mode or "counter").lower()
        if self.mode not in self.MODES:
            raise ValueError(f"log_to_metrics: unknown mode {self.metric_mode!r}")
        if self.mode in ("gauge", "histogram", "cardinality", "frequency") \
                and not self.value_field:
            raise ValueError(f"log_to_metrics: {self.mode} requires value_field")

        # grep-style pre-filter, property order preserved — shares
        # filter_grep's rule machinery (grep_filter_data is the same
        # legacy logic)
        self.rules = parse_grep_rules(instance.properties)

        # labels: [k8s...] + label_field RAs + add_label statics
        self.label_keys: List[str] = []
        self._label_ras: List[RecordAccessor] = []
        self._k8s_ra = RecordAccessor("$kubernetes") if self.kubernetes_mode else None
        if self.kubernetes_mode:
            self.label_keys.extend(K8S_LABELS)
        for lf in self.label_field or []:
            name = lf[1:] if lf.startswith("$") else lf
            self.label_keys.append(name.replace("['", "_").replace("']", "")
                                   .replace(".", "_"))
            self._label_ras.append(
                RecordAccessor(lf if lf.startswith("$") else "$" + lf)
            )
        self._static_labels: List[str] = []
        for pair in self.add_label or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) != 2:
                raise ValueError(f"log_to_metrics: invalid add_label {pair!r}")
            self.label_keys.append(parts[0])
            self._static_labels.append(parts[1])

        self.value_ra = RecordAccessor(
            self.value_field if str(self.value_field or "").startswith("$")
            else "$" + (self.value_field or "value")
        ) if self.value_field else None

        # the cmt context emitted through the pipeline
        self.cmt = MetricsRegistry()
        ns, sub = self.metric_namespace, self.metric_subsystem or ""
        keys = tuple(self.label_keys)
        if self.mode == "counter":
            self.metric = self.cmt.counter(ns, sub, self.metric_name,
                                           self.metric_description, keys)
        elif self.mode == "gauge":
            self.metric = self.cmt.gauge(ns, sub, self.metric_name,
                                         self.metric_description, keys)
        elif self.mode == "histogram":
            buckets = [float(b) for b in (self.bucket or [])] or None
            from ..core.metrics import DEFAULT_BUCKETS

            self.metric = self.cmt.histogram(
                ns, sub, self.metric_name, self.metric_description, keys,
                tuple(buckets) if buckets else DEFAULT_BUCKETS,
            )
        elif self.mode == "cardinality":
            self.metric = self.cmt.gauge(ns, sub, self.metric_name,
                                         self.metric_description, keys)
            from ..ops.sketch import HyperLogLog

            self.hll = HyperLogLog(p=self.sketch_precision)
        else:  # frequency
            self.metric = self.cmt.gauge(
                ns, sub, self.metric_name, self.metric_description,
                keys + ("value",),
            )
            from ..ops.sketch import CountMin

            self.cms = CountMin(depth=self.sketch_depth,
                                width=self.sketch_width)
            self._freq_candidates: Dict[bytes, None] = {}

        # batched raw path (process_batch): counter mode whose labels
        # are all static vectorizes as one native DFA pass over chunk
        # bytes + a single batched inc — no Python decode. The ≥1 keep
        # rule requirement makes non-map bodies consistently excluded
        # on both paths (they can never match, and the first Regex rule
        # then decides False — same verdict the dict-body check gives).
        self._batch_tables = None
        if (
            self.mode == "counter"
            and not self._label_ras
            and not self.kubernetes_mode
            and self.rules
            and any(not r.is_exclude for r in self.rules)
            and all(r.dfa is not None and not r.ra.parts
                    for r in self.rules)
        ):
            from .. import native as _native

            if _native.available():
                try:
                    self._batch_tables = _native.GrepTables(
                        [(r.ra.head.encode("utf-8"), r.dfa)
                         for r in self.rules]
                    )
                except Exception:
                    log.warning(
                        "log_to_metrics native table build failed; "
                        "batched fast path disabled", exc_info=True)
                    self._batch_tables = None
        self._report_shrink(engine)

        self.emitter = None
        self._dirty = False
        self._interval = 0.0
        if engine is not None:
            name = self.emitter_name or f"emitter_for_{instance.display_name}"
            ins = engine.hidden_input(
                "emitter", owner=instance, alias=name,
                mem_buf_limit=self.emitter_mem_buf_limit,
            )
            self.emitter = ins.plugin
            interval = self.flush_interval_sec + self.flush_interval_nsec / 1e9
            self._interval = interval
            if interval > 0:
                # timer-driven emission (the reference's flush timer):
                # piggyback an interval collector on the hidden emitter
                # so throttled updates are flushed even when no further
                # records arrive
                ins.plugin.collect_interval = interval
                ins.plugin.collect = (
                    lambda _engine: self._emit_snapshot() if self._dirty
                    else None
                )

    def _report_shrink(self, engine) -> None:
        """fluentbit_grep_shrink_* compile-outcome counters for the
        selector-rule DFAs — compiled through the same reducer as
        filter_grep's (FlbRegex → compile_dfa), so their savings land
        in the same dashboard family, labelled by plugin (PERF.md
        "shrink"); table bytes are accounted in the fbtpu-xray budget
        report (ANALYSIS.md "fbtpu-xray")."""
        if engine is None or getattr(engine, "m_shrink_states", None) \
                is None:
            return
        label = (self.name,)
        elim_s = elim_c = 0
        for r in self.rules:
            st = getattr(r.dfa, "shrink", None) if r.dfa is not None \
                else None
            if st is not None:
                elim_s += st.states_eliminated
                elim_c += st.classes_eliminated
        if elim_s:
            engine.m_shrink_states.inc(elim_s, label)
        if elim_c:
            engine.m_shrink_classes.inc(elim_c, label)

    # -- per-record helpers --

    def _selected(self, body: dict) -> bool:
        """LEGACY grep logic: first rule decides (grep_filter_data)."""
        return legacy_keep(self.rules, body)

    def _labels(self, body: dict) -> tuple:
        out: List[str] = []
        if self._k8s_ra is not None:
            k8s = self._k8s_ra.get(body) or {}
            for key in K8S_LABELS:
                v = k8s.get(key) if isinstance(k8s, dict) else None
                out.append(_stringify(v) if v is not None else "")
        for ra in self._label_ras:
            v = ra.get(body)
            out.append(_stringify(v) if v is not None else "")
        out.extend(self._static_labels)
        return tuple(out)

    def _value(self, body: dict) -> Optional[float]:
        v = self.value_ra.get(body) if self.value_ra else None
        if isinstance(v, bool) or v is None:
            return None
        try:
            return float(v)
        except (TypeError, ValueError):
            return None

    def _value_bytes(self, body: dict) -> Optional[bytes]:
        v = self.value_ra.get(body) if self.value_ra else None
        if v is None:
            return None
        return _stringify(v).encode("utf-8") if not isinstance(v, str) \
            else v.encode("utf-8")

    # -- batched raw-chunk execution (engine process_batch hook) --

    def can_process_batch(self) -> bool:
        return self._batch_tables is not None

    def process_batch(self, chunk):
        from .. import native
        from .filter_grep import legacy_keep_mask

        data = chunk.as_bytes()
        got = native.grep_match(data, self._batch_tables, n_hint=chunk.n)
        if got is None:
            return None
        mask, _offsets, n = got
        count = int(legacy_keep_mask(self.rules, mask).sum()) if n else 0
        if count:
            # one batched inc == n per-record incs on the same (static)
            # label set; the snapshot emits once per append, exactly
            # like the per-record path
            self.metric.inc(count, tuple(self._static_labels))
            self._dirty = True
            if self.emitter is not None and self._interval <= 0:
                try:
                    self._emit_snapshot()
                except Exception:
                    # the inc above is already committed: a raise here
                    # would decline the batch and the decoded-tail
                    # rerun would inc AGAIN for the same records —
                    # degrade to a deferred snapshot (_dirty stays set)
                    # to keep counter effects exactly-once
                    # (fbtpu-lint batch-commit-replay)
                    log.exception(
                        "log_to_metrics snapshot emit failed; "
                        "snapshot deferred")
        if self.discard_logs:
            return (0, b"", n)
        return (n, data, n)

    # -- the filter --

    def filter(self, events: list, tag: str, engine) -> tuple:
        selected = [
            ev for ev in events
            if isinstance(ev.body, dict) and self._selected(ev.body)
        ]
        if self.mode == "counter":
            for ev in selected:
                self.metric.inc(1, self._labels(ev.body))
        elif self.mode == "gauge":
            for ev in selected:
                v = self._value(ev.body)
                if v is not None:
                    self.metric.set(v, self._labels(ev.body))
        elif self.mode == "histogram":
            for ev in selected:
                v = self._value(ev.body)
                if v is not None:
                    self.metric.observe(v, self._labels(ev.body))
        elif self.mode == "cardinality":
            self._update_hll(selected)
        else:
            self._update_cms(selected)

        if selected:
            self._dirty = True
            # interval 0 (default): emit on every append; with an
            # interval configured, the emitter collector timer emits
            if self.emitter is not None and self._interval <= 0:
                self._emit_snapshot()
        if self.discard_logs:
            return (FilterResult.MODIFIED, [])
        return (FilterResult.NOTOUCH, events)

    def _emit_snapshot(self) -> None:
        payload = packb(self.cmt.to_msgpack_obj())
        self.emitter.add_event(
            self.tag, payload, EVENT_TYPE_METRICS,
            n_records=len(list(self.cmt.metrics())),
        )
        self._dirty = False

    # -- sketch modes --

    def _staged(self, values: List[Optional[bytes]]):
        from ..ops.batch import assemble, bucket_size

        return assemble(values, self.tpu_max_record_len,
                        bucket_size(len(values),
                                    max_len=self.tpu_max_record_len))

    def _update_hll(self, selected: list) -> None:
        vals = [self._value_bytes(ev.body) for ev in selected]
        vals = [v for v in vals if v is not None]
        if not vals:
            return
        b = self._staged(vals)
        self.hll.update(b.batch, b.lengths)
        for i in b.overflow:  # oversized values resolve on CPU
            self.hll.add_cpu(vals[i])
        labels = self._labels(selected[0].body) if self.label_keys else ()
        self.metric.set(self.hll.estimate(), labels)

    def _update_cms(self, selected: list) -> None:
        vals = [self._value_bytes(ev.body) for ev in selected]
        vals = [v for v in vals if v is not None]
        if not vals:
            return
        b = self._staged(vals)
        self.cms.update(b.batch, b.lengths)
        for i in b.overflow:  # oversized values resolve on CPU
            self.cms.add_cpu(vals[i])
        for v in vals:
            # delete-and-reinsert refreshes recency (dict preserves
            # insertion order; plain reassignment would not move the key)
            self._freq_candidates.pop(v, None)
            self._freq_candidates[v] = None
        # bound candidate memory: keep most recently seen 4096 values
        if len(self._freq_candidates) > 4096:
            drop = len(self._freq_candidates) - 4096
            for k in list(self._freq_candidates)[:drop]:
                del self._freq_candidates[k]
        base = self._labels(selected[0].body) if self.label_keys else ()
        # one device→host table copy for the whole candidate set
        ests = self.cms.query_many(list(self._freq_candidates))
        top = sorted(
            zip(ests, self._freq_candidates), reverse=True,
        )[: self.frequency_top_k]
        # the gauge reports the CURRENT top-k only: stale series from
        # values that dropped out must not linger in the exposition
        self.metric.clear()
        for est, v in top:
            self.metric.set(
                est, base + (v.decode("utf-8", "replace"),)
            )
