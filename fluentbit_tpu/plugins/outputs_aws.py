"""AWS outputs: s3 (fstore-staged uploads), cloudwatch_logs.

Reference: plugins/out_s3 (6452 LoC — buffered uploads staged through
fstore, s3_key_format with $TAG/time expansion, use_put_object vs
multipart) and plugins/out_cloudwatch_logs (PutLogEvents API). Both
sign with SigV4 (utils.aws) using the env/profile credential chain.
This build implements the put-object upload path (multipart's
CreateMultipartUpload/UploadPart dance needs nothing new from the
framework — the fstore staging and signing layers are the same — and
is left as an endpoint-parity TODO); ``endpoint`` points at any
S3-compatible HTTP endpoint (path-style).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from ..codec.events import decode_events
from ..core.config import ConfigMapEntry
from ..core.fstore import FStore
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..utils import aws as _aws
from .outputs_basic import format_json_lines
from .outputs_http_based import _dumps


async def _http_request(ins, host: str, port: int, method: str, path: str,
                        headers: Dict[str, str], body: bytes,
                        timeout: float = 30.0, quote_path: bool = True,
                        use_tls: Optional[bool] = None) -> Tuple[int, bytes]:
    from urllib.parse import quote

    from ..core.tls import open_connection

    # honor the instance's tls.* properties (never plaintext when
    # `tls on`). SigV4 callers keep quote_path=True: the request line
    # must carry the SAME encoding the signature was computed over
    # (identical quote + safe set); Google-style method paths
    # (…/entries:write) pass quote_path=False and pre-safe paths.
    if quote_path:
        path = quote(path, safe="/-_.~")
    if use_tls:
        import asyncio as _aio
        import ssl as _ssl

        ctx = _ssl.create_default_context()
        reader, writer = await _aio.wait_for(
            _aio.open_connection(host, port, ssl=ctx), 10.0
        )
    else:
        reader, writer = await open_connection(ins, host, port, timeout=10.0)
    try:
        lines = [f"{method} {path} HTTP/1.1", f"Host: {host}:{port}",
                 f"Content-Length: {len(body)}", "Connection: close"]
        lines += [f"{k}: {v}" for k, v in headers.items()]
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
        await asyncio.wait_for(writer.drain(), timeout)
        data = b""
        while True:
            chunk = await asyncio.wait_for(reader.read(65536), timeout)
            if not chunk:
                break
            data += chunk
        head, _, resp_body = data.partition(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        return status, resp_body
    finally:
        try:
            writer.close()
        except Exception:
            pass


@registry.register
class S3Output(OutputPlugin):
    name = "s3"
    description = "Amazon S3 (fstore-staged put-object uploads)"
    config_map = [
        ConfigMapEntry("bucket", "str"),
        ConfigMapEntry("region", "str", default="us-east-1"),
        ConfigMapEntry("endpoint", "str"),
        ConfigMapEntry("s3_key_format", "str",
                       default="/fluent-bit-logs/$TAG/%Y/%m/%d/%H_%M_%S"),
        ConfigMapEntry("total_file_size", "size", default="100M"),
        ConfigMapEntry("upload_timeout", "time", default="10m"),
        ConfigMapEntry("store_dir", "str", default="/tmp/fluent-bit/s3"),
        ConfigMapEntry("use_put_object", "bool", default=True),
        ConfigMapEntry("compression", "str"),
    ]

    def init(self, instance, engine) -> None:
        if not self.bucket:
            raise ValueError("s3: bucket is required")
        algo = (self.compression or "").lower()
        if algo in ("gzip", "zstd"):
            from ..utils import compression_available
            if not compression_available(algo):
                raise ValueError(f"s3: {algo} codec unavailable on "
                                 "this host")
        self._fstore = FStore(self.store_dir)
        self._stream = self._fstore.stream(f"s3-{instance.name}")
        self._opened: Dict[str, float] = {}  # tag → first-append time
        self._creds = _aws.get_credentials() or _aws.Credentials("", "")

    def _endpoint(self) -> Tuple[str, int]:
        ep = self.endpoint or f"s3.{self.region}.amazonaws.com"
        ep = ep.replace("http://", "").replace("https://", "")
        host, _, port = ep.partition(":")
        from ..core.tls import client_context

        default = 443 if client_context(self.instance) is not None else 80
        return host, int(port or default)

    def _key_for(self, tag: str) -> str:
        # strftime FIRST: a '%' inside the tag must never be read as a
        # time directive
        key = time.strftime(self.s3_key_format or "/", time.gmtime())
        key = key.replace("$TAG", tag)
        return key if key.startswith("/") else "/" + key

    async def _upload(self, tag: str, payload: bytes) -> FlushResult:
        algo = (self.compression or "").lower()
        if algo in ("gzip", "zstd"):  # reference out_s3 codecs
            from ..utils import compress

            payload = compress(algo, payload)
        host, port = self._endpoint()
        path = f"/{self.bucket}{self._key_for(tag)}"
        url = f"http://{host}:{port}{path}"
        headers = _aws.sigv4_headers("PUT", url, self.region, "s3",
                                     payload, self._creds)
        try:
            status, _body = await _http_request(self.instance, host,
                                                port, "PUT", path,
                                                headers, payload)
        except (OSError, asyncio.TimeoutError, ValueError, IndexError):
            return FlushResult.RETRY
        if 200 <= status < 300:
            return FlushResult.OK
        return FlushResult.RETRY if status >= 500 else FlushResult.ERROR

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        """Stage into fstore; upload when the buffer reaches
        total_file_size or upload_timeout elapses (out_s3's buffering
        contract — delivery is deferred, OK acknowledges staging)."""
        from urllib.parse import quote as _q

        fname = _q(tag, safe="")  # reversible: no cross-tag collisions
        f = self._stream.get(fname) or self._stream.create(fname)
        f.append(format_json_lines(data).encode() + b"\n")
        self._opened.setdefault(tag, time.monotonic())
        due = (
            f.size >= self.total_file_size
            or time.monotonic() - self._opened[tag] >= self.upload_timeout
        )
        if not due:
            return FlushResult.OK
        payload = f.content()
        res = await self._upload(tag, payload)
        if res == FlushResult.OK:
            f.delete()
            self._opened.pop(tag, None)
        return res

    def drain(self, engine) -> None:
        """Shutdown: upload everything still staged. Runs on the engine
        loop (the _main drain phase); the futures join the pending set
        so the grace period waits for them."""
        if getattr(engine, "loop", None) is None:
            return
        from urllib.parse import unquote as _uq

        for f in self._stream.files():
            tag = _uq(f.name)
            payload = f.content()
            if not payload:
                continue

            async def _final(tag=tag, payload=payload, f=f):
                if await self._upload(tag, payload) == FlushResult.OK:
                    f.delete()

            fut = asyncio.ensure_future(_final())
            engine._pending_flushes.add(fut)
            fut.add_done_callback(engine._pending_flushes.discard)


@registry.register
class CloudwatchLogsOutput(OutputPlugin):
    name = "cloudwatch_logs"
    description = "Amazon CloudWatch Logs (PutLogEvents)"
    config_map = [
        ConfigMapEntry("log_group_name", "str"),
        ConfigMapEntry("log_stream_name", "str"),
        ConfigMapEntry("region", "str", default="us-east-1"),
        ConfigMapEntry("endpoint", "str"),
        ConfigMapEntry("log_key", "str"),
    ]

    def init(self, instance, engine) -> None:
        if not self.log_group_name or not self.log_stream_name:
            raise ValueError(
                "cloudwatch_logs: log_group_name + log_stream_name required"
            )
        self._creds = _aws.get_credentials() or _aws.Credentials("", "")

    def format(self, data: bytes, tag: str) -> bytes:
        events = []
        for ev in decode_events(data):
            if self.log_key and isinstance(ev.body, dict):
                msg = str(ev.body.get(self.log_key, ""))
            else:
                msg = _dumps(ev.body)
            events.append({"timestamp": int(ev.ts_float * 1000),
                           "message": msg})
        return _dumps({
            "logGroupName": self.log_group_name,
            "logStreamName": self.log_stream_name,
            "logEvents": events,
        }).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        body = self.format(data, tag)
        ep = self.endpoint or f"logs.{self.region}.amazonaws.com"
        ep = ep.replace("http://", "").replace("https://", "")
        host, _, port = ep.partition(":")
        port = int(port or 80)
        url = f"http://{host}:{port}/"
        extra = {"X-Amz-Target": "Logs_20140328.PutLogEvents",
                 "Content-Type": "application/x-amz-json-1.1"}
        headers = _aws.sigv4_headers("POST", url, self.region, "logs",
                                     body, self._creds, headers=extra)
        headers.update(extra)
        try:
            status, _b = await _http_request(self.instance, host, port,
                                             "POST", "/", headers, body)
        except (OSError, asyncio.TimeoutError, ValueError, IndexError):
            return FlushResult.RETRY
        if 200 <= status < 300:
            return FlushResult.OK
        return FlushResult.RETRY if status >= 500 else FlushResult.ERROR
