"""AWS outputs: s3 (fstore-staged put-object + multipart uploads),
cloudwatch_logs.

Reference: plugins/out_s3 (6452 LoC — buffered uploads staged through
fstore, s3_key_format with $TAG/time expansion, use_put_object vs
multipart) and plugins/out_cloudwatch_logs (PutLogEvents API). Both
sign with SigV4 (utils.aws) using the env/profile credential chain.

Multipart mirrors s3.c:82-123 / s3_multipart.c: staged bytes reaching
``upload_chunk_size`` become an UploadPart on an upload created with
``POST ?uploads=`` (XML UploadId); reaching ``total_file_size`` or
``upload_timeout`` completes with the part manifest. Upload state
(UploadId + part ETags) persists in the staging file's fstore metadata,
so a restart RESUMES the open multipart upload instead of orphaning it
(get_upload/create_upload state machine, s3.c:82-123). ``endpoint``
points at any S3-compatible HTTP endpoint (path-style).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from .. import failpoints as _fp
from ..codec.events import decode_events
from ..core.config import ConfigMapEntry
from ..core.fstore import FStore
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..core.upstream import close_quietly
from ..utils import aws as _aws
from .outputs_basic import format_json_lines
from .outputs_http_based import _dumps


async def _http_request(ins, host: str, port: int, method: str, path: str,
                        headers: Dict[str, str], body: bytes,
                        timeout: float = 30.0, quote_path: bool = True,
                        use_tls: Optional[bool] = None) -> Tuple[int, bytes]:
    from urllib.parse import quote

    from ..core.tls import open_connection

    # honor the instance's tls.* properties (never plaintext when
    # `tls on`). SigV4 callers keep quote_path=True: the request line
    # must carry the SAME encoding the signature was computed over
    # (identical quote + safe set); Google-style method paths
    # (…/entries:write) pass quote_path=False and pre-safe paths.
    if quote_path:
        path = quote(path, safe="/-_.~")
    if use_tls:
        import asyncio as _aio
        import ssl as _ssl

        ctx = _ssl.create_default_context()
        reader, writer = await _aio.wait_for(
            _aio.open_connection(host, port, ssl=ctx), 10.0
        )
    else:
        reader, writer = await open_connection(ins, host, port, timeout=10.0)
    try:
        lines = [f"{method} {path} HTTP/1.1", f"Host: {host}:{port}",
                 f"Content-Length: {len(body)}", "Connection: close"]
        lines += [f"{k}: {v}" for k, v in headers.items()]
        if _fp.ACTIVE:
            # FailpointError is an OSError: callers' except clauses map
            # it to RETRY exactly like a real peer reset mid-request
            _fp.fire("upstream.send")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
        await asyncio.wait_for(writer.drain(), timeout)
        if _fp.ACTIVE:
            # the nastiest window: the request was SENT (the server may
            # have acted on it) but the response is lost — redelivery
            # after this fault is where duplication bugs live
            _fp.fire("upstream.recv")
        data = b""
        while True:
            chunk = await asyncio.wait_for(reader.read(65536), timeout)
            if not chunk:
                break
            data += chunk
        head, _, resp_body = data.partition(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        return status, head, resp_body
    finally:
        close_quietly(writer)


@registry.register
class S3Output(OutputPlugin):
    name = "s3"
    description = "Amazon S3 (fstore-staged put-object uploads)"
    config_map = [
        ConfigMapEntry("bucket", "str"),
        ConfigMapEntry("region", "str", default="us-east-1"),
        ConfigMapEntry("endpoint", "str"),
        ConfigMapEntry("s3_key_format", "str",
                       default="/fluent-bit-logs/$TAG/%Y/%m/%d/%H_%M_%S"),
        ConfigMapEntry("total_file_size", "size", default="100M"),
        ConfigMapEntry("upload_chunk_size", "size", default="5242880"),
        ConfigMapEntry("upload_timeout", "time", default="10m"),
        ConfigMapEntry("store_dir", "str", default="/tmp/fluent-bit/s3"),
        ConfigMapEntry("use_put_object", "bool", default=True),
        ConfigMapEntry("compression", "str"),
    ]

    def init(self, instance, engine) -> None:
        if not self.bucket:
            raise ValueError("s3: bucket is required")
        algo = (self.compression or "").lower()
        if algo in ("gzip", "zstd"):
            from ..utils import compression_available
            if not compression_available(algo):
                raise ValueError(f"s3: {algo} codec unavailable on "
                                 "this host")
        if not self.use_put_object:
            # s3.c:1102-1126 sizing rules (5MB AWS minimum relaxed only
            # for explicitly tiny test endpoints via upload_chunk_size)
            if self.upload_chunk_size > self.total_file_size:
                raise ValueError(
                    "s3: upload_chunk_size cannot exceed total_file_size")
        self._fstore = FStore(self.store_dir)
        self._stream = self._fstore.stream(f"s3-{instance.name}")
        # staging idempotence across RETRY redelivery (ADVICE.md): the
        # engine redelivers the SAME chunk bytes after a failed part
        # upload / complete. A per-tag sidecar in its OWN stream
        # carries {digest: staged-at} for every staged-but-unacked
        # chunk: a map, not one marker (other chunks for the tag may
        # flush while one is backing off); PERSISTED (a
        # filesystem-storage chunk redelivered after a crash/restart
        # must still dedup); and OUTSIDE the staging file's meta (a
        # completed upload deletes the staging file, but a RETRY-parked
        # chunk whose bytes rode that object must still dedup when its
        # retry lands). Entries are removed when their flush resolves,
        # and expire after the engine's worst-case retry window so a
        # chunk dropped without a final flush call can never swallow a
        # later byte-identical chunk.
        self._staged_stream = self._fstore.stream(
            f"s3-{instance.name}-staged")
        self._opened: Dict[str, float] = {}  # tag → first-append time
        # staging + part sequencing is read-modify-write around an
        # await: concurrent flushes for one tag must serialize or parts
        # collide / staged bytes vanish (the engine runs one coroutine
        # per (task x route) with no semaphore by default)
        self._tag_locks: Dict[str, "asyncio.Lock"] = {}
        self._creds = _aws.get_credentials() or _aws.Credentials("", "")

    def _endpoint(self) -> Tuple[str, int]:
        ep = self.endpoint or f"s3.{self.region}.amazonaws.com"
        ep = ep.replace("http://", "").replace("https://", "")
        host, _, port = ep.partition(":")
        from ..core.tls import client_context

        default = 443 if client_context(self.instance) is not None else 80
        return host, int(port or default)

    def _key_for(self, tag: str) -> str:
        # strftime FIRST: a '%' inside the tag must never be read as a
        # time directive
        key = time.strftime(self.s3_key_format or "/", time.gmtime())
        key = key.replace("$TAG", tag)
        return key if key.startswith("/") else "/" + key

    async def _upload(self, tag: str, payload: bytes) -> FlushResult:
        algo = (self.compression or "").lower()
        if algo in ("gzip", "zstd"):  # reference out_s3 codecs
            from ..utils import compress

            payload = compress(algo, payload)
        host, port = self._endpoint()
        path = f"/{self.bucket}{self._key_for(tag)}"
        url = f"http://{host}:{port}{path}"
        self._creds = _aws.current(self._creds) or self._creds
        headers = _aws.sigv4_headers("PUT", url, self.region, "s3",
                                     payload, self._creds)
        try:
            status, _head, _body = await _http_request(self.instance, host,
                                                       port, "PUT", path,
                                                       headers, payload)
        except (OSError, asyncio.TimeoutError, ValueError, IndexError):
            return FlushResult.RETRY
        if 200 <= status < 300:
            return FlushResult.OK
        return FlushResult.RETRY if status >= 500 else FlushResult.ERROR

    # ------------------------------------------------------ multipart

    async def _s3_call(self, method: str, key: str, query: str,
                       payload: bytes) -> Tuple[int, bytes, bytes]:
        """One signed S3 request with a query string; returns
        (status, response head, response body)."""
        from urllib.parse import quote

        host, port = self._endpoint()
        raw_path = f"/{self.bucket}{key}"
        # sign over the RAW path: sigv4_headers percent-encodes it once
        # for the canonical request, and the wire path below applies the
        # SAME single encoding — pre-quoting here would double-encode
        # the signature side only (SignatureDoesNotMatch on any key
        # with a space or non-ASCII byte)
        url = f"http://{host}:{port}{raw_path}{query}"
        self._creds = _aws.current(self._creds) or self._creds
        headers = _aws.sigv4_headers(method, url, self.region, "s3",
                                     payload, self._creds)
        wire_path = quote(raw_path, safe="/-_.~") + query
        status, head, body = await _http_request(
            self.instance, host, port, method, wire_path, headers,
            payload, quote_path=False)
        return status, head, body

    async def _mp_create(self, key: str) -> Optional[str]:
        """CreateMultipartUpload (s3_multipart.c:558: POST ?uploads=);
        returns the UploadId."""
        status, _head, body = await self._s3_call("POST", key,
                                                  "?uploads=", b"")
        if not 200 <= status < 300:
            return None
        import re as _re

        m = _re.search(rb"<UploadId>([^<]+)</UploadId>", body)
        return m.group(1).decode() if m else None

    async def _mp_upload_part(self, key: str, upload_id: str, n: int,
                              payload: bytes) -> Optional[str]:
        """UploadPart (s3_multipart.c:685: PUT ?partNumber=N&uploadId=);
        returns the part's ETag."""
        if _fp.ACTIVE:
            try:
                _fp.fire("s3.upload_part")
            except _fp.FailpointError:
                return None  # part upload failed → flush returns RETRY
        status, head, _body = await self._s3_call(
            "PUT", key, f"?partNumber={n}&uploadId={upload_id}", payload)
        if not 200 <= status < 300:
            return None
        import re as _re

        m = _re.search(rb"(?im)^etag:\s*(\S+)\s*$", head)
        if m is None:
            # no ETag → the part cannot ever appear in a valid complete
            # manifest; fail the flush (RETRY) while the staged bytes
            # are still on disk
            return None
        return m.group(1).decode().strip('"')

    async def _mp_complete(self, key: str, upload_id: str,
                           parts: List[dict]) -> bool:
        """CompleteMultipartUpload (s3_multipart.c:405: POST ?uploadId=
        with the part manifest)."""
        if _fp.ACTIVE:
            try:
                _fp.fire("s3.complete")
            except _fp.FailpointError:
                # parts uploaded, completion lost: redelivery follows —
                # the ADVICE.md duplication window in its pure form
                return False
        xml = ["<CompleteMultipartUpload>"]
        for p in parts:
            xml.append(
                f"<Part><PartNumber>{p['n']}</PartNumber>"
                f"<ETag>\"{p['etag']}\"</ETag></Part>")
        xml.append("</CompleteMultipartUpload>")
        status, _head, body = await self._s3_call(
            "POST", key, f"?uploadId={upload_id}",
            "".join(xml).encode())
        # a 200 body may still carry <Error> (S3 completes lazily)
        return 200 <= status < 300 and b"<Error>" not in body

    def _staged_ttl(self, engine) -> Optional[float]:
        """Upper bound on how long the engine can still redeliver one
        chunk: the summed worst-case capped backoff over the retry
        budget (x2 + slack for scheduling). None with unlimited
        retries — redelivery can then come arbitrarily late, and the
        engine never drops the chunk short of shutdown."""
        svc = getattr(engine, "service", None)
        if svc is None:
            return 600.0
        limit = self.instance.retry_limit
        if limit is None:
            limit = svc.retry_limit
        if limit == -1:
            return None
        total = 0.0
        for k in range(1, max(1, int(limit)) + 1):
            total += min(svc.scheduler_cap,
                         svc.scheduler_base * (2 ** k)) + 1.0
        return total * 2 + 60.0

    def _persist_staged(self, fname: str, sf, staged):
        """Write the staged-digest map's sidecar (delete it when the
        map empties); returns the current sidecar file or None."""
        if staged:
            sf = sf or self._staged_stream.create(fname)
            sf.set_meta(staged)
            return sf
        if sf is not None:
            sf.delete()
        return None

    def _mp_state(self, f) -> dict:
        st = f.meta()
        return st if st.get("upload_id") else {}

    async def _mp_flush_part(self, f, tag: str,
                             final: bool) -> FlushResult:
        """Upload the staged bytes as the next part; on final, complete
        the upload with the accumulated manifest."""
        st = self._mp_state(f)
        if not st:
            key = self._key_for(tag)
            upload_id = await self._mp_create(key)
            if upload_id is None:
                return FlushResult.RETRY
            st = {"upload_id": upload_id, "key": key, "parts": []}
            f.set_meta(st)
        payload = f.content()
        if payload:
            algo = (self.compression or "").lower()
            if algo in ("gzip", "zstd"):
                from ..utils import compress

                payload = compress(algo, payload)
            n = len(st["parts"]) + 1
            if n > 10000:  # hard S3 limit (s3.c:1688)
                return FlushResult.ERROR
            etag = await self._mp_upload_part(st["key"], st["upload_id"],
                                              n, payload)
            if etag is None:
                return FlushResult.RETRY
            st["parts"].append({"n": n, "etag": etag})
            # staged bytes are uploaded: restart the staging file but
            # KEEP the upload state (restart resume reads it back)
            name = f.name
            f.delete()
            f = self._stream.create(name)
            f.set_meta(st)
        if final:
            if not st["parts"]:
                f.delete()
                self._opened.pop(tag, None)
                return FlushResult.OK
            if not await self._mp_complete(st["key"], st["upload_id"],
                                           st["parts"]):
                return FlushResult.RETRY
            f.delete()
            self._opened.pop(tag, None)
        return FlushResult.OK

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        """Stage into fstore; upload when the buffer reaches
        total_file_size or upload_timeout elapses (out_s3's buffering
        contract — delivery is deferred, OK acknowledges staging). In
        multipart mode (use_put_object off) every upload_chunk_size of
        staged bytes becomes an UploadPart immediately."""
        from urllib.parse import quote as _q

        import hashlib

        lock = self._tag_locks.setdefault(tag, asyncio.Lock())
        async with lock:
            fname = _q(tag, safe="")  # reversible: no cross-tag collisions
            f = self._stream.get(fname) or self._stream.create(fname)
            digest = hashlib.sha256(data).hexdigest()
            sf = self._staged_stream.get(fname)
            staged = dict(sf.meta()) if sf is not None else {}
            staged_orig = dict(staged)
            ttl = self._staged_ttl(engine)
            now = time.time()  # wall clock: must survive a restart
            if ttl is not None and staged:
                staged = {d: ts for d, ts in staged.items()
                          if now - ts <= ttl}  # redelivery window over
            if digest not in staged:
                f.append(format_json_lines(data).encode() + b"\n")
                staged[digest] = now
            # else: RETRY redelivery (same process or post-restart) of
            # a chunk whose bytes are already staged — or already
            # uploaded, whether the object is still open or was since
            # completed — re-appending would duplicate the records.
            # Known tradeoff: identity is CONTENT (the flush ABI
            # carries no chunk id), so a genuinely new chunk that is
            # byte-identical — same records AND same event timestamps —
            # to one still parked in RETRY dedups against it; an
            # unbounded duplication bug is traded for that corner.
            if staged != staged_orig:
                # persist BEFORE the awaited upload: a crash during the
                # network call must not leave appended bytes with an
                # unrecorded digest (restart redelivery would re-append)
                sf = self._persist_staged(fname, sf, staged)
                staged_orig = dict(staged)
            self._opened.setdefault(tag, time.monotonic())
            timed_out = (time.monotonic() - self._opened[tag]
                         >= self.upload_timeout)
            if not self.use_put_object:
                st = self._mp_state(f)
                uploaded = (len(st.get("parts", []))
                            * self.upload_chunk_size)
                final = (uploaded + f.size >= self.total_file_size
                         or timed_out)
                if final or f.size >= self.upload_chunk_size:
                    res = await self._mp_flush_part(f, tag, final)
                else:
                    res = FlushResult.OK
            else:
                due = f.size >= self.total_file_size or timed_out
                if due:
                    payload = f.content()
                    res = await self._upload(tag, payload)
                    if res == FlushResult.OK:
                        f.delete()
                        self._opened.pop(tag, None)
                else:
                    res = FlushResult.OK
            if res != FlushResult.RETRY:
                # OK (acked — no redelivery coming) or ERROR (dropped —
                # no redelivery either): a future byte-identical chunk
                # is a NEW chunk and must stage
                staged.pop(digest, None)
            if staged != staged_orig:
                sf = self._persist_staged(fname, sf, staged)
            return res

    def drain(self, engine) -> None:
        """Shutdown: upload everything still staged (completing any open
        multipart uploads). Runs on the engine loop (the _main drain
        phase); the futures join the pending set so the grace period
        waits for them."""
        if getattr(engine, "loop", None) is None:
            return
        from urllib.parse import unquote as _uq

        for f in self._stream.files():
            tag = _uq(f.name)
            lock = self._tag_locks.setdefault(tag, asyncio.Lock())
            if not self.use_put_object:
                if not f.size and not self._mp_state(f):
                    continue

                async def _final_mp(tag=tag, f=f, lock=lock):
                    async with lock:
                        await self._mp_flush_part(f, tag, final=True)

                fut = asyncio.ensure_future(_final_mp())
            else:
                if not f.size:
                    continue

                async def _final(tag=tag, f=f, lock=lock):
                    async with lock:
                        payload = f.content()
                        if payload and await self._upload(
                                tag, payload) == FlushResult.OK:
                            f.delete()

                fut = asyncio.ensure_future(_final())
            engine._pending_flushes.add(fut)
            fut.add_done_callback(engine._pending_flushes.discard)


@registry.register
class CloudwatchLogsOutput(OutputPlugin):
    name = "cloudwatch_logs"
    description = "Amazon CloudWatch Logs (PutLogEvents)"
    config_map = [
        ConfigMapEntry("log_group_name", "str"),
        ConfigMapEntry("log_stream_name", "str"),
        ConfigMapEntry("region", "str", default="us-east-1"),
        ConfigMapEntry("endpoint", "str"),
        ConfigMapEntry("log_key", "str"),
    ]

    def init(self, instance, engine) -> None:
        if not self.log_group_name or not self.log_stream_name:
            raise ValueError(
                "cloudwatch_logs: log_group_name + log_stream_name required"
            )
        self._creds = _aws.get_credentials() or _aws.Credentials("", "")

    def format(self, data: bytes, tag: str) -> bytes:
        events = []
        for ev in decode_events(data):
            if self.log_key and isinstance(ev.body, dict):
                msg = str(ev.body.get(self.log_key, ""))
            else:
                msg = _dumps(ev.body)
            events.append({"timestamp": int(ev.ts_float * 1000),
                           "message": msg})
        return _dumps({
            "logGroupName": self.log_group_name,
            "logStreamName": self.log_stream_name,
            "logEvents": events,
        }).encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        body = self.format(data, tag)
        ep = self.endpoint or f"logs.{self.region}.amazonaws.com"
        ep = ep.replace("http://", "").replace("https://", "")
        host, _, port = ep.partition(":")
        port = int(port or 80)
        url = f"http://{host}:{port}/"
        self._creds = _aws.current(self._creds) or self._creds
        extra = {"X-Amz-Target": "Logs_20140328.PutLogEvents",
                 "Content-Type": "application/x-amz-json-1.1"}
        headers = _aws.sigv4_headers("POST", url, self.region, "logs",
                                     body, self._creds, headers=extra)
        headers.update(extra)
        try:
            status, _h, _b = await _http_request(self.instance, host, port,
                                                 "POST", "/", headers, body)
        except (OSError, asyncio.TimeoutError, ValueError, IndexError):
            return FlushResult.RETRY
        if 200 <= status < 300:
            return FlushResult.OK
        return FlushResult.RETRY if status >= 500 else FlushResult.ERROR
