"""MQTT 3.1.1 server input.

Reference: plugins/in_mqtt (mqtt_prot.c — a broker-side listener, not a
client: devices CONNECT straight to the agent and PUBLISH JSON payloads;
the plugin answers CONNACK/PUBACK and appends each publish as one record
``{"topic": <topic>, ...payload keys}``, or nesting the payload map under
``payload_key`` when configured, mqtt_prot.c:126-200). QoS 0/1/2 publish
flows are acknowledged (PUBACK / PUBREC+PUBCOMP, mqtt_prot.c:302-330);
non-JSON payloads are warned and dropped, the connection stays up.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from ..core.upstream import close_quietly

log = logging.getLogger("flb.in_mqtt")

# control packet types (spec §2.2.1)
CONNECT = 1
CONNACK = 2
PUBLISH = 3
PUBACK = 4
PUBREC = 5
PUBREL = 6
PUBCOMP = 7
SUBSCRIBE = 8
SUBACK = 9
UNSUBSCRIBE = 10
UNSUBACK = 11
PINGREQ = 12
PINGRESP = 13
DISCONNECT = 14


async def _read_packet(reader):
    """One control packet → (type, flags, payload bytes)."""
    first = await reader.readexactly(1)
    ptype = first[0] >> 4
    flags = first[0] & 0x0F
    # remaining length: 1..4 continuation-bit bytes (spec §2.2.3)
    mult = 1
    length = 0
    for _ in range(4):
        b = (await reader.readexactly(1))[0]
        length += (b & 0x7F) * mult
        if not b & 0x80:
            break
        mult *= 128
    else:
        raise ValueError("malformed remaining length")
    payload = await reader.readexactly(length) if length else b""
    return ptype, flags, payload


@registry.register
class MqttInput(InputPlugin):
    name = "mqtt"
    description = "MQTT 3.1.1 server (broker-side listener)"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=1883),
        ConfigMapEntry("payload_key", "str"),
    ]

    def init(self, instance, engine) -> None:
        self._server = None
        self.bound_port: Optional[int] = None

    async def start_server(self, engine) -> None:
        from ..core.tls import server_context

        async def handle(reader, writer):
            await self._handle_conn(reader, writer, engine)

        self._server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]
        async with self._server:
            await self._server.serve_forever()

    async def _handle_conn(self, reader, writer, engine) -> None:
        connected = False
        try:
            while True:
                try:
                    ptype, flags, payload = await _read_packet(reader)
                except (asyncio.IncompleteReadError, ValueError):
                    break
                if not connected:
                    # the first packet MUST be CONNECT (mqtt_prot.c:391)
                    if ptype != CONNECT:
                        break
                    # CONNACK: session-present 0, return code 0
                    writer.write(bytes([CONNACK << 4, 2, 0, 0]))
                    await writer.drain()
                    connected = True
                    continue
                if ptype == PUBLISH:
                    if not self._handle_publish(flags, payload, writer,
                                                engine):
                        break
                    await writer.drain()
                elif ptype == PUBREL:
                    # QoS2 leg 2: answer PUBCOMP with the same packet id
                    writer.write(bytes([PUBCOMP << 4, 2]) + payload[:2])
                    await writer.drain()
                elif ptype == PINGREQ:
                    writer.write(bytes([PINGRESP << 4, 0]))
                    await writer.drain()
                elif ptype == DISCONNECT:
                    break
                elif ptype in (SUBSCRIBE, UNSUBSCRIBE):
                    # not a broker: acknowledge with failure code so
                    # well-behaved clients notice (0x80 = failure)
                    resp = SUBACK if ptype == SUBSCRIBE else UNSUBACK
                    body = payload[:2] + (b"\x80" if resp == SUBACK else b"")
                    writer.write(bytes([resp << 4, len(body)]) + body)
                    await writer.drain()
                # anything else: ignore
        except (OSError, ConnectionError):
            pass
        finally:
            close_quietly(writer)

    def _handle_publish(self, flags, payload, writer, engine) -> bool:
        qos = (flags >> 1) & 0x03
        if len(payload) < 2:
            return False
        topic_len = int.from_bytes(payload[:2], "big")
        if 2 + topic_len > len(payload):
            return False
        topic = payload[2:2 + topic_len].decode("utf-8", "replace")
        pos = 2 + topic_len
        if qos > 0:
            if pos + 2 > len(payload):
                return False
            pkt_id = payload[pos:pos + 2]
            pos += 2
            ack = PUBACK if qos == 1 else PUBREC
            writer.write(bytes([ack << 4, 2]) + pkt_id)
        msg = payload[pos:]
        try:
            obj = json.loads(msg.decode("utf-8"))
            if not isinstance(obj, dict):
                raise ValueError
        except (ValueError, UnicodeDecodeError):
            log.warning("mqtt: packet incomplete or is not JSON")
            return True  # drop the record, keep the connection
        body = {"topic": topic}
        if self.payload_key:
            body[self.payload_key] = obj
        else:
            body.update(obj)
        engine.input_log_append(
            self.instance, self.instance.tag,
            encode_event(body, now_event_time()), 1)
        return True
