"""Trace sampling processor — probabilistic and tail modes.

Reference: plugins/processor_sampling/sampling.c (mode vtable),
sampling_probabilistic.c:63-90 (deterministic trace-id percentage over
spans), sampling_tail.c:677-745 (decision window + condition check +
reconcile + re-injection via the input pipeline), and
sampling_span_registry.c (trace-keyed span registry with max_traces
eviction). Condition evaluators mirror sampling_cond_latency.c,
sampling_cond_span_count.c, sampling_cond_status_codes.c,
sampling_cond_string_attribute.c, sampling_cond_numeric_attribute.c,
sampling_cond_boolean_attribute.c and sampling_cond_trace_state.c.

Tail mode buffers every span by trace id; ``decision_wait`` after a
trace's first span arrives, its spans are evaluated against the
configured conditions — ONE matching span samples the whole trace
(check_conditions, sampling_tail.c:677) — and sampled traces are
reconciled into fresh typed payloads and re-injected through a hidden
emitter input (the flb_input_trace_append_skip_processor_stages
equivalent: the emitter carries no processors, so re-entry is
impossible by construction).
"""

from __future__ import annotations

import logging
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.plugin import ProcessorPlugin, registry

log = logging.getLogger("flb.sampling")

_STATUS = {"UNSET": 0, "OK": 1, "ERROR": 2}


def _parse_time_s(v, default: float) -> float:
    """'30s' / '500ms' / '2m' / bare numbers → seconds."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    m = re.fullmatch(r"([0-9.]+)\s*(ms|s|m|h)?", s)
    if not m:
        raise ValueError(f"invalid time value {v!r}")
    n = float(m.group(1))
    return n * {"ms": 1e-3, None: 1.0, "s": 1.0, "m": 60.0,
                "h": 3600.0}[m.group(2)]


def _latency_ms(span: dict) -> Optional[int]:
    start = int(span.get("startTimeUnixNano", 0) or 0)
    end = int(span.get("endTimeUnixNano", 0) or 0)
    if start > end:
        return None  # sampling_cond_latency.c:34 — malformed: no match
    return (end - start) // 1_000_000


class _Cond:
    """One evaluator; check(entry_spans, span) -> bool."""

    def __init__(self, cfg: Dict[str, Any]):
        self.cfg = cfg

    def check(self, trace_spans: List[dict], span: dict) -> bool:
        raise NotImplementedError


class _CondLatency(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.low = int(cfg.get("threshold_ms_low", 0) or 0)
        self.high = int(cfg.get("threshold_ms_high", 0) or 0)
        if not self.low and not self.high:
            raise ValueError(
                "latency condition needs threshold_ms_low or "
                "threshold_ms_high")

    def check(self, trace_spans, span):
        lat = _latency_ms(span)
        if lat is None:
            return False
        return bool((self.low and lat <= self.low)
                    or (self.high and lat >= self.high))


class _CondStatusCodes(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        codes = cfg.get("status_codes")
        if not codes:
            raise ValueError("status_code condition needs 'status_codes'")
        self.codes = set()
        for c in codes:
            cu = str(c).upper()
            if cu not in _STATUS:
                raise ValueError(f"invalid status code {c!r}")
            self.codes.add(_STATUS[cu])

    def check(self, trace_spans, span):
        code = int((span.get("status") or {}).get("code", 0) or 0)
        return code in self.codes


class _CondSpanCount(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        if "min_spans" not in cfg:
            raise ValueError("span_count condition needs 'min_spans'")
        self.min = int(cfg["min_spans"])
        self.max = int(cfg.get("max_spans", 2**31 - 1))

    def check(self, trace_spans, span):
        return self.min <= len(trace_spans) <= self.max


class _CondStringAttribute(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.key = cfg.get("key")
        if not self.key:
            raise ValueError("string_attribute condition needs 'key'")
        self.match_type = str(cfg.get("match_type", "strict")).lower()
        if self.match_type not in ("strict", "exists", "regex"):
            raise ValueError(
                f"invalid match_type {cfg.get('match_type')!r}")
        values = cfg.get("values") or []
        if not values and self.match_type != "exists":
            raise ValueError("string_attribute condition needs 'values'")
        if self.match_type == "regex":
            from ..regex import FlbRegex

            self.patterns = [FlbRegex(str(v)) for v in values]
        else:
            self.values = {str(v) for v in values}

    def check(self, trace_spans, span):
        attrs = span.get("attributes") or {}
        if self.key not in attrs:
            return False
        if self.match_type == "exists":
            return True
        v = attrs[self.key]
        if not isinstance(v, str):
            return False
        if self.match_type == "regex":
            return any(p.match(v) for p in self.patterns)
        return v in self.values


class _CondNumericAttribute(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.key = cfg.get("key")
        if not self.key:
            raise ValueError("numeric_attribute condition needs 'key'")
        self.match_type = str(cfg.get("match_type", "strict")).lower()
        if self.match_type not in ("strict", "exists"):
            raise ValueError(
                f"invalid match_type {cfg.get('match_type')!r}")
        if self.match_type == "strict":
            if "min_value" not in cfg or "max_value" not in cfg:
                raise ValueError(
                    "numeric_attribute condition needs 'min_value' and "
                    "'max_value'")
            self.min = int(cfg["min_value"])
            self.max = int(cfg["max_value"])

    def check(self, trace_spans, span):
        attrs = span.get("attributes") or {}
        if self.key not in attrs:
            return False
        if self.match_type == "exists":
            return True
        v = attrs[self.key]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return False
        return self.min <= v <= self.max


class _CondBooleanAttribute(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.key = cfg.get("key")
        if not self.key:
            raise ValueError("boolean_attribute condition needs 'key'")
        if "value" not in cfg:
            raise ValueError("boolean_attribute condition needs 'value'")
        v = cfg["value"]
        if isinstance(v, str):
            v = v.strip().lower() == "true"
        self.value = bool(v)

    def check(self, trace_spans, span):
        v = (span.get("attributes") or {}).get(self.key)
        if not isinstance(v, bool):
            return False
        return v is self.value


class _CondTraceState(_Cond):
    def __init__(self, cfg):
        super().__init__(cfg)
        values = cfg.get("values")
        if not values:
            raise ValueError("trace_state condition needs 'values'")
        self.values = {str(v).strip() for v in values}

    def check(self, trace_spans, span):
        state = span.get("traceState") or ""
        for kv in state.split(","):
            if kv.strip() in self.values:
                return True
        return False


_COND_TYPES = {
    "latency": _CondLatency,
    "status_code": _CondStatusCodes,
    "status_codes": _CondStatusCodes,
    "span_count": _CondSpanCount,
    "string_attribute": _CondStringAttribute,
    "numeric_attribute": _CondNumericAttribute,
    "boolean_attribute": _CondBooleanAttribute,
    "trace_state": _CondTraceState,
}


def make_condition(cfg: Dict[str, Any]) -> _Cond:
    t = str(cfg.get("type", "")).lower()
    if t not in _COND_TYPES:
        raise ValueError(f"unknown sampling condition type {cfg.get('type')!r}")
    return _COND_TYPES[t](cfg)


class _TraceEntry:
    __slots__ = ("ts_created", "tag", "spans")

    def __init__(self, ts: float, tag: str):
        self.ts_created = ts
        self.tag = tag
        # (resource_attrs, scope, span) trios preserving origin context
        self.spans: List[Tuple[dict, dict, dict]] = []


def _trace_key(span: dict) -> str:
    tid = span.get("traceId") or b""
    return tid.hex() if isinstance(tid, bytes) else str(tid)


def _reconcile(entry: _TraceEntry) -> dict:
    """Group a trace's spans back into resourceSpans/scopeSpans trees
    (reconcile_and_create_ctrace_optimized, sampling_tail.c:694-735)."""
    rs_list: List[dict] = []
    rs_index: Dict[str, dict] = {}
    for resource, scope, span in entry.spans:
        rkey = repr(sorted((resource or {}).items()))
        rs = rs_index.get(rkey)
        if rs is None:
            rs = {"resource": resource or {}, "scopeSpans": [],
                  "_scopes": {}}
            rs_index[rkey] = rs
            rs_list.append(rs)
        skey = ((scope or {}).get("name", ""),
                (scope or {}).get("version", ""))
        ss = rs["_scopes"].get(skey)
        if ss is None:
            ss = {"scope": scope or {}, "spans": []}
            rs["_scopes"][skey] = ss
            rs["scopeSpans"].append(ss)
        ss["spans"].append(span)
    for rs in rs_list:
        del rs["_scopes"]
    return {"resourceSpans": rs_list}


def _trace_id_fraction(span: dict) -> float:
    """First 8 bytes of trace_id, big-endian, mod 1e6 / 1e4 — the
    deterministic hash of sampling_probabilistic.c:63-90 (same trace
    always gets the same verdict across agents)."""
    tid = span.get("traceId") or b""
    if isinstance(tid, str):
        try:
            tid = bytes.fromhex(tid)
        except ValueError:
            tid = b""
    if len(tid) < 8:
        return 0.0
    return (int.from_bytes(tid[:8], "big") % 1_000_000) / 10_000.0


@registry.register
class SamplingProcessor(ProcessorPlugin):
    """processor_sampling: probabilistic (logs + traces) and tail
    (traces) sampling."""

    name = "sampling"
    description = "probabilistic and tail trace sampling"
    config_map = [
        ConfigMapEntry("type", "str", default="probabilistic"),
        ConfigMapEntry("sampling_settings", "raw"),
        ConfigMapEntry("conditions", "raw"),
        ConfigMapEntry("sampling_settings_sampling_percentage", "double",
                       default=10.0),
        ConfigMapEntry("percentage", "double"),
        ConfigMapEntry("seed", "int"),
    ]

    def init(self, instance, engine) -> None:
        import random

        self.mode = (self.type or "probabilistic").lower()
        self._lock = threading.Lock()
        self._emitter = None
        settings = instance.prop("sampling_settings") or {}
        if isinstance(settings, str):
            import json

            settings = json.loads(settings)  # classic .conf: JSON value
        if not isinstance(settings, dict):
            raise ValueError("sampling_settings must be a mapping")
        if self.mode == "probabilistic":
            pct = self.percentage
            if pct is None:
                pct = settings.get("sampling_percentage")
            if pct is None:
                pct = self.sampling_settings_sampling_percentage
            self._p = max(0.0, min(100.0, float(pct)))
            self._rng = random.Random(self.seed)
            return
        if self.mode != "tail":
            raise ValueError(
                f"sampling: unknown type {self.mode!r} "
                "(probabilistic|tail)")
        if getattr(instance, "side", "input") == "output":
            # an output-side tail sampler would buffer at flush and
            # re-inject through the pipeline BACK to the same output —
            # an infinite buffer/re-route cycle that never delivers
            raise ValueError(
                "tail sampling must run on an input's traces pipeline, "
                "not on an output")
        self.decision_wait = _parse_time_s(
            settings.get("decision_wait"), 30.0)
        self.max_traces = int(settings.get("max_traces", 50000))
        conds = instance.prop("conditions")
        if conds is None:
            conds = settings.get("conditions")
        if isinstance(conds, str):
            import json

            conds = json.loads(conds)
        self.conditions = [make_condition(c) for c in (conds or [])]
        self._traces: "OrderedDict[str, _TraceEntry]" = OrderedDict()
        self._evicted = 0
        if engine is not None:
            self._attach_timer(engine)

    def _attach_timer(self, engine) -> None:
        """Hidden emitter input: carries the decision timer (the
        FLB_SCHED_TIMER_CB_PERM of sampling_tail.c:860) and re-injects
        sampled traces with no processors attached."""
        ins = engine.hidden_input(
            "emitter", owner=self.instance,
            alias=f"emitter_for_{self.instance.name}")
        self._emitter = ins
        proc = self

        def _tick(eng):
            proc.flush_decided(eng)

        ins.plugin.collect_interval = min(1.0, self.decision_wait)
        ins.plugin.collect = _tick
        engine.ensure_collector(ins)

    # ------------------------------------------------------------ logs

    def process_logs(self, events: list, tag: str, engine) -> list:
        if self.mode != "probabilistic":
            return events
        p = self._p / 100.0
        return [ev for ev in events if self._rng.random() < p]

    # ---------------------------------------------------------- traces

    def process_traces(self, payloads: list, tag: str, engine) -> list:
        if self.mode == "probabilistic":
            return self._probabilistic_traces(payloads)
        self._register_spans(payloads, tag)
        return []  # buffered; the timer emits decided traces

    def _probabilistic_traces(self, payloads: list) -> list:
        out = []
        for payload in payloads:
            rs_out = []
            for rs in payload.get("resourceSpans", []):
                ss_out = []
                for ss in rs.get("scopeSpans", []):
                    spans = [s for s in ss.get("spans", [])
                             if _trace_id_fraction(s) < self._p]
                    if spans:
                        ss_out.append({**ss, "spans": spans})
                if ss_out:
                    rs_out.append({**rs, "scopeSpans": ss_out})
            if rs_out:
                out.append({"resourceSpans": rs_out})
        return out

    def _register_spans(self, payloads: list, tag: str) -> None:
        now = time.monotonic()
        with self._lock:
            for payload in payloads:
                for rs in payload.get("resourceSpans", []):
                    resource = rs.get("resource") or {}
                    for ss in rs.get("scopeSpans", []):
                        scope = ss.get("scope") or {}
                        for span in ss.get("spans", []):
                            key = _trace_key(span)
                            entry = self._traces.get(key)
                            if entry is None:
                                entry = _TraceEntry(now, tag)
                                self._traces[key] = entry
                                # max_traces cap: evict the OLDEST trace
                                # undecided (sampling_span_registry.c)
                                while len(self._traces) > self.max_traces:
                                    old_key, old = self._traces.popitem(
                                        last=False)
                                    self._evicted += 1
                                    log.warning(
                                        "sampling: max_traces=%d "
                                        "exceeded, evicted trace %s "
                                        "(%d spans)", self.max_traces,
                                        old_key, len(old.spans))
                            entry.spans.append((resource, scope, span))

    def _sampled(self, entry: _TraceEntry) -> bool:
        """ONE span matching ANY condition samples the trace; no
        conditions configured → sample everything
        (check_conditions, sampling_tail.c:677-691)."""
        if not self.conditions:
            return True
        spans = [s for _, _, s in entry.spans]
        for span in spans:
            for cond in self.conditions:
                if cond.check(spans, span):
                    return True
        return False

    def flush_decided(self, engine, force: bool = False) -> int:
        """Evaluate traces whose decision window elapsed; re-inject the
        sampled ones through the emitter. Returns spans emitted."""
        from ..codec.chunk import EVENT_TYPE_TRACES
        from ..codec.telemetry import count_spans

        now = time.monotonic()
        decided: List[Tuple[str, _TraceEntry]] = []
        with self._lock:
            for key, entry in list(self._traces.items()):
                if force or now - entry.ts_created >= self.decision_wait:
                    decided.append((key, entry))
                    del self._traces[key]
        emitted = 0
        for key, entry in decided:
            if not self._sampled(entry):
                continue
            payload = _reconcile(entry)
            n = count_spans(payload)
            if engine is not None:
                if self._emitter is None:
                    self._attach_timer(engine)
                engine.input_event_append(
                    self._emitter, entry.tag, packb(payload),
                    EVENT_TYPE_TRACES, n_records=n)
            emitted += n
        return emitted

    def pending_traces(self) -> int:
        with self._lock:
            return len(self._traces)

    def drain(self, engine) -> None:
        """Engine shutdown: decide everything still buffered NOW so a
        stop inside the decision window doesn't lose sampled traces
        (the engine drains plugins + processors before its final
        flush)."""
        if self.mode == "tail":
            self.flush_decided(engine, force=True)
