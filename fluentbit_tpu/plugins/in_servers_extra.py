"""Server-style inputs: splunk (HEC) + elasticsearch (bulk API).

Reference: plugins/in_splunk (Splunk HTTP Event Collector server:
/services/collector[/event] JSON events, /services/collector/raw raw
lines, token auth, store_token_in_metadata) and plugins/
in_elasticsearch (Elasticsearch bulk-API server: POST /_bulk NDJSON
action/document pairs, answering the bulk response shape so
beats/agents accept the sink). Both ride the shared HTTP server base
(net_http.HttpServerInputBase).
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from ..codec.events import encode_event, now_event_time
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import registry
from .net_http import HttpServerInputBase

log = logging.getLogger("flb.servers")


@registry.register
class SplunkInput(HttpServerInputBase):
    name = "splunk"
    description = "Splunk HEC server"
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=8088),
        ConfigMapEntry("splunk_token", "str"),
        ConfigMapEntry("store_token_in_metadata", "bool", default=False),
    ]

    def _authorized(self, headers) -> Optional[str]:
        auth = headers.get("authorization", "")
        token = auth[len("Splunk "):] if auth.startswith("Splunk ") else None
        if not self.splunk_token:
            return token or ""
        return token if token == self.splunk_token else None

    def handle_request(self, engine, method, path, headers, body):
        if method != "POST":
            return 400, b'{"text":"Bad Request","code":6}'
        token = self._authorized(headers)
        if token is None:
            return 401, b'{"text":"Invalid token","code":4}'
        if path not in ("/services/collector", "/services/collector/event",
                        "/services/collector/raw"):
            return 404, b'{"text":"Not Found","code":404}'
        # out_splunk passthrough: keep the presented token in metadata
        meta = {"hec_token": token} \
            if self.store_token_in_metadata and token else None
        out = bytearray()
        n = 0
        if path.endswith("/raw"):
            for raw in body.splitlines():
                line = raw.decode("utf-8", "replace").strip()
                if line:
                    out += encode_event({"log": line}, now_event_time(),
                                        meta)
                    n += 1
        else:
            # concatenated JSON objects (HEC allows back-to-back docs)
            dec = json.JSONDecoder()
            text = body.decode("utf-8", "replace").strip()
            pos = 0
            while pos < len(text):
                try:
                    obj, end = dec.raw_decode(text, pos)
                except ValueError:
                    return 400, b'{"text":"Invalid data format","code":6}'
                pos = end
                while pos < len(text) and text[pos] in " \r\n\t":
                    pos += 1
                if not isinstance(obj, dict):
                    # real HEC rejects non-object events (code 6)
                    return 400, b'{"text":"Invalid data format","code":6}'
                event = obj.get("event", obj)
                rec = event if isinstance(event, dict) else {"event": event}
                rec = dict(rec)
                for k in ("source", "sourcetype", "index", "host"):
                    if k in obj:
                        rec.setdefault(k, obj[k])
                if isinstance(obj.get("fields"), dict):
                    for k, v in obj["fields"].items():
                        rec.setdefault(k, v)
                ts = obj.get("time")
                try:
                    ts = EventTime.from_float(float(ts)) if ts is not None \
                        else now_event_time()
                except (TypeError, ValueError):
                    ts = now_event_time()
                out += encode_event(rec, ts, meta)
                n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)
        return 200, b'{"text":"Success","code":0}'


@registry.register
class ElasticsearchInput(HttpServerInputBase):
    name = "elasticsearch"
    description = "Elasticsearch bulk-API server"
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=9200),
        ConfigMapEntry("meta_key", "str", default="@es_meta",
                       desc="store the bulk action metadata under this key"),
        ConfigMapEntry("hostname", "str", default="fluentbit-tpu"),
        ConfigMapEntry("version", "str", default="8.0.0"),
    ]

    def handle_request(self, engine, method, path, headers, body):
        if method in ("GET", "HEAD"):
            # beats probe the root + license endpoints before bulking
            info = {"name": self.hostname,
                    "version": {"number": self.version},
                    "tagline": "You Know, for Search"}
            return 200, json.dumps(info).encode()
        if method != "POST" or not path.endswith("_bulk"):
            return 400, b'{"error":"unsupported"}'
        out = bytearray()
        n = 0
        items = []
        action_meta = None
        for raw in body.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except ValueError:
                return 400, b'{"error":"malformed bulk body"}'
            if action_meta is None:
                # action line: {"index": {...}} / {"create": {...}} —
                # delete has no document line
                if not isinstance(obj, dict) or not obj:
                    return 400, b'{"error":"bad action"}'
                op = next(iter(obj))
                meta = obj.get(op)
                if meta is not None and not isinstance(meta, dict):
                    return 400, b'{"error":"bad action metadata"}'
                if op == "delete":
                    items.append({op: {"status": 200}})
                    continue
                action_meta = (op, meta or {})
                continue
            op, meta = action_meta
            action_meta = None
            if not isinstance(obj, dict):
                # clients correlate items positionally: a bad document
                # must fail the request, never silently desync
                return 400, b'{"error":"bulk document must be an object"}'
            rec = dict(obj)
            if self.meta_key:
                rec[self.meta_key] = {"op": op, **meta}
            out += encode_event(rec, now_event_time())
            n += 1
            items.append({op: {"status": 201, "result": "created"}})
        if action_meta is not None:
            return 400, b'{"error":"action without document"}'
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)
        return 200, json.dumps({"took": 0, "errors": False,
                                "items": items}).encode()
