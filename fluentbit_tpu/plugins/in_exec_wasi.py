"""in_exec_wasi — run a WASI command module and ingest its stdout.

Reference: plugins/in_exec_wasi/in_exec_wasi.c. Each collection tick
instantiates the module and runs ``_start`` with stdout redirected to
a capture buffer (the reference points the WAMR instance's stdoutfd at
a tmpfile, in_exec_wasi.c:54-96); afterwards every stdout line is
parsed with the configured parser (in_exec_wasi.c:99-152) or ingested
as ``{"wasi_stdout": <line>}`` (in_exec_wasi.c:157-174). ``oneshot``
runs exactly once; ``wasm_heap_size``/``wasm_stack_size`` bound the
instance like filter_wasm's. The guest runs on the from-scratch
wasmrt interpreter with its WASI preview1 host surface
(`wasmrt/wasi.py`) — no filesystem preopens (``accessible_paths`` is
accepted for config parity but the sandbox exposes no host paths).
"""

from __future__ import annotations

import logging

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry, parse_size
from ..core.plugin import InputPlugin, registry
from ..wasmrt import Module, Trap, WasmError
from ..wasmrt.wasi import WasiEnv, WasiExit

log = logging.getLogger("flb.exec_wasi")


@registry.register
class ExecWasiInput(InputPlugin):
    name = "exec_wasi"
    description = "Exec WASI Input"
    config_map = [
        ConfigMapEntry("wasi_path", "str"),
        ConfigMapEntry("accessible_paths", "clist", default="."),
        ConfigMapEntry("parser", "str"),
        ConfigMapEntry("interval_sec", "int", default=1),
        ConfigMapEntry("interval_nsec", "int", default=0),
        ConfigMapEntry("wasm_heap_size", "size", default="8192k"),
        ConfigMapEntry("wasm_stack_size", "size", default="8192k"),
        ConfigMapEntry("buf_size", "size", default="8192"),
        ConfigMapEntry("oneshot", "bool", default=False),
    ]

    def init(self, instance, engine) -> None:
        if not self.wasi_path:
            raise ValueError("exec_wasi: no input 'command' was given")
        with open(self.wasi_path, "rb") as f:
            self._binary = f.read()
        # instantiate once up front so a broken module fails at init
        self._instantiate()
        self._ins = instance
        self._parser = None
        if self.parser:
            self._parser = (engine.parsers.get(self.parser)
                            if engine is not None else None)
            if self._parser is None:
                log.error("exec_wasi: requested parser '%s' not found",
                          self.parser)
        interval = max(0, int(self.interval_sec)) + \
            max(0, int(self.interval_nsec)) / 1e9
        self.collect_interval = interval if interval > 0 else 1.0
        self._done = False

    def _instantiate(self):
        wasi = WasiEnv(args=[self.wasi_path])
        mod = Module(
            self._binary,
            max_memory_bytes=parse_size(self.wasm_heap_size),
            max_call_depth=max(64, parse_size(self.wasm_stack_size)
                               // 4096),
            host_imports=wasi.imports(),
        )
        return mod, wasi

    def collect(self, engine) -> None:
        if self._done:
            return
        if self.oneshot:
            self._done = True
        try:
            mod, wasi = self._instantiate()
        except (WasmError, Trap) as e:
            log.error("exec_wasi: instantiation failed: %s", e)
            return
        try:
            if "_start" in mod.exports:
                mod.call("_start", [])
            else:
                log.error("exec_wasi: module has no _start export")
                return
        except WasiExit as e:
            if e.code != 0:
                log.warning("exec_wasi: guest exited with code %d",
                            e.code)
        except (Trap, WasmError) as e:
            log.error("exec_wasi: guest trapped: %s", e)
            return
        except Exception as e:  # noqa: BLE001 — same containment
            # stance as filter_wasm: a guest must never take the
            # collector down (RecursionError from deep wasm recursion,
            # struct.error from a bad pointer, ...)
            log.error("exec_wasi: guest error: %r", e)
            return
        self._ingest_stdout(engine, bytes(wasi.stdout))

    def _ingest_stdout(self, engine, data: bytes) -> None:
        if not data:
            return
        buf_max = parse_size(self.buf_size)
        events = []
        for line in data.splitlines():
            if not line:
                continue
            line = line[:buf_max]
            text = line.decode("utf-8", "replace")
            if self._parser is not None:
                got = self._parser.do(text)
                if got is not None:
                    fields, ts = got
                    events.append(encode_event(
                        fields, ts if ts else now_event_time()))
                    continue
            events.append(encode_event({"wasi_stdout": text},
                                       now_event_time()))
        if events:
            engine.input_log_append(self._ins, self._ins.tag,
                                    b"".join(events), len(events))
