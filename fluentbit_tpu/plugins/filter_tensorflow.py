"""filter_tensorflow — TF-Lite inference over a record field.

Reference: plugins/filter_tensorflow/tensorflow.c. Each record whose
``input_field`` holds a numeric array (or a byte string, cast per
element) of the model's input size is replaced by a record carrying
(optionally) all original fields plus ``inference_time`` and
``output`` — the float32 output tensor as an array
(tensorflow.c:420-476). ``normalization_value`` divides every input
element first (tensorflow.c:236-241). Records without the field, or
with mismatched sizes, pass through untouched after an error log,
exactly like the reference's per-record break-outs.

The model runs on the from-scratch TF-Lite loader/executor
(`utils/tflite.py`); unlike the reference's one Invoke per record, all
matching records in the chunk are stacked into ONE batched forward
pass.
"""

from __future__ import annotations

import logging
import struct
import time
from typing import List

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..utils.tflite import Model, TFLiteError

log = logging.getLogger("flb.tensorflow")


@registry.register
class TensorflowFilter(FilterPlugin):
    name = "tensorflow"
    description = "TensorFlow Lite inference on record fields"
    config_map = [
        ConfigMapEntry("input_field", "str"),
        ConfigMapEntry("model_file", "str"),
        ConfigMapEntry("include_input_fields", "bool", default=True),
        ConfigMapEntry("normalization_value", "double", default=0.0),
    ]

    def init(self, instance, engine) -> None:
        if not self.input_field:
            raise ValueError("tensorflow: input field is not defined!")
        if not self.model_file:
            raise ValueError("tensorflow: model file is not defined!")
        with open(self.model_file, "rb") as f:
            binary = f.read()
        try:
            self.model = Model(binary)
        except TFLiteError as e:
            raise ValueError(f"tensorflow: {e}") from e
        except (struct.error, IndexError) as e:
            # truncated/corrupt flatbuffer past the TFL3 check
            raise ValueError(
                f"tensorflow: corrupt model file: {e!r}") from e
        self._input_size = 1
        for d in self.model.input_shape[1:]:
            self._input_size *= max(1, d)
        log.info("tensorflow: model %s input=%s output=%s",
                 self.model_file, self.model.input_shape,
                 self.model.output_shape)

    def _vectorize(self, value) -> List[float]:
        """Reference input handling: numeric array, or bytes cast
        per-element (tensorflow.c:335-410)."""
        if isinstance(value, (list, tuple)):
            if not value or not all(
                    isinstance(v, (int, float)) and
                    not isinstance(v, bool) for v in value):
                return None
            vec = [float(v) for v in value]
        elif isinstance(value, (bytes, bytearray)):
            vec = [float(b) for b in value]
        else:
            return None
        if len(vec) != self._input_size:
            log.error("tensorflow: input data size doesn't match "
                      "model's input size!")
            return None
        if self.normalization_value:
            vec = [v / self.normalization_value for v in vec]
        return vec

    def filter(self, events: list, tag: str, engine) -> tuple:
        import numpy as np

        t0 = time.perf_counter()
        todo = []  # (event index, vector)
        for i, ev in enumerate(events):
            if not isinstance(ev.body, dict) or \
                    self.input_field not in ev.body:
                continue
            vec = self._vectorize(ev.body[self.input_field])
            if vec is not None:
                todo.append((i, vec))
        if not todo:
            return (FilterResult.NOTOUCH, events)
        try:
            batch = np.asarray([v for _, v in todo], dtype=np.float32)
            outputs = self.model.run(batch)
        except (TFLiteError, ValueError, struct.error,
                IndexError) as e:
            log.error("tensorflow: inference failed: %s", e)
            return (FilterResult.NOTOUCH, events)
        inference_time = time.perf_counter() - t0
        out = list(events)
        for (i, _), row in zip(todo, outputs):
            ev = events[i]
            body = dict(ev.body) if self.include_input_fields else {}
            body["inference_time"] = inference_time
            body["output"] = [float(x) for x in row]
            out[i] = LogEvent(ev.timestamp, body, ev.metadata, raw=None)
        return (FilterResult.MODIFIED, out)
