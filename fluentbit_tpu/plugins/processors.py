"""Processors — per-instance pipelines attached to inputs/outputs.

Reference: plugins/processor_content_modifier (3546 LoC: actions
insert/upsert/delete/rename/hash/extract/convert over body or
metadata), plugins/processor_labels (metric label edits),
plugins/processor_metrics_selector (include/exclude by name). Processor
units run at input ingest (flb_processor_run, src/flb_input_log.c:1562)
and at output flush — the engine invokes ``process_logs`` /
``process_metrics`` accordingly (YAML ``processors:`` blocks wire them,
src/config_format/flb_cf_yaml.c being the only format exposing them).
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Dict, List, Optional

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import ProcessorPlugin, registry
from ..regex import FlbRegex


@registry.register
class ContentModifierProcessor(ProcessorPlugin):
    name = "content_modifier"
    description = ("modify record content: insert/upsert/delete/rename/"
                   "hash/extract/convert")
    config_map = [
        ConfigMapEntry("action", "str"),
        ConfigMapEntry("context", "str", default="body"),
        ConfigMapEntry("key", "str"),
        ConfigMapEntry("value", "str"),
        ConfigMapEntry("pattern", "str"),
        ConfigMapEntry("converted_type", "str"),
    ]

    ACTIONS = ("insert", "upsert", "delete", "rename", "hash", "extract",
               "convert")

    def init(self, instance, engine) -> None:
        if self.action not in self.ACTIONS:
            raise ValueError(f"content_modifier: unknown action {self.action!r}")
        if not self.key:
            raise ValueError("content_modifier: key is required")
        if self.action == "extract" and not self.pattern:
            raise ValueError("content_modifier: extract requires pattern")
        if self.action in ("insert", "upsert", "rename") and self.value is None:
            raise ValueError(
                f"content_modifier: {self.action} requires a value"
            )
        self._rx = FlbRegex(self.pattern) if self.pattern else None
        ctx = (self.context or "body").lower()
        if ctx in ("body", "attributes"):
            self._meta = False
        elif ctx in ("metadata", "otel_resource_attributes"):
            self._meta = True
        else:
            raise ValueError(f"content_modifier: unknown context {ctx!r}")

    def _convert(self, v):
        t = (self.converted_type or "string").lower()
        try:
            if t == "int":
                return int(float(v))
            if t == "double":
                return float(v)
            if t == "boolean":
                s = str(v).lower()
                return s in ("true", "on", "1", "yes")
        except (TypeError, ValueError):
            return v
        return str(v)

    def process_logs(self, events: list, tag: str, engine) -> list:
        out = []
        for ev in events:
            target = ev.metadata if self._meta else ev.body
            if not isinstance(target, dict):
                out.append(ev)
                continue
            target = dict(target)
            a = self.action
            if a == "insert":
                target.setdefault(self.key, self.value)
            elif a == "upsert":
                target[self.key] = self.value
            elif a == "delete":
                target.pop(self.key, None)
            elif a == "rename":
                if self.key in target:
                    target[self.value] = target.pop(self.key)
            elif a == "hash":
                if self.key in target:
                    target[self.key] = hashlib.sha256(
                        str(target[self.key]).encode()
                    ).hexdigest()
            elif a == "extract":
                v = target.get(self.key)
                if isinstance(v, str):
                    got = self._rx.parse_record(v)
                    if got:
                        target.update(got)
            elif a == "convert":
                if self.key in target:
                    target[self.key] = self._convert(target[self.key])
            if self._meta:
                out.append(LogEvent(ev.timestamp, ev.body, target, raw=None))
            else:
                out.append(LogEvent(ev.timestamp, target, ev.metadata,
                                    raw=None))
        return out


@registry.register
class LabelsProcessor(ProcessorPlugin):
    name = "labels"
    description = "edit labels on metrics"
    config_map = [
        ConfigMapEntry("insert", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("upsert", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("delete", "str", multiple=True),
    ]

    @staticmethod
    def _pairs(entries):
        out = []
        for e in entries or []:
            parts = e if isinstance(e, list) else str(e).split(None, 1)
            if len(parts) == 2:
                out.append((parts[0], parts[1]))
        return out

    def process_metrics(self, payloads: list, tag: str, engine) -> list:
        inserts = self._pairs(self.insert)
        upserts = self._pairs(self.upsert)
        deletes = list(self.delete or [])
        for payload in payloads:
            for m in payload.get("metrics", []):
                keys: List[str] = list(m.get("labels", []))
                # deletes first: drop the key + its per-sample values
                drop_idx = [i for i, k in enumerate(keys) if k in deletes]
                if drop_idx:
                    m["labels"] = [k for i, k in enumerate(keys)
                                   if i not in drop_idx]
                    for s in m.get("values", []):
                        s["labels"] = [v for i, v in
                                       enumerate(s.get("labels", []))
                                       if i not in drop_idx]
                    keys = m["labels"]
                for key, value in upserts + inserts:
                    if key in keys:
                        if (key, value) in inserts:
                            continue  # insert never overwrites
                        i = keys.index(key)
                        for s in m.get("values", []):
                            s["labels"][i] = value
                    else:
                        m["labels"] = keys = list(keys) + [key]
                        for s in m.get("values", []):
                            s["labels"] = list(s.get("labels", [])) + [value]
        return payloads


@registry.register
class SqlProcessor(ProcessorPlugin):
    """plugins/processor_sql — lighter per-instance SELECT projection +
    WHERE over records, distinct from the engine-level stream processor
    (SURVEY §2.5 contrast) but sharing its expression engine."""

    name = "sql"
    description = "SELECT projection/WHERE over records"
    config_map = [ConfigMapEntry("query", "str")]

    def init(self, instance, engine) -> None:
        from ..stream_processor import parse_sql

        if not self.query:
            raise ValueError("sql processor requires a query")
        q = parse_sql(self.query)
        if q.has_aggregates or q.window or q.group_by:
            raise ValueError(
                "sql processor supports projection/WHERE only — use a "
                "stream-processor task for aggregates/windows"
            )
        self._q = q

    def process_logs(self, events: list, tag: str, engine) -> list:
        from ..stream_processor import eval_cond, project

        q = self._q
        out = []
        for ev in events:
            if not isinstance(ev.body, dict):
                out.append(ev)
                continue
            if q.where is not None and not eval_cond(q.where, ev.body,
                                                     ev.ts_float):
                continue
            out.append(LogEvent(ev.timestamp, project(ev.body, q.keys),
                                ev.metadata, raw=None))
        return out


@registry.register
class MetricsSelectorProcessor(ProcessorPlugin):
    name = "metrics_selector"
    description = "include/exclude metrics by name"
    config_map = [
        ConfigMapEntry("metric_name", "str"),
        ConfigMapEntry("action", "str", default="include"),
        ConfigMapEntry("operation_type", "str", default="substring"),
    ]

    def init(self, instance, engine) -> None:
        if not self.metric_name:
            raise ValueError("metrics_selector: metric_name is required")
        name = self.metric_name
        if name.startswith("/") and name.endswith("/"):
            self._rx = re.compile(name[1:-1])
            self._match = lambda n: bool(self._rx.search(n))
        elif (self.operation_type or "substring").lower() == "prefix":
            self._match = lambda n: n.startswith(name)
        else:
            self._match = lambda n: name in n

    def process_metrics(self, payloads: list, tag: str, engine) -> list:
        include = (self.action or "include").lower() == "include"
        for payload in payloads:
            payload["metrics"] = [
                m for m in payload.get("metrics", [])
                if self._match(m.get("name", "")) == include
            ]
        return payloads
