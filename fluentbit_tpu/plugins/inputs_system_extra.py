"""System-ish inputs rounding out the registry: docker,
prometheus_textfile, gpu_metrics, event_type, event_test.

Reference: plugins/in_docker (cgroup v1/v2 per-container cpu/mem
snapshots, record {id, name, cpu_used, mem_used, mem_limit},
docker.c:408-448), plugins/in_prometheus_textfile (glob *.prom files
→ metrics, the node_exporter textfile-collector role),
plugins/in_gpu_metrics (AMD sysfs /sys/class/drm/cardN/device gauges —
gpu_metrics.c:95-126 metric names; NVML cards need the vendor library
and report absent here), plugins/in_event_type + in_event_test (test
generators emitting each signal type on an interval — the runtime-test
scaffolding inputs).
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import time
from typing import List, Optional

from ..codec.chunk import EVENT_TYPE_METRICS
from ..codec.events import encode_event, now_event_time
from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry

log = logging.getLogger("flb.system_extra")


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            v = f.read().strip()
        return int(v) if v != "max" else -1
    except (OSError, ValueError):
        return None


@registry.register
class DockerInput(InputPlugin):
    """plugins/in_docker: per-container cpu/mem from cgroups."""

    name = "docker"
    description = "docker container cgroup metrics"
    config_map = [
        ConfigMapEntry("interval_sec", "int", default=1),
        ConfigMapEntry("include", "str",
                       desc="space-separated container ids to include"),
        ConfigMapEntry("exclude", "str"),
        ConfigMapEntry("path.sysfs", "str", default="/sys/fs/cgroup"),
        ConfigMapEntry("path.containers", "str",
                       default="/var/lib/docker/containers"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.interval_sec or 1)
        self._include = set((self.include or "").split()) or None
        self._exclude = set((self.exclude or "").split())

    def _container_name(self, cid: str) -> str:
        """config.v2.json carries the user-facing name
        (docker.c docker_extract_name)."""
        cfg = os.path.join(self.path_containers, cid, "config.v2.json")
        try:
            with open(cfg) as f:
                name = json.load(f).get("Name", "")
            return name.lstrip("/") or cid[:12]
        except (OSError, ValueError):
            return cid[:12]

    def _stats(self, cid: str):
        sysfs = self.path_sysfs
        # cgroup v2: system.slice/docker-<id>.scope
        base = os.path.join(sysfs, "system.slice", f"docker-{cid}.scope")
        if os.path.isdir(base):
            mem = _read_int(os.path.join(base, "memory.current"))
            lim = _read_int(os.path.join(base, "memory.max"))
            cpu = None
            try:
                with open(os.path.join(base, "cpu.stat")) as f:
                    for line in f:
                        if line.startswith("usage_usec"):
                            cpu = int(line.split()[1]) * 1000  # → ns
            except OSError:
                pass
            if mem is not None or cpu is not None:
                return cpu or 0, mem or 0, lim if lim and lim > 0 else 0
        # cgroup v1: cpu/docker/<id>, memory/docker/<id>
        cpu = _read_int(os.path.join(sysfs, "cpu", "docker", cid,
                                     "cpuacct.usage"))
        mem = _read_int(os.path.join(sysfs, "memory", "docker", cid,
                                     "memory.usage_in_bytes"))
        lim = _read_int(os.path.join(sysfs, "memory", "docker", cid,
                                     "memory.limit_in_bytes"))
        if cpu is None and mem is None:
            return None
        return cpu or 0, mem or 0, lim or 0

    def _ids(self) -> List[str]:
        try:
            return [d for d in os.listdir(self.path_containers)
                    if len(d) == 64]
        except OSError:
            return []

    def collect(self, engine) -> None:
        out = bytearray()
        n = 0
        for cid in self._ids():
            if self._include is not None and cid not in self._include \
                    and cid[:12] not in self._include:
                continue
            if cid in self._exclude or cid[:12] in self._exclude:
                continue
            stats = self._stats(cid)
            if stats is None:
                continue
            cpu, mem, lim = stats
            out += encode_event({
                "id": cid[:12],
                "name": self._container_name(cid),
                "cpu_used": cpu,
                "mem_used": mem,
                "mem_limit": lim,
            }, now_event_time())
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)


@registry.register
class PrometheusTextfileInput(InputPlugin):
    """plugins/in_prometheus_textfile: glob .prom exposition files."""

    name = "prometheus_textfile"
    description = "scrape Prometheus exposition text files"
    config_map = [
        ConfigMapEntry("path", "str",
                       desc="glob pattern of .prom files"),
        ConfigMapEntry("scrape_interval", "time", default="10"),
    ]

    def init(self, instance, engine) -> None:
        if not self.path:
            raise ValueError("prometheus_textfile requires 'path'")
        self.collect_interval = float(self.scrape_interval or 10)

    def collect(self, engine) -> None:
        from .inputs_net_extra import parse_prometheus_text

        metrics: List[dict] = []
        for path in sorted(_glob.glob(self.path)):
            try:
                with open(path) as f:
                    metrics.extend(parse_prometheus_text(f.read()))
            except OSError:
                log.debug("prometheus_textfile: cannot read %s", path)
        if metrics:
            payload = {"meta": {"ts": time.time()}, "metrics": metrics}
            engine.input_event_append(
                self.instance, self.instance.tag, packb(payload),
                EVENT_TYPE_METRICS, n_records=len(metrics))


# gpu_metrics.c:95-126 gauge names; per-card sysfs files (AMD)
_GPU_FILES = [
    ("gpu_utilization_percent", "gpu_busy_percent", 1.0),
    ("gpu_memory_used_bytes", "mem_info_vram_used", 1.0),
    ("gpu_memory_total_bytes", "mem_info_vram_total", 1.0),
]
_HWMON_FILES = [
    ("gpu_power_watts", "power1_average", 1e-6),
    ("gpu_temperature_celsius", "temp1_input", 1e-3),
    ("gpu_fan_speed_rpm", "fan1_input", 1.0),
]


@registry.register
class GpuMetricsInput(InputPlugin):
    """plugins/in_gpu_metrics (AMD sysfs side; NVML needs the vendor
    library and is reported absent)."""

    name = "gpu_metrics"
    description = "AMD GPU sysfs metrics"
    config_map = [
        ConfigMapEntry("interval_sec", "int", default=1),
        ConfigMapEntry("path.sysfs", "str", default="/sys"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.interval_sec or 1)

    def _cards(self) -> List[str]:
        pattern = os.path.join(self.path_sysfs, "class", "drm",
                               "card[0-9]*", "device")
        return [d for d in sorted(_glob.glob(pattern))
                if os.path.isdir(d)]

    def collect(self, engine) -> None:
        metrics: List[dict] = []
        ts = time.time()
        for dev in self._cards():
            card = os.path.basename(os.path.dirname(dev))
            values = []
            for metric, fname, scale in _GPU_FILES:
                v = _read_int(os.path.join(dev, fname))
                if v is not None:
                    values.append((metric, v * scale))
            for hw in sorted(_glob.glob(os.path.join(dev, "hwmon",
                                                     "hwmon[0-9]*"))):
                for metric, fname, scale in _HWMON_FILES:
                    v = _read_int(os.path.join(hw, fname))
                    if v is not None:
                        values.append((metric, v * scale))
            for metric, value in values:
                metrics.append({
                    "name": metric, "type": "gauge", "desc": "",
                    "labels": ["gpu"], "ts": ts,
                    "values": [{"labels": [card], "value": value}],
                })
        if metrics:
            payload = {"meta": {"ts": ts}, "metrics": metrics}
            engine.input_event_append(
                self.instance, self.instance.tag, packb(payload),
                EVENT_TYPE_METRICS, n_records=len(metrics))


@registry.register
class EventTypeInput(InputPlugin):
    """plugins/in_event_type: emit one record of the chosen signal type
    per interval (test scaffolding; event_type.c send_logs/send_metrics)."""

    name = "event_type"
    description = "test generator for logs/metrics signals"
    config_map = [
        ConfigMapEntry("type", "str", default="logs"),
        ConfigMapEntry("interval_sec", "int", default=1),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.interval_sec or 1)
        kind = (self.type or "logs").lower()
        if kind not in ("logs", "metrics"):
            raise ValueError(f"event_type: unsupported type {kind!r}")
        self._kind = kind
        self._n = 0

    def collect(self, engine) -> None:
        self._n += 1
        if self._kind == "logs":
            engine.input_log_append(
                self.instance, self.instance.tag,
                encode_event({"event_type": "some logs"},
                             now_event_time()), 1)
        else:
            payload = {"meta": {"ts": time.time()}, "metrics": [{
                "name": "event_test_counter", "type": "counter",
                "desc": "event_type test counter", "labels": [],
                "ts": time.time(),
                "values": [{"labels": [], "value": float(self._n)}],
            }]}
            engine.input_event_append(
                self.instance, self.instance.tag, packb(payload),
                EVENT_TYPE_METRICS, n_records=1)


@registry.register
class EventTestInput(InputPlugin):
    """plugins/in_event_test: pause/resume exerciser — emits a counter
    record per interval; the runtime tests toggle pause on it."""

    name = "event_test"
    description = "test input emitting sequence records"
    config_map = [
        ConfigMapEntry("interval_sec", "int", default=1),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.interval_sec or 1)
        self._seq = 0

    def collect(self, engine) -> None:
        self._seq += 1
        engine.input_log_append(
            self.instance, self.instance.tag,
            encode_event({"seq": self._seq}, now_event_time()), 1)
