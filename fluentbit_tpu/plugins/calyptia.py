"""Calyptia control plane: out_calyptia + custom_calyptia +
in_calyptia_fleet.

Reference: plugins/out_calyptia/calyptia.c (agent registration at init
via a synchronous upstream — POST /v1/agents with the project token,
PATCH /v1/agents/<id> when a stored session already has an id+token —
then metrics delivery to /v1/agents/<id>/metrics with the agent
token), plugins/custom_calyptia/calyptia.c (a custom plugin that wires
the hidden pipeline: a fluentbit_metrics input tagged _calyptia_cloud,
the calyptia output matched to it, and a calyptia_fleet input when a
fleet is configured), and plugins/in_calyptia_fleet/in_calyptia_fleet.c
(periodic GET of the fleet config — fleet_name resolved to fleet_id
through /v1/search using the ProjectID decoded from the api_key's
first base64 token segment, in_calyptia_fleet.c:936-973 — storing each
new revision as <last_modified>.conf under config_dir/<fleet> and
triggering hot reload onto it).

Endpoint/header constants follow
include/fluent-bit/calyptia/calyptia_constants.h. ``cloud_host`` is
overridable exactly as in the reference ("development purposes only"),
which is what the runtime tests use.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import platform
import socket
import time
import uuid
from typing import List, Optional, Tuple

from .. import __version__
from ..codec.chunk import EVENT_TYPE_METRICS
from ..codec.msgpack import Unpacker, packb
from ..core.config import ConfigMapEntry
from ..core.plugin import (
    CustomPlugin,
    FlushResult,
    InputPlugin,
    registry,
)
from ..utils import sync_http_request
from .outputs_http_based import _HttpDeliveryOutput

log = logging.getLogger("flb.calyptia")

CALYPTIA_HOST = "cloud-api.calyptia.com"
ENDPOINT_CREATE = "/v1/agents"
ENDPOINT_PATCH = "/v1/agents/{}"
ENDPOINT_METRICS = "/v1/agents/{}/metrics"
ENDPOINT_FLEET_CONFIG = "/v1/fleets/{}/config?format=ini&config_format=ini"
ENDPOINT_FLEET_BY_NAME = ("/v1/search?project_id={}&resource=fleet"
                          "&term={}&exact=true")
SESSION_FILE = "session.CALYPTIA"
HDR_PROJECT = "X-Project-Token"
HDR_AGENT_TOKEN = "X-Agent-Token"


def _tls_settings(ins) -> Tuple[bool, bool]:
    """(tls_on, tls_verify) from an instance's tls.* properties."""
    from ..core.config import parse_bool
    from ..core.tls import tls_enabled
    return (tls_enabled(ins),
            parse_bool(ins.properties.get("tls.verify", True)))


def _parse_label(entry) -> Tuple[str, str]:
    parts = entry if isinstance(entry, list) \
        else str(entry).split(None, 1)
    if len(parts) != 2:
        raise ValueError(f"calyptia: bad add_label {entry!r}")
    return str(parts[0]), str(parts[1])


def _machine_arch() -> str:
    m = platform.machine().lower()
    return {"x86_64": "x86_64", "amd64": "x86_64", "aarch64": "arm64",
            "arm64": "arm64", "i686": "x86", "i386": "x86",
            "arm": "arm"}.get(m, m or "unknown")


def _agent_metadata(machine_id: str, fleet_id: Optional[str],
                    raw_config: str) -> dict:
    """out_calyptia get_agent_metadata (calyptia.c:180-318)."""
    meta = {
        "name": socket.gethostname() or "unknown",
        "type": "fluentbit",
        "rawConfig": raw_config,
        "version": __version__,
        "edition": "community",
        "os": "linux" if platform.system() == "Linux" else
              platform.system().lower() or "unknown",
        "arch": _machine_arch(),
        "machineID": machine_id,
    }
    if fleet_id:
        meta["fleetID"] = fleet_id
    return meta


@registry.register
class CalyptiaOutput(_HttpDeliveryOutput):
    name = "calyptia"
    description = "Calyptia Cloud connector"
    event_types = (EVENT_TYPE_METRICS,)
    config_map = [
        ConfigMapEntry("api_key", "str"),
        ConfigMapEntry("cloud_host", "str", default=CALYPTIA_HOST),
        ConfigMapEntry("cloud_port", "int", default=443),
        ConfigMapEntry("machine_id", "str"),
        ConfigMapEntry("fleet_id", "str"),
        ConfigMapEntry("store_path", "str"),
        ConfigMapEntry("add_label", "slist", multiple=True,
                       slist_max_split=1),
        ConfigMapEntry("register_retry_on_flush", "bool", default=True),
    ]

    def init(self, instance, engine) -> None:
        if not self.api_key:
            raise ValueError("calyptia: configuration 'api_key' is missing")
        if not self.machine_id:
            # the reference requires custom_calyptia to provide it
            raise ValueError("calyptia: machine_id has not been set")
        self.host = self.cloud_host
        self.port = self.cloud_port
        self._labels: List[Tuple[str, str]] = [
            _parse_label(e) for e in self.add_label or []]
        self.agent_id: Optional[str] = None
        self.agent_token: Optional[str] = None
        self._load_session()
        ok = self._register_agent()
        if not ok and not self.register_retry_on_flush:
            raise RuntimeError(
                "calyptia: agent registration failed and "
                "register_retry_on_flush=false")

    # -- session store (store_session_set/get, calyptia.c:475-600) -----

    def _session_path(self) -> Optional[str]:
        if not self.store_path:
            return None
        return os.path.join(self.store_path, SESSION_FILE)

    def _load_session(self) -> None:
        path = self._session_path()
        if not path or not os.path.isfile(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if data.get("id") and data.get("token"):
                self.agent_id = data["id"]
                self.agent_token = data["token"]
                log.info("calyptia: session setup OK")
        except (OSError, ValueError):
            pass

    def _store_session(self, payload: dict) -> None:
        path = self._session_path()
        if not path:
            return
        try:
            os.makedirs(self.store_path, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(payload, f)
        except OSError:
            log.warning("calyptia: could not store session")

    # -- registration (api_agent_create, calyptia.c:608-715) -----------

    def _tls_pair(self) -> Tuple[bool, bool]:
        return _tls_settings(self.instance)

    def _register_agent(self) -> bool:
        raw_config = ""
        meta = json.dumps(_agent_metadata(self.machine_id, self.fleet_id,
                                          raw_config)).encode()
        tls, verify = self._tls_pair()
        if self.agent_id and self.agent_token:
            got = sync_http_request(
                self.host, self.port, "PATCH",
                ENDPOINT_PATCH.format(self.agent_id),
                {HDR_PROJECT: self.api_key,
                 "Content-Type": "application/json"},
                meta, tls=tls, tls_verify=verify)
            ok = got is not None and got[0] in (200, 201, 204)
            if ok:
                log.info("calyptia: known agent registration successful")
            return ok
        got = sync_http_request(
            self.host, self.port, "POST", ENDPOINT_CREATE,
            {HDR_PROJECT: self.api_key,
             "Content-Type": "application/json"},
            meta, tls=tls, tls_verify=verify)
        if got is None or got[0] not in (200, 201, 204):
            log.warning("calyptia: agent registration failed")
            return False
        try:
            payload = json.loads(got[2])
            self.agent_id = str(payload["id"])
            self.agent_token = str(payload["token"])
        except (ValueError, KeyError):
            return False
        self._store_session(payload)
        log.info("calyptia: connected to Calyptia, agent_id=%s",
                 self.agent_id)
        return True

    # -- metrics delivery (cb_calyptia_flush) --------------------------

    def _content_type(self) -> str:
        return "application/x-msgpack"

    def _apply_labels(self, data: bytes) -> bytes:
        """append_labels: stamp configured add_label pairs onto every
        metric of every snapshot in the chunk."""
        if not self._labels:
            return data
        out = []
        for payload in Unpacker(data):
            for m in payload.get("metrics", []):
                keys = list(m.get("labels", []))
                add = [(k, v) for k, v in self._labels if k not in keys]
                if not add:
                    continue
                m["labels"] = keys + [k for k, _ in add]
                vals = [v for _, v in add]
                for s in m.get("values", []):
                    s["labels"] = list(s.get("labels", [])) + vals
                for h in m.get("hist", []):
                    h["labels"] = list(h.get("labels", [])) + vals
            out.append(packb(payload))
        return b"".join(out)

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        if not (self.agent_id and self.agent_token):
            if not self.register_retry_on_flush:
                return FlushResult.ERROR
            # the blocking sync-upstream registration must not stall
            # the event loop on retried flushes (init-time blocking is
            # fine — the reference's api_agent_create is synchronous)
            import asyncio
            loop = asyncio.get_running_loop()
            ok = await loop.run_in_executor(None, self._register_agent)
            if not ok:
                return FlushResult.RETRY
        try:
            body = self._apply_labels(data)
        except Exception:
            return FlushResult.ERROR
        return await self._post(
            body, extra_headers=[f"{HDR_AGENT_TOKEN}: {self.agent_token}"],
            uri=ENDPOINT_METRICS.format(self.agent_id))


@registry.register
class CalyptiaFleetInput(InputPlugin):
    """Pulls the fleet's config and hot-reloads onto each new revision."""

    name = "calyptia_fleet"
    description = "Calyptia fleet config manager"
    config_map = [
        ConfigMapEntry("api_key", "str"),
        ConfigMapEntry("host", "str", default=CALYPTIA_HOST),
        ConfigMapEntry("port", "int", default=443),
        ConfigMapEntry("fleet_id", "str"),
        ConfigMapEntry("fleet_name", "str"),
        ConfigMapEntry("machine_id", "str"),
        ConfigMapEntry("config_dir", "str", default="/tmp/calyptia-fleet"),
        ConfigMapEntry("interval_sec", "int", default=15),
        ConfigMapEntry("fleet_config_legacy_format", "bool", default=True),
        ConfigMapEntry("max_http_buffer_size", "size", default="8M"),
    ]

    threaded_capable = True

    def init(self, instance, engine) -> None:
        if not self.api_key:
            raise ValueError("calyptia_fleet: api_key is required")
        if not self.fleet_id and not self.fleet_name:
            raise ValueError(
                "calyptia_fleet: fleet_id or fleet_name is required")
        self._ins = instance
        # the blocking cloud polls must not ride the event loop
        # (reference runs this input threaded); honor an explicit
        # `threaded off` only
        if instance.properties.get("threaded") is None:
            instance.threaded = True
        self.collect_interval = max(1, int(self.interval_sec))
        # recover dedup state from the on-disk revision store so a hot
        # reload (which replaces this instance) does not re-apply the
        # same revision in a loop — the reference scans config_dir for
        # existing <ts>.conf files the same way
        self._last_modified = 0.0
        self._last_body = None
        if self.fleet_id or self.fleet_name:
            try:
                revs = sorted(
                    f for f in os.listdir(self._fleet_dir())
                    if f.endswith(".conf")
                    and f[:-len(".conf")].isdigit())
            except OSError:
                revs = []
            if revs:
                newest = revs[-1]
                self._last_modified = float(newest[:-len(".conf")])
                try:
                    with open(os.path.join(self._fleet_dir(), newest),
                              "rb") as f:
                        self._last_body = f.read()
                except OSError:
                    pass

    def _tls_pair(self) -> Tuple[bool, bool]:
        return _tls_settings(self._ins)

    def _project_id(self) -> Optional[str]:
        """First '.'-separated api_key segment is padded base64 JSON
        carrying ProjectID (in_calyptia_fleet.c:936-973)."""
        head, sep, _ = str(self.api_key).partition(".")
        if not sep:
            return None
        pad = "=" * (-len(head) % 4)
        try:
            return json.loads(base64.b64decode(head + pad))["ProjectID"]
        except (ValueError, KeyError, TypeError):
            return None

    def _resolve_fleet_id(self) -> bool:
        if self.fleet_id:
            return True
        project = self._project_id()
        if project is None:
            log.error("calyptia_fleet: could not parse project id "
                      "from api_key")
            return False
        tls, verify = self._tls_pair()
        got = sync_http_request(
            self.host, self.port, "GET",
            ENDPOINT_FLEET_BY_NAME.format(project, self.fleet_name),
            {HDR_PROJECT: self.api_key}, tls=tls, tls_verify=verify)
        if got is None or got[0] != 200:
            log.error("calyptia_fleet: fleet search failed")
            return False
        try:
            matches = json.loads(got[2])
            self.fleet_id = str(matches[0]["id"])
        except (ValueError, KeyError, IndexError, TypeError):
            log.error("calyptia_fleet: unable to find fleet: %s",
                      self.fleet_name)
            return False
        return True

    def _fleet_dir(self) -> str:
        # fleet_name wins over fleet_id (reference
        # generate_base_fleet_directory, in_calyptia_fleet.c:183-189) —
        # and stays stable across the name→id resolution in collect
        return os.path.join(self.config_dir,
                            self.machine_id or "default",
                            self.fleet_name or self.fleet_id or "fleet")

    def collect(self, engine) -> None:
        if not self._resolve_fleet_id():
            return
        tls, verify = self._tls_pair()
        got = sync_http_request(
            self.host, self.port, "GET",
            ENDPOINT_FLEET_CONFIG.format(self.fleet_id),
            {HDR_PROJECT: self.api_key}, tls=tls, tls_verify=verify,
            # bound ingestion itself, not just the post-hoc check — an
            # oversized response is abandoned mid-read
            max_bytes=int(self.max_http_buffer_size) + 4096)
        if got is None or got[0] != 200:
            return
        status, headers, body = got
        if len(body) > self.max_http_buffer_size:
            log.warning("calyptia_fleet: config larger than "
                        "max_http_buffer_size, ignoring")
            return
        lm = headers.get("last-modified")
        if lm:
            try:
                import calendar
                # the header is GMT — timegm, not mktime (which would
                # skew by the host timezone and misorder revisions)
                ts = calendar.timegm(time.strptime(
                    lm, "%a, %d %b %Y %H:%M:%S GMT"))
            except ValueError:
                ts = time.time()
        else:
            ts = time.time()
        if body == self._last_body or (
                self._last_modified and ts <= self._last_modified):
            return  # nothing newer (check_timestamp_is_newer)
        fleet_dir = self._fleet_dir()
        os.makedirs(fleet_dir, exist_ok=True)
        path = os.path.join(fleet_dir, f"{int(ts)}.conf")
        with open(path, "wb") as f:
            f.write(body)
        self._last_modified = ts
        self._last_body = body
        cb = getattr(engine, "reload_callback", None) if engine else None
        if cb is None:
            log.warning("calyptia_fleet: new config stored at %s but "
                        "hot reload is not enabled", path)
            return
        log.info("calyptia_fleet: loading configuration from %s", path)
        engine.reload_config_path = path
        cb()


@registry.register
class CalyptiaCustom(CustomPlugin):
    """custom_calyptia: wires the hidden control-plane pipeline."""

    name = "calyptia"
    description = "Calyptia Cloud control plane"
    config_map = [
        ConfigMapEntry("api_key", "str"),
        ConfigMapEntry("calyptia_host", "str", default=CALYPTIA_HOST),
        ConfigMapEntry("calyptia_port", "int", default=443),
        ConfigMapEntry("calyptia_tls", "bool", default=True),
        ConfigMapEntry("calyptia_tls.verify", "bool", default=True),
        ConfigMapEntry("machine_id", "str"),
        ConfigMapEntry("fleet_id", "str"),
        ConfigMapEntry("fleet_name", "str"),
        ConfigMapEntry("store_path", "str"),
        ConfigMapEntry("fleet_config_dir", "str",
                       default="/tmp/calyptia-fleet"),
        ConfigMapEntry("fleet_interval_sec", "int", default=15),
        ConfigMapEntry("add_label", "slist", multiple=True,
                       slist_max_split=1),
        ConfigMapEntry("register_retry_on_flush", "bool", default=True),
    ]

    def _provision_machine_id(self) -> str:
        """machine_id property > stored machine-id > fresh UUID
        (persisted when store_path is set), custom_calyptia
        create_agent_directory + agent_config_filename flow."""
        if self.machine_id:
            return self.machine_id
        path = None
        if self.store_path:
            path = os.path.join(self.store_path, "machine-id")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    existing = f.read().strip()
                if existing:
                    return existing
            except OSError:
                pass
        mid = uuid.uuid4().hex
        if path:
            try:
                os.makedirs(self.store_path, exist_ok=True)
                with open(path, "w", encoding="utf-8") as f:
                    f.write(mid)
            except OSError:
                pass
        return mid

    def init(self, instance, engine) -> None:
        if not self.api_key:
            raise ValueError("custom calyptia: api_key is required")
        machine_id = self._provision_machine_id()
        tls = "on" if self.calyptia_tls else "off"
        verify = "on" if getattr(self, "calyptia_tls_verify", True) \
            else "off"
        # hidden metrics source → cloud connector (setup_metrics_payload
        # + setup_cloud_output, custom_calyptia/calyptia.c:234-340)
        engine.input("fluentbit_metrics", tag="_calyptia_cloud",
                     scrape_on_start="true", scrape_interval="30")
        out_props = {
            "match": "_calyptia_cloud",
            "api_key": self.api_key,
            "machine_id": machine_id,
            "cloud_host": self.calyptia_host,
            "cloud_port": str(self.calyptia_port),
            "tls": tls,
            "tls.verify": verify,
            "register_retry_on_flush":
                "true" if self.register_retry_on_flush else "false",
        }
        if self.store_path:
            out_props["store_path"] = self.store_path
        if self.fleet_id:
            out_props["fleet_id"] = self.fleet_id
        out_ins = engine.output("calyptia", **out_props)
        for e in self.add_label or []:
            out_ins.set("add_label", " ".join(_parse_label(e)))
        if self.fleet_id or self.fleet_name:
            fleet_props = {
                "tag": "_calyptia_fleet",
                "api_key": self.api_key,
                "host": self.calyptia_host,
                "port": str(self.calyptia_port),
                "tls": tls,
                "tls.verify": verify,
                "machine_id": machine_id,
                "config_dir": self.fleet_config_dir,
                "interval_sec": str(self.fleet_interval_sec),
            }
            if self.fleet_id:
                fleet_props["fleet_id"] = self.fleet_id
            if self.fleet_name:
                fleet_props["fleet_name"] = self.fleet_name
            engine.input("calyptia_fleet", **fleet_props)
