"""in_kafka — native Kafka consumer.

Reference: plugins/in_kafka/in_kafka.c (librdkafka consumer; record
shape in_kafka.c:55-130: {topic, partition, offset, error, key,
payload}). This build speaks the broker protocol directly: Metadata v1
→ ListOffsets v1 (initial position) → Fetch v4 polling, decoding
magic-v2 RecordBatches. With ``group_id`` set it runs the full
consumer-group protocol the way librdkafka's cgrp state machine does:
FindCoordinator → JoinGroup (range assignor computed by the elected
leader) → SyncGroup → committed-offset resume via OffsetFetch,
scheduled Heartbeats with rebalance-triggered rejoin, and
OffsetCommit after consumption. Without a group it is a simple
consumer reading every partition of the configured topics;
``initial_offset`` picks latest/earliest.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from ..utils import kafka_protocol as kp

log = logging.getLogger("flb.in_kafka")


@registry.register
class KafkaInput(InputPlugin):
    name = "kafka"
    description = "Kafka consumer (native wire protocol + groups)"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("brokers", "str", default="127.0.0.1:9092"),
        ConfigMapEntry("topics", "str"),
        ConfigMapEntry("poll_ms", "int", default=500),
        ConfigMapEntry("format", "str", default="none",
                       desc="none | json (parse payloads)"),
        ConfigMapEntry("initial_offset", "str", default="latest",
                       desc="latest | earliest"),
        ConfigMapEntry("client_id", "str", default="fluentbit-tpu"),
        ConfigMapEntry("group_id", "str",
                       desc="consumer group: coordinator discovery, "
                            "join/sync with the range assignor, "
                            "heartbeats, committed offsets"),
        ConfigMapEntry("session_timeout_ms", "int", default=10000),
    ]

    def init(self, instance, engine) -> None:
        if not self.topics:
            raise ValueError("in_kafka requires 'topics'")
        self._topics = [t.strip() for t in self.topics.split(",")
                        if t.strip()]
        self._brokers: List[Tuple[str, int]] = []
        for item in (self.brokers or "").split(","):
            item = item.strip()
            if item:
                host, _, port = item.partition(":")
                self._brokers.append((host, int(port or 9092)))
        if not self._brokers:
            raise ValueError("in_kafka: no brokers configured")
        self._offsets: Dict[Tuple[str, int], int] = {}
        self._expected_parts = 0
        self._corr = 0
        self._pools: Dict[Tuple[str, int], object] = {}
        # consumer-group state (librdkafka's cgrp state machine)
        self._member_id = ""
        self._generation = -1
        self._coordinator: Optional[Tuple[str, int]] = None
        self._assignment: Dict[str, List[int]] = {}
        self._last_heartbeat = 0.0
        self._hb_ok = time.monotonic()
        self._uncommitted = False
        # partitions whose COMMITTED offset came back trimmed
        # (OFFSET_OUT_OF_RANGE): resolution bypasses OffsetFetch
        self._oor: set = set()

    def _pool(self, addr):
        from ..core.upstream import Upstream

        pool = self._pools.get(addr)
        if pool is None:
            self._pools[addr] = pool = Upstream(
                self.instance, addr[0], addr[1], connect_timeout=10.0)
        return pool

    def exit(self) -> None:
        for pool in self._pools.values():
            pool.close()
        self._pools.clear()

    async def _rpc_to(self, addr, api: int, version: int,
                      body: bytes) -> bytes:
        """Pooled request/response against ONE broker address."""
        self._corr += 1
        corr = self._corr
        pool = self._pool(addr)
        reader, writer, _reused, uses = await pool.get()
        try:
            writer.write(kp.request(api, version, corr,
                                    self.client_id or "fbtpu", body))
            await asyncio.wait_for(writer.drain(), 10.0)
            raw = await asyncio.wait_for(reader.readexactly(4), 10.0)
            n = int.from_bytes(raw, "big")
            if n < 4 or n > 64 * 1024 * 1024:
                raise kp.KafkaProtocolError("bad response length")
            payload = await asyncio.wait_for(
                reader.readexactly(n), 15.0)
            got, rest = kp.parse_response_header(payload)
            if got != corr:
                raise kp.KafkaProtocolError("correlation mismatch")
        except (OSError, asyncio.TimeoutError,
                asyncio.IncompleteReadError, kp.KafkaProtocolError):
            pool.release(reader, writer, reusable=False)
            raise
        pool.release(reader, writer, reusable=True, use_count=uses)
        return rest

    async def _rpc(self, api: int, version: int, body: bytes) -> bytes:
        """_rpc_to over the bootstrap list (first reachable wins; the
        poll loop runs twice a second — per-RPC TCP churn would defeat
        the shared keepalive layer)."""
        last: Exception = OSError("no brokers reachable")
        for addr in self._brokers:
            try:
                return await self._rpc_to(addr, api, version, body)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError,
                    kp.KafkaProtocolError) as e:
                last = e
                continue
        raise last

    async def _bootstrap(self) -> bool:
        try:
            rest = await self._rpc(kp.API_METADATA, 1,
                                   kp.metadata_request(self._topics))
            _nodes, tops, errors = kp.parse_metadata_response(rest)
            for t, err in errors.items():
                log.warning("in_kafka: metadata error %d for %s", err, t)
            want: Dict[str, List[int]] = {
                t: sorted(parts) for t, parts in tops.items() if parts
            }
            if not want:
                return False
            ts = -2 if (self.initial_offset or "latest").lower() \
                == "earliest" else -1
            rest = await self._rpc(kp.API_LIST_OFFSETS, 1,
                                   kp.list_offsets_request(want, ts))
            for topic, pid, err, off in \
                    kp.parse_list_offsets_response(rest):
                if err == 0 and (topic, pid) not in self._offsets:
                    self._offsets[(topic, pid)] = off
            self._expected_parts = max(
                getattr(self, "_expected_parts", 0), len(self._offsets))
            return bool(self._offsets)
        except (OSError, asyncio.TimeoutError,
                kp.KafkaProtocolError) as e:
            log.debug("in_kafka bootstrap failed: %s", e)
            return False

    # -- consumer group (librdkafka cgrp state machine) ---------------

    def _reset_group(self, forget_member: bool = False) -> None:
        self._generation = -1
        self._assignment = {}
        self._offsets = {}
        # OFFSET_OUT_OF_RANGE markers must not survive a rebalance:
        # another member may have committed a VALID offset since, and a
        # stale marker would bypass OffsetFetch on reassignment and
        # reset the partition to latest/earliest (skipping or
        # duplicating records — ADVICE.md low)
        self._oor.clear()
        # fresh session: a stale pre-outage timestamp would turn the
        # FIRST transient heartbeat failure after rejoin into another
        # full reset (rebalance churn on flaky networks)
        self._hb_ok = time.monotonic()
        if forget_member:
            self._member_id = ""

    async def _group_bootstrap(self) -> bool:
        """FindCoordinator → JoinGroup → SyncGroup (leader runs the
        range assignor) → OffsetFetch/ListOffsets for the assignment."""
        try:
            # topic → partitions via metadata (the leader needs the
            # full partition map to assign)
            rest = await self._rpc(kp.API_METADATA, 1,
                                   kp.metadata_request(self._topics))
            _nodes, tops, _errors = kp.parse_metadata_response(rest)
            partitions = {t: sorted(p) for t, p in tops.items() if p}
            if not partitions:
                return False
            rest = await self._rpc(
                kp.API_FIND_COORDINATOR, 0,
                kp.find_coordinator_request(self.group_id))
            err, _node, host, port = \
                kp.parse_find_coordinator_response(rest)
            if err:
                log.warning("in_kafka: FindCoordinator error %d", err)
                return False
            self._coordinator = (host, port)
            rest = await self._rpc_to(
                self._coordinator, kp.API_JOIN_GROUP, 0,
                kp.join_group_request(self.group_id,
                                      int(self.session_timeout_ms),
                                      self._member_id, self._topics))
            err, gen, _proto, leader, member_id, members = \
                kp.parse_join_group_response(rest)
            if err == kp.ERR_UNKNOWN_MEMBER_ID:
                self._reset_group(forget_member=True)
                return False
            if err:
                log.warning("in_kafka: JoinGroup error %d", err)
                return False
            self._member_id = member_id
            self._generation = gen
            assignments = []
            if leader == member_id:
                plan = kp.range_assign(members, partitions)
                assignments = [(mid, kp.consumer_assignment(p))
                               for mid, p in plan.items()]
            rest = await self._rpc_to(
                self._coordinator, kp.API_SYNC_GROUP, 0,
                kp.sync_group_request(self.group_id, gen, member_id,
                                      assignments))
            err, blob = kp.parse_sync_group_response(rest)
            if err:
                log.warning("in_kafka: SyncGroup error %d", err)
                self._reset_group(
                    forget_member=(err == kp.ERR_UNKNOWN_MEMBER_ID))
                return False
            self._assignment = kp.parse_consumer_assignment(blob)
            if not self._assignment:
                log.info("in_kafka: empty assignment (generation %d)",
                         gen)
            self._offsets = {}
            await self._resolve_group_offsets()
            self._expected_parts = sum(
                len(p) for p in self._assignment.values())
            self._last_heartbeat = time.monotonic()
            log.info("in_kafka: joined group %r generation %d as %s "
                     "(%d partitions)", self.group_id, gen, member_id,
                     self._expected_parts)
            return True
        except (OSError, asyncio.TimeoutError,
                kp.KafkaProtocolError) as e:
            log.debug("in_kafka group bootstrap failed: %s", e)
            return False

    async def _resolve_group_offsets(self) -> None:
        """Committed offsets first; -1 (no commit) falls back to the
        configured initial_offset via ListOffsets. Only partitions
        with NO in-memory position are touched — consumed-but-not-yet-
        committed progress on healthy partitions must never be wound
        back to the committed offset (that re-emits duplicates)."""
        if not self._assignment:
            return
        missing: Dict[str, List[int]] = {}
        for topic, pids in self._assignment.items():
            for pid in pids:
                if (topic, pid) not in self._offsets:
                    missing.setdefault(topic, []).append(pid)
        if not missing:
            return
        # partitions whose committed offset was trimmed
        # (OFFSET_OUT_OF_RANGE) bypass OffsetFetch entirely
        oor_now = {tp for tp in self._oor
                   if tp[0] in missing and tp[1] in missing[tp[0]]}
        fetchable = {t: [p for p in ps if (t, p) not in oor_now]
                     for t, ps in missing.items()}
        fetchable = {t: ps for t, ps in fetchable.items() if ps}
        uncommitted: Dict[str, List[int]] = {}
        for topic, pid in oor_now:
            uncommitted.setdefault(topic, []).append(pid)
        if fetchable:
            rest = await self._rpc_to(
                self._coordinator, kp.API_OFFSET_FETCH, 1,
                kp.offset_fetch_request(self.group_id, fetchable))
            for topic, pid, off, err in \
                    kp.parse_offset_fetch_response(rest):
                if err == 0 and off >= 0:
                    self._offsets[(topic, pid)] = off
                else:
                    uncommitted.setdefault(topic, []).append(pid)
        if uncommitted:
            ts = -2 if (self.initial_offset or "latest").lower() \
                == "earliest" else -1
            rest = await self._rpc(kp.API_LIST_OFFSETS, 1,
                                   kp.list_offsets_request(uncommitted,
                                                           ts))
            for topic, pid, err, off in \
                    kp.parse_list_offsets_response(rest):
                if err == 0:
                    self._offsets[(topic, pid)] = off
                    if (topic, pid) in self._oor:
                        self._oor.discard((topic, pid))
                        # commit the reset position promptly so a
                        # rebalance doesn't hand the trimmed offset to
                        # another member
                        self._uncommitted = True

    async def _group_heartbeat_and_commit(self) -> bool:
        """Heartbeat on schedule + commit consumed offsets; returns
        False when the group must be rejoined (rebalance)."""
        now = time.monotonic()
        interval = max(1.0, int(self.session_timeout_ms) / 3000.0)
        if self._uncommitted and self._offsets:
            try:
                rest = await self._rpc_to(
                    self._coordinator, kp.API_OFFSET_COMMIT, 2,
                    kp.offset_commit_request(
                        self.group_id, self._generation,
                        self._member_id, self._offsets))
                errs = [e for _t, _p, e in
                        kp.parse_offset_commit_response(rest) if e]
                if any(e in (kp.ERR_ILLEGAL_GENERATION,
                             kp.ERR_REBALANCE_IN_PROGRESS,
                             kp.ERR_UNKNOWN_MEMBER_ID) for e in errs):
                    return False
                if errs:
                    # transient rejection (coordinator loading, ...):
                    # keep _uncommitted so the commit retries
                    log.debug("in_kafka commit errors: %s", errs)
                else:
                    self._uncommitted = False
            except (OSError, asyncio.TimeoutError,
                    kp.KafkaProtocolError) as e:
                log.debug("in_kafka commit failed: %s", e)
        if now - self._last_heartbeat < interval:
            return True
        self._last_heartbeat = now
        try:
            rest = await self._rpc_to(
                self._coordinator, kp.API_HEARTBEAT, 0,
                kp.heartbeat_request(self.group_id, self._generation,
                                     self._member_id))
            err = kp.parse_error_response(rest)
            if err in (kp.ERR_REBALANCE_IN_PROGRESS,
                       kp.ERR_ILLEGAL_GENERATION):
                log.info("in_kafka: rebalance signalled (%d)", err)
                return False
            if err == kp.ERR_UNKNOWN_MEMBER_ID:
                self._reset_group(forget_member=True)
                return False
            self._hb_ok = now
            return True
        except (OSError, asyncio.TimeoutError,
                kp.KafkaProtocolError) as e:
            log.debug("in_kafka heartbeat failed: %s", e)
            # transient failures tolerated only within the session
            # timeout: past it the broker has already evicted this
            # member and rebalanced its partitions elsewhere —
            # continuing to fetch makes a ZOMBIE consuming duplicates
            # it can never commit. Rejoin instead.
            session = max(1.0, int(self.session_timeout_ms) / 1000.0)
            if now - self._hb_ok >= session:
                log.info("in_kafka: no successful heartbeat for %.0fs "
                         "(session timeout) — rejoining group", session)
                self._reset_group(forget_member=True)
                return False
            return True  # transient: keep fetching, retry next tick

    def _emit(self, engine, topic: str, pid: int, base: int,
              records) -> int:
        out = bytearray()
        n = 0
        fmt = (self.format or "none").lower()
        for key, value, _ts, delta in records:
            if value is None:
                payload: object = None  # tombstone (compacted topics)
            else:
                payload = value.decode("utf-8", "replace")
                if fmt == "json":
                    try:
                        payload = json.loads(value)
                    except ValueError:
                        pass  # keep the raw string (reference keeps going)
            body = {
                "topic": topic,
                "partition": pid,
                "offset": base + delta,
                "error": None,
                "key": key.decode("utf-8", "replace")
                if key is not None else None,
                "payload": payload,
            }
            out += encode_event(body, now_event_time())
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)
        return n

    async def _leave_group(self) -> None:
        """Best-effort LeaveGroup so the coordinator reassigns this
        member's partitions immediately instead of after the session
        timeout (librdkafka does the same on clean close)."""
        if not (self.group_id and self._coordinator
                and self._member_id and self._generation >= 0):
            return
        try:
            await asyncio.wait_for(
                self._rpc_to(self._coordinator, kp.API_LEAVE_GROUP, 0,
                             kp.leave_group_request(self.group_id,
                                                    self._member_id)),
                1.0)
        except Exception as e:  # noqa: BLE001 — shutdown must not stall
            log.debug("leave_group at shutdown failed: %r", e)

    async def start_server(self, engine) -> None:
        try:
            await self._run(engine)
        except asyncio.CancelledError:
            await self._leave_group()
            raise

    async def _run(self, engine) -> None:
        poll = max(0.05, float(self.poll_ms or 500) / 1000.0)
        grouped = bool(self.group_id)
        if grouped:
            # the Fetch long-poll must stay well under the heartbeat
            # interval or the coordinator evicts the member mid-fetch
            poll = min(poll, max(0.05,
                                 int(self.session_timeout_ms) / 6000.0))
            while not await self._group_bootstrap():
                await asyncio.sleep(poll)
        else:
            while not await self._bootstrap():
                await asyncio.sleep(poll)
        while True:
            try:
                if grouped:
                    ok = await self._group_heartbeat_and_commit()
                    if not ok or self._generation < 0:
                        self._reset_group()
                        while not await self._group_bootstrap():
                            await asyncio.sleep(poll)
                        continue
                parts: Dict[str, List[Tuple[int, int]]] = {}
                for (topic, pid), off in self._offsets.items():
                    parts.setdefault(topic, []).append((pid, off))
                rest = await self._rpc(
                    kp.API_FETCH, 4,
                    kp.fetch_request(parts,
                                     max_wait_ms=int(poll * 1000)))
                got_any = False
                for topic, pid, err, _hw, record_set in \
                        kp.parse_fetch_response(rest):
                    if err:
                        log.warning("in_kafka fetch error %d on %s[%d]",
                                    err, topic, pid)
                        # stale leadership / trimmed offset: drop the
                        # position so the next bootstrap re-resolves it
                        # via Metadata + ListOffsets instead of
                        # re-fetching the same failure forever
                        self._offsets.pop((topic, pid), None)
                        if err == kp.ERR_OFFSET_OUT_OF_RANGE:
                            # the COMMITTED offset itself is trimmed:
                            # grouped-mode re-resolution must skip
                            # OffsetFetch (it would hand the same bad
                            # offset back forever) and go straight to
                            # ListOffsets
                            self._oor.add((topic, pid))
                        continue
                    for base, crc_ok, records, next_off in \
                            kp.iter_record_batches(record_set):
                        if not crc_ok:
                            log.warning("in_kafka: CRC mismatch on "
                                        "%s[%d]@%d", topic, pid, base)
                            continue
                        if self._emit(engine, topic, pid, base, records):
                            got_any = True
                        # honors lastOffsetDelta (compacted batches)
                        self._offsets[(topic, pid)] = next_off
                        if grouped:
                            self._uncommitted = True
                if not got_any:
                    await asyncio.sleep(poll)
                if len(self._offsets) < self._expected_parts:
                    # partitions dropped by fetch errors re-resolve:
                    # grouped mode re-reads committed offsets, the
                    # simple consumer re-runs Metadata + ListOffsets
                    if grouped:
                        try:
                            await self._resolve_group_offsets()
                        except (OSError, asyncio.TimeoutError,
                                kp.KafkaProtocolError):
                            pass
                    else:
                        await self._bootstrap()
            except asyncio.CancelledError:
                raise
            except (OSError, asyncio.TimeoutError,
                    kp.KafkaProtocolError) as e:
                log.debug("in_kafka poll failed: %s", e)
                await asyncio.sleep(poll)
