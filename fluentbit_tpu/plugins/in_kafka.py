"""in_kafka — native Kafka consumer (simple/partition mode).

Reference: plugins/in_kafka/in_kafka.c (librdkafka consumer; record
shape in_kafka.c:55-130: {topic, partition, offset, error, key,
payload}). This build speaks the broker protocol directly: Metadata v1
→ ListOffsets v1 (initial position) → Fetch v4 polling, decoding
magic-v2 RecordBatches. Documented divergence: no consumer-group
coordination (librdkafka's group_id rebalancing needs the group
protocol) — this is a simple consumer reading every partition of the
configured topics; ``initial_offset`` picks latest/earliest.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, List, Optional, Tuple

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from ..utils import kafka_protocol as kp

log = logging.getLogger("flb.in_kafka")


@registry.register
class KafkaInput(InputPlugin):
    name = "kafka"
    description = "Kafka consumer (native wire protocol, no groups)"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("brokers", "str", default="127.0.0.1:9092"),
        ConfigMapEntry("topics", "str"),
        ConfigMapEntry("poll_ms", "int", default=500),
        ConfigMapEntry("format", "str", default="none",
                       desc="none | json (parse payloads)"),
        ConfigMapEntry("initial_offset", "str", default="latest",
                       desc="latest | earliest"),
        ConfigMapEntry("client_id", "str", default="fluentbit-tpu"),
        ConfigMapEntry("group_id", "str",
                       desc="accepted for parity; group coordination "
                            "is not implemented (simple consumer)"),
    ]

    def init(self, instance, engine) -> None:
        if not self.topics:
            raise ValueError("in_kafka requires 'topics'")
        self._topics = [t.strip() for t in self.topics.split(",")
                        if t.strip()]
        self._brokers: List[Tuple[str, int]] = []
        for item in (self.brokers or "").split(","):
            item = item.strip()
            if item:
                host, _, port = item.partition(":")
                self._brokers.append((host, int(port or 9092)))
        if not self._brokers:
            raise ValueError("in_kafka: no brokers configured")
        if self.group_id:
            log.warning("in_kafka: group_id is accepted but consumer-"
                        "group coordination is not implemented")
        self._offsets: Dict[Tuple[str, int], int] = {}
        self._expected_parts = 0
        self._corr = 0
        self._pools: Dict[Tuple[str, int], object] = {}

    def _pool(self, addr):
        from ..core.upstream import Upstream

        pool = self._pools.get(addr)
        if pool is None:
            self._pools[addr] = pool = Upstream(
                self.instance, addr[0], addr[1], connect_timeout=10.0)
        return pool

    def exit(self) -> None:
        for pool in self._pools.values():
            pool.close()
        self._pools.clear()

    async def _rpc(self, api: int, version: int, body: bytes) -> bytes:
        """Pooled request/response (the poll loop runs twice a second
        — per-RPC TCP churn would defeat the shared keepalive layer)."""
        self._corr += 1
        corr = self._corr
        last: Exception = OSError("no brokers reachable")
        for addr in self._brokers:
            pool = self._pool(addr)
            try:
                reader, writer, _reused, uses = await pool.get()
            except (OSError, asyncio.TimeoutError) as e:
                last = e
                continue
            try:
                writer.write(kp.request(api, version, corr,
                                        self.client_id or "fbtpu",
                                        body))
                await asyncio.wait_for(writer.drain(), 10.0)
                raw = await asyncio.wait_for(reader.readexactly(4), 10.0)
                n = int.from_bytes(raw, "big")
                if n < 4 or n > 64 * 1024 * 1024:
                    raise kp.KafkaProtocolError("bad response length")
                payload = await asyncio.wait_for(
                    reader.readexactly(n), 15.0)
                got, rest = kp.parse_response_header(payload)
                if got != corr:
                    raise kp.KafkaProtocolError("correlation mismatch")
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError,
                    kp.KafkaProtocolError) as e:
                pool.release(reader, writer, reusable=False)
                last = e
                continue
            pool.release(reader, writer, reusable=True, use_count=uses)
            return rest
        raise last

    async def _bootstrap(self) -> bool:
        try:
            rest = await self._rpc(kp.API_METADATA, 1,
                                   kp.metadata_request(self._topics))
            _nodes, tops, errors = kp.parse_metadata_response(rest)
            for t, err in errors.items():
                log.warning("in_kafka: metadata error %d for %s", err, t)
            want: Dict[str, List[int]] = {
                t: sorted(parts) for t, parts in tops.items() if parts
            }
            if not want:
                return False
            ts = -2 if (self.initial_offset or "latest").lower() \
                == "earliest" else -1
            rest = await self._rpc(kp.API_LIST_OFFSETS, 1,
                                   kp.list_offsets_request(want, ts))
            for topic, pid, err, off in \
                    kp.parse_list_offsets_response(rest):
                if err == 0 and (topic, pid) not in self._offsets:
                    self._offsets[(topic, pid)] = off
            self._expected_parts = max(
                getattr(self, "_expected_parts", 0), len(self._offsets))
            return bool(self._offsets)
        except (OSError, asyncio.TimeoutError,
                kp.KafkaProtocolError) as e:
            log.debug("in_kafka bootstrap failed: %s", e)
            return False

    def _emit(self, engine, topic: str, pid: int, base: int,
              records) -> int:
        out = bytearray()
        n = 0
        fmt = (self.format or "none").lower()
        for key, value, _ts, delta in records:
            if value is None:
                payload: object = None  # tombstone (compacted topics)
            else:
                payload = value.decode("utf-8", "replace")
                if fmt == "json":
                    try:
                        payload = json.loads(value)
                    except ValueError:
                        pass  # keep the raw string (reference keeps going)
            body = {
                "topic": topic,
                "partition": pid,
                "offset": base + delta,
                "error": None,
                "key": key.decode("utf-8", "replace")
                if key is not None else None,
                "payload": payload,
            }
            out += encode_event(body, now_event_time())
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)
        return n

    async def start_server(self, engine) -> None:
        poll = max(0.05, float(self.poll_ms or 500) / 1000.0)
        while not await self._bootstrap():
            await asyncio.sleep(poll)
        while True:
            try:
                parts: Dict[str, List[Tuple[int, int]]] = {}
                for (topic, pid), off in self._offsets.items():
                    parts.setdefault(topic, []).append((pid, off))
                rest = await self._rpc(
                    kp.API_FETCH, 4,
                    kp.fetch_request(parts,
                                     max_wait_ms=int(poll * 1000)))
                got_any = False
                for topic, pid, err, _hw, record_set in \
                        kp.parse_fetch_response(rest):
                    if err:
                        log.warning("in_kafka fetch error %d on %s[%d]",
                                    err, topic, pid)
                        # stale leadership / trimmed offset: drop the
                        # position so the next bootstrap re-resolves it
                        # via Metadata + ListOffsets instead of
                        # re-fetching the same failure forever
                        self._offsets.pop((topic, pid), None)
                        continue
                    for base, crc_ok, records, next_off in \
                            kp.iter_record_batches(record_set):
                        if not crc_ok:
                            log.warning("in_kafka: CRC mismatch on "
                                        "%s[%d]@%d", topic, pid, base)
                            continue
                        if self._emit(engine, topic, pid, base, records):
                            got_any = True
                        # honors lastOffsetDelta (compacted batches)
                        self._offsets[(topic, pid)] = next_off
                if not got_any:
                    await asyncio.sleep(poll)
                if len(self._offsets) < self._expected_parts:
                    # partitions dropped by fetch errors re-resolve
                    # through a fresh Metadata + ListOffsets pass
                    await self._bootstrap()
            except asyncio.CancelledError:
                raise
            except (OSError, asyncio.TimeoutError,
                    kp.KafkaProtocolError) as e:
                log.debug("in_kafka poll failed: %s", e)
                await asyncio.sleep(poll)
