"""filter_kubernetes — pod metadata enrichment.

Reference: plugins/filter_kubernetes (kubernetes.c, kube_meta.c,
kube_property.c, kube_regex.h). Tag → pod identity (the in_tail
``kube.var.log.containers.<pod>_<namespace>_<container>-<id>.log``
convention), metadata from a TTL cache fed by (a) a pre-warmed cache
directory of ``<namespace>_<pod>.meta`` JSON files (kube_meta.c:331-360
— the offline/test path), or (b) a GET against ``kube_url`` —
https with the service-account CA (``kube_ca_file``) and bearer token
(``kube_token_file`` / ``kube_token_command``, TTL-refreshed with a
401-driven re-read; kube_meta.c:101-191,240-248). ``merge_log`` parses the
``log`` field (JSON or a named parser) into structured fields
(kubernetes.c:295-330); pod annotations ``fluentbit.io/parser`` and
``fluentbit.io/exclude`` override per-pod behavior when enabled by
``k8s-logging.parser`` / ``k8s-logging.exclude`` (kube_property.c).

Mostly host-side work (SURVEY §2.5: network + cache); the merge_log
JSON parse is the device-batch candidate once the JSON field-extraction
kernel lands.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional, Tuple

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..regex import FlbRegex

log = logging.getLogger("flb.kube")

DEFAULT_TAG_PREFIX = "kube.var.log.containers."

#: `<pod>_<namespace>_<container>-<docker_id>.log` (kube_regex.h tag regex
#: shape, re-specified)
TAG_REGEX = (
    r"(?<pod_name>[a-z0-9](?:[-a-z0-9.]*[a-z0-9])?)_"
    r"(?<namespace_name>[^_]+)_"
    r"(?<container_name>.+)-(?<docker_id>[a-f0-9]{12,64})\.log$"
)


@registry.register
class KubernetesFilter(FilterPlugin):
    name = "kubernetes"
    description = "enrich records with Kubernetes pod metadata"
    config_map = [
        ConfigMapEntry("kube_tag_prefix", "str", default=DEFAULT_TAG_PREFIX),
        ConfigMapEntry("kube_url", "str"),
        ConfigMapEntry("kube_meta_preload_cache_dir", "str"),
        ConfigMapEntry("kube_meta_cache_ttl", "time", default="0"),
        ConfigMapEntry("regex_parser", "str"),
        ConfigMapEntry("merge_log", "bool", default=False),
        ConfigMapEntry("merge_log_key", "str"),
        ConfigMapEntry("merge_log_trim", "bool", default=True),
        ConfigMapEntry("merge_parser", "str"),
        ConfigMapEntry("keep_log", "bool", default=True),
        ConfigMapEntry("labels", "bool", default=True),
        ConfigMapEntry("annotations", "bool", default=True),
        ConfigMapEntry("k8s-logging.parser", "bool", default=False),
        ConfigMapEntry("k8s-logging.exclude", "bool", default=False),
        ConfigMapEntry("buffer_size", "str", default="32k"),
        ConfigMapEntry("tls.verify", "bool", default=True),
        ConfigMapEntry("use_kubelet", "bool", default=False),
        ConfigMapEntry("kube_ca_file", "str",
                       default="/var/run/secrets/kubernetes.io/"
                               "serviceaccount/ca.crt"),
        ConfigMapEntry("kube_token_file", "str",
                       default="/var/run/secrets/kubernetes.io/"
                               "serviceaccount/token"),
        ConfigMapEntry("kube_token_command", "str"),
        ConfigMapEntry("kube_token_ttl", "time", default="10m"),
    ]

    def init(self, instance, engine) -> None:
        self._engine = engine
        self._tag_rx = FlbRegex(TAG_REGEX)
        self._cache: Dict[Tuple[str, str], Tuple[float, dict]] = {}
        self._merge_parser = None
        self._token: Optional[str] = None
        self._token_created = 0.0
        if self.merge_parser:
            self._merge_parser = (engine.parsers if engine else {}).get(
                self.merge_parser
            )
            if self._merge_parser is None:
                raise ValueError(
                    f"kubernetes: unknown merge_parser {self.merge_parser!r}"
                )

    # -- identity + metadata --

    def tag_to_identity(self, tag: str) -> Optional[Dict[str, str]]:
        """kube.var.log.containers.<pod>_<ns>_<ctr>-<id>.log → fields."""
        rest = tag
        prefix = self.kube_tag_prefix or ""
        if prefix and rest.startswith(prefix):
            rest = rest[len(prefix):]
        return self._tag_rx.parse_record(rest)

    def _load_meta(self, namespace: str, pod: str) -> dict:
        key = (namespace, pod)
        hit = self._cache.get(key)
        now = time.monotonic()
        ttl = self.kube_meta_cache_ttl or 0
        if hit is not None and (ttl <= 0 or now - hit[0] < ttl):
            return hit[1]
        meta = {}
        if self.kube_meta_preload_cache_dir:
            path = os.path.join(self.kube_meta_preload_cache_dir,
                                f"{namespace}_{pod}.meta")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {}
        if not meta and self.kube_url:
            meta = self._fetch_meta(namespace, pod)
        self._cache[key] = (now, meta)
        return meta

    def _auth_token(self) -> Optional[str]:
        """Service-account bearer token, refreshed every kube_token_ttl
        (kube_meta.c:101-191 get_token_with_command / file_to_buffer,
        refresh_token_if_needed at :240-248)."""
        now = time.monotonic()
        ttl = self.kube_token_ttl or 600
        if self._token is not None and now - self._token_created < ttl:
            return self._token
        if now < getattr(self, "_token_retry_at", 0.0):
            return self._token  # failed refresh backs off (stale token)
        token = None
        if self.kube_token_command:
            import subprocess

            try:
                proc = subprocess.run(self.kube_token_command, shell=True,
                                      capture_output=True, timeout=10)
                if proc.returncode == 0 and proc.stdout.strip():
                    token = proc.stdout.strip().decode(
                        "utf-8", "replace")
                else:
                    log.warning("kubernetes: kube_token_command failed "
                                "rc=%d", proc.returncode)
            except (OSError, subprocess.TimeoutExpired) as e:
                log.warning("kubernetes: kube_token_command: %s", e)
        elif self.kube_token_file:
            try:
                with open(self.kube_token_file, encoding="utf-8") as f:
                    token = f.read().strip()
            except OSError:
                pass  # not in-cluster: unauthenticated fetch
        if token:
            self._token = token
            self._token_created = now
            self._token_retry_at = 0.0
        else:
            # a hanging/failing kube_token_command must not re-run
            # (blocking, up to 10 s) on every cache miss
            self._token_retry_at = now + 30.0
        return self._token

    def _fetch_meta(self, namespace: str, pod: str) -> dict:
        """Blocking GET of the pod object (API-server path shape:
        /api/v1/namespaces/<ns>/pods/<pod>) — https with the
        service-account CA + bearer token when kube_url is https
        (kube_meta.c:101-191; TLS to the apiserver is the in-cluster
        default, flb_kube_conf.c FLB_API_TLS)."""
        url = self.kube_url.rstrip("/")
        use_tls = url.startswith("https://")
        if not use_tls and not url.startswith("http://"):
            log.warning("kubernetes: kube_url must be http(s)://")
            return {}
        from ..utils import sync_http_request

        hostport = url.split("://", 1)[1].split("/")[0]
        host, _, port = hostport.partition(":")
        try:
            port_n = int(port or (443 if use_tls else 80))
        except ValueError:
            log.warning("kubernetes: malformed kube_url port %r", port)
            return {}
        path = f"/api/v1/namespaces/{namespace}/pods/{pod}"
        headers = {}
        token = self._auth_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        ca = self.kube_ca_file if (self.kube_ca_file
                                   and os.path.exists(self.kube_ca_file)) \
            else None
        got = sync_http_request(
            host, port_n, "GET", path, headers=headers, timeout=3,
            tls=use_tls, tls_verify=bool(self.tls_verify),
            tls_ca_file=ca)
        if got is None:
            return {}
        status, _hdrs, body = got
        if status == 401 and token:
            # token rotated under us: force a refresh and retry once
            # (also clear the failure backoff — the 401 IS the signal
            # that a re-read is worth it right now)
            self._token = None
            self._token_created = 0.0
            self._token_retry_at = 0.0
            token = self._auth_token()
            if token:
                headers["Authorization"] = f"Bearer {token}"
                got = sync_http_request(
                    host, port_n, "GET", path, headers=headers, timeout=3,
                    tls=use_tls, tls_verify=bool(self.tls_verify),
                    tls_ca_file=ca)
                if got is None:
                    return {}
                status, _hdrs, body = got
        if status != 200:
            return {}
        try:
            return json.loads(body)
        except ValueError:
            return {}

    def _kubernetes_map(self, identity: dict, meta: dict) -> dict:
        k8s: Dict[str, Any] = {
            "pod_name": identity["pod_name"],
            "namespace_name": identity["namespace_name"],
            "container_name": identity["container_name"],
            "docker_id": identity["docker_id"],
        }
        md = meta.get("metadata", {}) if isinstance(meta, dict) else {}
        spec = meta.get("spec", {}) if isinstance(meta, dict) else {}
        if md.get("uid"):
            k8s["pod_id"] = md["uid"]
        if spec.get("nodeName"):
            k8s["host"] = spec["nodeName"]
        if self.labels and md.get("labels"):
            k8s["labels"] = md["labels"]
        if self.annotations and md.get("annotations"):
            k8s["annotations"] = md["annotations"]
        return k8s

    def _pod_properties(self, meta: dict) -> dict:
        """fluentbit.io/* annotations gated by k8s-logging.* options."""
        out = {}
        anns = (meta.get("metadata", {}) or {}).get("annotations", {}) \
            if isinstance(meta, dict) else {}
        if not isinstance(anns, dict):
            return out
        if getattr(self, "k8s_logging_parser", False):
            p = anns.get("fluentbit.io/parser")
            if p:
                out["parser"] = p
        if getattr(self, "k8s_logging_exclude", False):
            ex = str(anns.get("fluentbit.io/exclude", "")).lower()
            if ex in ("true", "on", "1", "yes"):
                out["exclude"] = True
        return out

    # -- merge_log --

    def _merge(self, ev: LogEvent, props: dict) -> Optional[dict]:
        """Parse the log field into structured fields; returns the new
        body or None when nothing merged."""
        content = ev.body.get("log")
        if not isinstance(content, str):
            return None
        if self.merge_log_trim:
            content = content.rstrip()
        parsed = None
        parser = self._merge_parser
        pname = props.get("parser")
        if pname and self._engine is not None:
            parser = self._engine.parsers.get(pname, parser)
        if parser is not None:
            got = parser.do(content)
            if got is not None:
                parsed = got[0]
        elif content[:1] == "{":
            try:
                obj = json.loads(content)
                if isinstance(obj, dict):
                    parsed = obj
            except ValueError:
                parsed = None
        if parsed is None:
            return None
        body = dict(ev.body)
        if not self.keep_log:
            body.pop("log", None)
        if self.merge_log_key:
            body[self.merge_log_key] = parsed
        else:
            for k, v in parsed.items():
                body.setdefault(k, v)
        return body

    # -- the filter --

    def filter(self, events: list, tag: str, engine) -> tuple:
        identity = self.tag_to_identity(tag)
        if identity is None:
            return (FilterResult.NOTOUCH, events)
        meta = self._load_meta(identity["namespace_name"],
                               identity["pod_name"])
        k8s = self._kubernetes_map(identity, meta)
        props = self._pod_properties(meta)
        if props.get("exclude"):
            return (FilterResult.MODIFIED, [])
        out = []
        for ev in events:
            if not isinstance(ev.body, dict):
                out.append(ev)
                continue
            body = (self._merge(ev, props) if self.merge_log else None) \
                or dict(ev.body)
            body["kubernetes"] = k8s
            out.append(LogEvent(timestamp=ev.timestamp, body=body,
                                metadata=ev.metadata, raw=None))
        return (FilterResult.MODIFIED, out)
