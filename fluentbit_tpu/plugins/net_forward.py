"""Fluentd Forward protocol — in_forward server + out_forward client.

Reference: plugins/in_forward (Fluentd protocol server, fw_prot.c) and
plugins/out_forward (forward.c: msgpack over TCP, modes Message /
Forward / PackedForward, ack via the ``chunk`` option, shared-key
HELO/PING/PONG handshake :259-340). The protocol is the reference's
"cluster fabric" (SURVEY §5): agent→aggregator fan-in/out over DCN.

Wire formats accepted by the server:
- Message:        ``[tag, time, record, option?]``
- Forward:        ``[tag, [[time, record], ...], option?]``
- PackedForward:  ``[tag, bin(msgpack stream of [time, record]), option?]``
  (CompressedPackedForward when option.compressed == "gzip")
When ``option.chunk`` is present the server replies ``{"ack": chunk}``
(at-least-once). The client sends PackedForward, optionally gzip'd,
with ``require_ack_response`` waiting for the matching ack.
"""

from __future__ import annotations

import asyncio
import gzip
import hashlib
import logging
import os
import socket
from typing import Optional

from ..codec.events import encode_event
from ..codec.msgpack import EventTime, OutOfData, Unpacker, packb
from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import FlushResult, InputPlugin, OutputPlugin, registry
from ..core.upstream import close_quietly

log = logging.getLogger("flb.forward")


def _entries_to_events(entries) -> tuple:
    """Forward entries [[time, record], ...] → (encoded V2 buffer, n)."""
    out = bytearray()
    n = 0
    for entry in entries:
        if not isinstance(entry, (list, tuple)) or len(entry) < 2:
            continue
        ts, record = entry[0], entry[1]
        if not isinstance(record, dict):
            continue
        out += encode_event(record, ts)
        n += 1
    return bytes(out), n


@registry.register
class ForwardInput(InputPlugin):
    name = "forward"
    description = "Fluentd Forward protocol server"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=24224),
        ConfigMapEntry("shared_key", "str"),
        ConfigMapEntry("self_hostname", "str", default="fluentbit-tpu"),
        ConfigMapEntry("tag_prefix", "str"),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    async def start_server(self, engine) -> None:
        async def handle(reader, writer):
            try:
                await self._handle_conn(reader, writer, engine)
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            except Exception:
                log.exception("in_forward connection failed")
            finally:
                close_quietly(writer)

        from ..core.tls import server_context

        server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()

    async def _handle_conn(self, reader, writer, engine) -> None:
        nonce = b""
        if self.shared_key:
            # HELO/PING/PONG handshake (forward.c:259-340 client side)
            nonce = os.urandom(16)
            writer.write(packb(["HELO", {"nonce": nonce, "auth": b"",
                                         "keepalive": True}]))
            await writer.drain()
        u = Unpacker()
        authed = not self.shared_key
        while True:
            data = await reader.read(65536)
            if not data:
                return
            u.feed(data)
            for msg in u:
                if not isinstance(msg, (list, tuple)) or not msg:
                    continue
                if not authed:
                    authed = self._check_ping(msg, nonce, writer)
                    if not authed:
                        return
                    await writer.drain()
                    continue
                await self._dispatch(msg, writer, engine)

    def _check_ping(self, msg, nonce: bytes, writer) -> bool:
        if msg[0] != "PING" or len(msg) < 6:
            return False
        _, hostname, salt, digest = msg[0], msg[1], msg[2], msg[3]
        salt = salt if isinstance(salt, bytes) else str(salt).encode()
        want = hashlib.sha512(
            salt + str(hostname).encode() + nonce + self.shared_key.encode()
        ).hexdigest()
        ok = digest == want
        shared_key_digest = hashlib.sha512(
            salt + self.self_hostname.encode() + nonce
            + self.shared_key.encode()
        ).hexdigest()
        writer.write(packb(["PONG", ok, "" if ok else "shared_key mismatch",
                            self.self_hostname, shared_key_digest]))
        return ok

    async def _dispatch(self, msg, writer, engine) -> None:
        tag = msg[0]
        if not isinstance(tag, str):
            return
        if self.tag_prefix:
            tag = f"{self.tag_prefix}.{tag}"
        option = None
        if isinstance(msg[1], (bytes, memoryview)):
            # PackedForward / CompressedPackedForward
            option = msg[2] if len(msg) > 2 and isinstance(msg[2], dict) else None
            blob = bytes(msg[1])
            if option and option.get("compressed") == "gzip":
                blob = gzip.decompress(blob)
            entries = list(Unpacker(blob))
            buf, n = _entries_to_events(entries)
        elif isinstance(msg[1], (list, tuple)):
            # Forward mode
            option = msg[2] if len(msg) > 2 and isinstance(msg[2], dict) else None
            buf, n = _entries_to_events(msg[1])
        else:
            # Message mode [tag, time, record, option?]
            if len(msg) < 3 or not isinstance(msg[2], dict):
                return
            option = msg[3] if len(msg) > 3 and isinstance(msg[3], dict) else None
            buf, n = _entries_to_events([[msg[1], msg[2]]])
        if n:
            engine.input_log_append(self.instance, tag, buf, n)
        chunk_id = option.get("chunk") if option else None
        if chunk_id is not None:
            writer.write(packb({"ack": chunk_id}))
            await writer.drain()


@registry.register
class ForwardOutput(OutputPlugin):
    name = "forward"
    description = "Fluentd Forward protocol client"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=24224),
        ConfigMapEntry("shared_key", "str"),
        ConfigMapEntry("self_hostname", "str"),
        ConfigMapEntry("require_ack_response", "bool", default=False),
        ConfigMapEntry("compress", "str"),
        ConfigMapEntry("time_as_integer", "bool", default=False),
        ConfigMapEntry("ack_timeout", "time", default="10"),
        ConfigMapEntry("upstream", "str",
                       desc="upstream HA definition file: weighted "
                            "[NODE] sections with failover"),
    ]

    def init(self, instance, engine) -> None:
        self._reader = None
        self._writer = None
        # one connection per output instance: concurrent flush coroutines
        # must not interleave writes or steal each other's acks
        self._lock = asyncio.Lock()
        # upstream HA (flb_upstream_ha.c): weighted nodes + failover
        self._ha = None
        self._node = None
        if self.upstream:
            from ..core.upstream import parse_upstream_file

            self._ha = parse_upstream_file(self.upstream)

    async def _connect(self):
        if self._writer is not None and not self._writer.is_closing():
            return
        from ..core.tls import open_connection

        host, port = self.host, self.port
        if self._ha is not None:
            self._node = self._ha.pick()
            host, port = self._node.host, self._node.port
        try:
            self._reader, self._writer = await open_connection(
                self.instance, host, port, timeout=10
            )
        except (OSError, asyncio.TimeoutError):
            if self._ha is not None and self._node is not None:
                self._ha.mark_down(self._node)
            raise
        if self._ha is not None and self._node is not None:
            self._ha.mark_up(self._node)
        if self.shared_key:
            await self._handshake()

    async def _handshake(self) -> None:
        u = Unpacker()
        helo = await self._read_msg(u)
        if not (isinstance(helo, list) and helo and helo[0] == "HELO"):
            raise ConnectionError("forward: expected HELO")
        nonce = helo[1].get("nonce", b"")
        nonce = nonce if isinstance(nonce, bytes) else str(nonce).encode()
        hostname = self.self_hostname or socket.gethostname()
        salt = os.urandom(16)
        digest = hashlib.sha512(
            salt + hostname.encode() + nonce + self.shared_key.encode()
        ).hexdigest()
        self._writer.write(packb(["PING", hostname, salt, digest, "", ""]))
        await io_deadline(self._writer.drain(), 10)
        pong = await self._read_msg(u)
        if not (isinstance(pong, list) and len(pong) >= 2 and pong[0] == "PONG"
                and pong[1]):
            raise ConnectionError("forward: handshake rejected")

    async def _read_msg(self, u: Unpacker):
        while True:
            try:
                return u.unpack()
            except OutOfData:
                data = await io_deadline(self._reader.read(65536))
                if not data:
                    raise ConnectionError("forward: peer closed")
                u.feed(data)

    def _packed_entries(self, data: bytes) -> tuple:
        """V2 events buffer → forward-format entry stream + count."""
        from ..codec.events import iter_events

        out = bytearray()
        n = 0
        for ev in iter_events(data):
            ts = ev.timestamp
            if self.time_as_integer:
                ts = int(ev.ts_float)
            elif isinstance(ts, float):
                ts = EventTime.from_float(ts)
            out += packb([ts, ev.body])
            n += 1
        return bytes(out), n

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        async with self._lock:
            return await self._flush_locked(data, tag)

    async def _flush_locked(self, data: bytes, tag: str) -> FlushResult:
        try:
            await self._connect()
            blob, n = self._packed_entries(data)
            if n == 0:
                return FlushResult.OK
            option = {"size": n, "fluent_signal": 1}
            if (self.compress or "").lower() == "gzip":
                blob = gzip.compress(blob)
                option["compressed"] = "gzip"
            chunk_id = None
            if self.require_ack_response:
                chunk_id = os.urandom(16).hex()
                option["chunk"] = chunk_id
            self._writer.write(packb([tag, blob, option]))
            await io_deadline(self._writer.drain())
            if chunk_id is not None:
                u = Unpacker()
                try:
                    ack = await asyncio.wait_for(
                        self._read_msg(u), timeout=self.ack_timeout
                    )
                except asyncio.TimeoutError:
                    self._writer = None
                    if self._ha is not None and self._node is not None:
                        # TCP-alive-but-hung node: failover like a
                        # connect error, or weight keeps re-picking it
                        self._ha.mark_down(self._node)
                    return FlushResult.RETRY
                if not (isinstance(ack, dict) and ack.get("ack") == chunk_id):
                    self._writer = None
                    if self._ha is not None and self._node is not None:
                        self._ha.mark_down(self._node)
                    return FlushResult.RETRY
        except (ConnectionError, OSError):
            self._writer = None
            if self._ha is not None and self._node is not None:
                self._ha.mark_down(self._node)  # fail over next flush
            return FlushResult.RETRY
        return FlushResult.OK
