"""Fluentd Forward protocol — in_forward server + out_forward client.

Reference: plugins/in_forward (Fluentd protocol server, fw_prot.c) and
plugins/out_forward (forward.c: msgpack over TCP, modes Message /
Forward / PackedForward, ack via the ``chunk`` option, shared-key
HELO/PING/PONG handshake :259-340). The protocol is the reference's
"cluster fabric" (SURVEY §5): agent→aggregator fan-in/out over DCN.

Wire formats accepted by the server:
- Message:        ``[tag, time, record, option?]``
- Forward:        ``[tag, [[time, record], ...], option?]``
- PackedForward:  ``[tag, bin(msgpack stream of [time, record]), option?]``
  (CompressedPackedForward when option.compressed == "gzip")
When ``option.chunk`` is present the server replies ``{"ack": chunk}``
(at-least-once). The client sends PackedForward, optionally gzip'd,
with ``require_ack_response`` waiting for the matching ack.

fbtpu-relay hardening (FAULTS.md "fbtpu-relay") on top of the base
protocol:

- **Effectively-once absorption.** The client's chunk-id is a CONTENT
  digest (core/relay.stable_chunk_id) — stable across reconnect
  resends, backoff interleavings, and post-crash storage replays of
  the same chunk. The server keeps a durable
  :class:`~..core.relay.DedupLedger`: a redelivered id inside the
  retry window is acked WITHOUT re-absorbing, so the aggregator's flux
  sketches (duplicate-sensitive counts/sums) see every edge chunk at
  most once. The ledger record is persisted BEFORE the ack leaves —
  the lost-ack window (``forward.ack_drop``) can only ever produce a
  dedup hit, never a double-absorb. The one deliberate trade: two
  legitimately byte-identical (tag, entries) chunks inside the TTL
  dedup to one absorb — with real per-record timestamps in the stream
  that requires a digest collision in practice, and it is the price of
  ids that survive an edge crash (a random id would not).

- **Wire QoS stamps.** The client copies the flushed chunk's
  tenant/priority (core/plugin.FLUSH_CHUNK) into the option map; the
  server restores them onto the aggregator-side chunk (ChunkPool.stamp)
  and meters the REMOTE tenant's token bucket (qos.admit_stamped), so
  per-tenant quotas and QoS classes hold fleet-wide across the hop.

- **Backpressure instead of blind acks.** A DEFER verdict (tenant
  over quota, or local buffer pressure) delays the ack up to
  ``defer_ack_window``; exhausted, the ack is WITHHELD — the peer's
  ack timeout turns into RETRY + backoff, pausing the stream without
  losing a byte (resends dedup at the ledger).

- **Armored client.** Per-upstream circuit breakers (core/guard.py,
  visible in /api/v1/health), UpstreamHA failover mid-stream, full-
  jitter backoff between attempts; when EVERY upstream refuses (a
  partition), the already-packed entry stream degrades to an fstore
  spool under the tenant's storage quota and replays via the mmap +
  offset-sidecar path on heal, carrying the SAME chunk-id.
"""

from __future__ import annotations

import asyncio
import gzip
import hashlib
import logging
import os
import socket
import time
from types import SimpleNamespace
from typing import Optional

from ..codec.events import encode_event
from ..codec.msgpack import EventTime, OutOfData, Unpacker, packb
from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import FLUSH_CHUNK, FlushResult, InputPlugin, \
    OutputPlugin, registry
from ..core.relay import DedupLedger, ForwardSpool, stable_chunk_id
from ..core.scheduler import backoff_full_jitter
from ..core.upstream import close_quietly
from .. import failpoints as _fp

log = logging.getLogger("flb.forward")

#: wire-stamp hygiene: the tenant name is attacker-adjacent input
#: (any peer with the shared key can send one) — bound it before it
#: becomes a metric label / quota bucket key
_TENANT_MAX_LEN = 128
_PRIORITY_MAX = 7


def _entries_to_events(entries) -> tuple:
    """Forward entries [[time, record], ...] → (encoded V2 buffer, n)."""
    out = bytearray()
    n = 0
    for entry in entries:
        if not isinstance(entry, (list, tuple)) or len(entry) < 2:
            continue
        ts, record = entry[0], entry[1]
        if not isinstance(record, dict):
            continue
        out += encode_event(record, ts)
        n += 1
    return bytes(out), n


def _wire_stamp(option) -> tuple:
    """(tenant, priority) from a forward option map, validated: the
    stamp crosses a trust boundary, so an oversized/typed-wrong value
    degrades to unstamped rather than poisoning quota keys."""
    if not isinstance(option, dict):
        return None, None
    tenant = option.get("tenant")
    if not isinstance(tenant, str) or not tenant \
            or len(tenant) > _TENANT_MAX_LEN:
        tenant = None
    priority = option.get("priority")
    if isinstance(priority, bool) or not isinstance(priority, int):
        priority = None
    else:
        priority = min(max(priority, 0), _PRIORITY_MAX)
    return tenant, priority


@registry.register
class ForwardInput(InputPlugin):
    name = "forward"
    description = "Fluentd Forward protocol server"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=24224),
        ConfigMapEntry("shared_key", "str"),
        ConfigMapEntry("self_hostname", "str", default="fluentbit-tpu"),
        ConfigMapEntry("tag_prefix", "str"),
        ConfigMapEntry("dedup", "bool", default=True,
                       desc="effectively-once absorption: dedup "
                            "redelivered chunk ids against the durable "
                            "ledger before they reach engine/flux state"),
        ConfigMapEntry("dedup_ttl", "time", default="300",
                       desc="retry window: how long an absorbed "
                            "chunk-id stays in the dedup ledger"),
        ConfigMapEntry("defer_ack_window", "time", default="5",
                       desc="max time an ack is delayed while the "
                            "append defers (tenant quota / buffer "
                            "pressure); exhausted, the ack is withheld "
                            "and the peer's own timeout backpressures"),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None
        self._ledger: Optional[DedupLedger] = None
        if self.dedup:
            root = getattr(engine.service, "storage_path", None)
            self._ledger = DedupLedger(root, ttl=self.dedup_ttl)
        # plain ints mirror the exported counters for /api/v1/health
        # (the metrics registry has no read-back API)
        self.n_absorbed = 0
        self.n_deferred_acks = 0
        self.n_withheld_acks = 0
        self.n_shed_remote = 0
        m = engine.metrics
        self._m_dedup = m.counter(
            "fluentbit", "forward", "dedup_hits_total",
            "Redelivered chunk ids absorbed zero times (acked from "
            "the dedup ledger)", ("instance",))
        self._m_absorbed = m.counter(
            "fluentbit", "forward", "absorbed_chunks_total",
            "Forward chunks absorbed into engine state", ("instance",))
        self._m_deferred = m.counter(
            "fluentbit", "forward", "deferred_acks_total",
            "Acks delayed by quota/buffer backpressure", ("instance",))
        self._m_withheld = m.counter(
            "fluentbit", "forward", "withheld_acks_total",
            "Acks withheld after the defer window (peer retries)",
            ("instance",))

    async def start_server(self, engine) -> None:
        async def handle(reader, writer):
            try:
                await self._handle_conn(reader, writer, engine)
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            except Exception:
                log.exception("in_forward connection failed")
            finally:
                close_quietly(writer)

        from ..core.tls import server_context

        server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()

    async def _handle_conn(self, reader, writer, engine) -> None:
        nonce = b""
        if self.shared_key:
            # HELO/PING/PONG handshake (forward.c:259-340 client side)
            nonce = os.urandom(16)
            writer.write(packb(["HELO", {"nonce": nonce, "auth": b"",
                                         "keepalive": True}]))
            await writer.drain()
        u = Unpacker()
        authed = not self.shared_key
        while True:
            data = await reader.read(65536)
            if not data:
                return
            u.feed(data)
            for msg in u:
                if not isinstance(msg, (list, tuple)) or not msg:
                    continue
                if not authed:
                    authed = self._check_ping(msg, nonce, writer)
                    if not authed:
                        return
                    await writer.drain()
                    continue
                await self._dispatch(msg, writer, engine)

    def _check_ping(self, msg, nonce: bytes, writer) -> bool:
        if msg[0] != "PING" or len(msg) < 6:
            return False
        _, hostname, salt, digest = msg[0], msg[1], msg[2], msg[3]
        salt = salt if isinstance(salt, bytes) else str(salt).encode()
        want = hashlib.sha512(
            salt + str(hostname).encode() + nonce + self.shared_key.encode()
        ).hexdigest()
        ok = digest == want
        shared_key_digest = hashlib.sha512(
            salt + self.self_hostname.encode() + nonce
            + self.shared_key.encode()
        ).hexdigest()
        writer.write(packb(["PONG", ok, "" if ok else "shared_key mismatch",
                            self.self_hostname, shared_key_digest]))
        return ok

    async def _dispatch(self, msg, writer, engine) -> None:
        tag = msg[0]
        if not isinstance(tag, str):
            return
        if self.tag_prefix:
            tag = f"{self.tag_prefix}.{tag}"
        option = None
        if isinstance(msg[1], (bytes, memoryview)):
            # PackedForward / CompressedPackedForward
            option = msg[2] if len(msg) > 2 and isinstance(msg[2], dict) else None
            blob = bytes(msg[1])
            if option and option.get("compressed") == "gzip":
                blob = gzip.decompress(blob)
            entries = list(Unpacker(blob))
            buf, n = _entries_to_events(entries)
        elif isinstance(msg[1], (list, tuple)):
            # Forward mode
            option = msg[2] if len(msg) > 2 and isinstance(msg[2], dict) else None
            buf, n = _entries_to_events(msg[1])
        else:
            # Message mode [tag, time, record, option?]
            if len(msg) < 3 or not isinstance(msg[2], dict):
                return
            option = msg[3] if len(msg) > 3 and isinstance(msg[3], dict) else None
            buf, n = _entries_to_events([[msg[1], msg[2]]])
        ack_ref = option.get("chunk") if option else None
        cid = self._chunk_key(ack_ref)
        if n:
            if cid is not None and self._ledger is not None \
                    and self._ledger.seen(cid):
                # redelivery inside the retry window: lost ack,
                # ambiguous-ack resend, or post-crash replay — acked,
                # absorbed zero times
                self._m_dedup.inc(1, (self.instance.display_name,))
            else:
                tenant, priority = _wire_stamp(option)
                absorbed = await self._absorb(engine, tag, buf, n,
                                              tenant, priority, cid)
                if not absorbed:
                    # backpressure: NO ack — the peer's ack timeout
                    # turns into RETRY+backoff, pausing the stream;
                    # the resend dedups if a later pass absorbed it
                    self.n_withheld_acks += 1
                    self._m_withheld.inc(
                        1, (self.instance.display_name,))
                    return
        if ack_ref is not None:
            if _fp.ACTIVE:
                try:
                    # absorb recorded, ack not yet written: the classic
                    # lost-ack window — the edge resends, the ledger
                    # dedups (connection stays up: a dropped ack is not
                    # a dropped link)
                    _fp.fire("forward.ack_drop")
                except _fp.FailpointError:
                    return
            writer.write(packb({"ack": ack_ref}))
            await writer.drain()

    @staticmethod
    def _chunk_key(ack_ref) -> Optional[str]:
        """Ledger key for a wire ``chunk`` option (str or bytes)."""
        if ack_ref is None:
            return None
        if isinstance(ack_ref, (bytes, memoryview)):
            return bytes(ack_ref).decode("latin-1")
        return str(ack_ref)

    async def _absorb(self, engine, tag: str, buf: bytes, n: int,
                      tenant, priority, cid: Optional[str]) -> bool:
        """Absorb one decoded chunk into engine state effectively once.

        Meters the wire-stamped tenant (fleet-wide quota), stamps the
        aggregator-side chunk, and converts DEFER verdicts into delayed
        acks bounded by ``defer_ack_window``. Returns False when the
        window exhausts — the caller withholds the ack entirely.
        """
        ins = self.instance
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.defer_ack_window
        led = self._ledger if cid is not None else None
        deferred = False
        while True:
            if led is not None and led.seen(cid):
                # a concurrent delivery of the same chunk absorbed it
                # while this one slept in the defer loop — just ack
                self._m_dedup.inc(1, (ins.display_name,))
                return True
            rc = None
            if tenant is not None:
                verdict = engine.qos.admit_stamped(tenant, len(buf))
                if verdict == 2:  # SHED: consumed by the tenant's
                    # declared overflow policy — acked, not absorbed
                    # (the edge must not resend policy-shed bytes)
                    self.n_shed_remote += 1
                    return True
                if verdict == 1:  # DEFER
                    rc = -1
            if rc is None:
                stamped = tenant is not None
                if stamped:
                    # the stamp joins the pool key and lands on the
                    # chunk; qos_exempt skips the LOCAL tenant's bucket
                    # (the remote tenant was already metered above) —
                    # input_log_append is synchronous, so no other
                    # dispatch interleaves while these are set
                    ins.pool.stamp = (tenant, priority)
                    ins.qos_exempt = True
                try:
                    rc = engine.input_log_append(ins, tag, buf, n)
                finally:
                    if stamped:
                        ins.pool.stamp = None
                        ins.qos_exempt = False
            if rc >= 0:
                if led is not None:
                    # durable BEFORE the ack leaves: an ack whose
                    # absorb-record died with the process would turn
                    # the peer's next resend into a double-absorb
                    led.record(cid)
                self.n_absorbed += 1
                self._m_absorbed.inc(1, (ins.display_name,))
                return True
            # rc == -1: backpressure (remote-tenant DEFER or local
            # buffer/quota pause) — delay the ack and retry
            if not deferred:
                deferred = True
                self.n_deferred_acks += 1
                self._m_deferred.inc(1, (ins.display_name,))
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False
            if tenant is not None:
                hint = engine.qos.stamped_defer_hint(tenant, len(buf))
            else:
                hint = 0.05
            await asyncio.sleep(min(max(hint, 0.02), 0.25, remaining))

    def health_block(self) -> dict:
        out = {
            "role": "server",
            "absorbed": self.n_absorbed,
            "deferred_acks": self.n_deferred_acks,
            "withheld_acks": self.n_withheld_acks,
            "shed_remote": self.n_shed_remote,
        }
        if self._ledger is not None:
            out["dedup_hits"] = self._ledger.dedup_hits
            out["dedup_entries"] = self._ledger.size()
        return out


@registry.register
class ForwardOutput(OutputPlugin):
    name = "forward"
    description = "Fluentd Forward protocol client"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=24224),
        ConfigMapEntry("shared_key", "str"),
        ConfigMapEntry("self_hostname", "str"),
        ConfigMapEntry("require_ack_response", "bool", default=False),
        ConfigMapEntry("compress", "str"),
        ConfigMapEntry("time_as_integer", "bool", default=False),
        ConfigMapEntry("ack_timeout", "time", default="10"),
        ConfigMapEntry("upstream", "str",
                       desc="upstream HA definition file: weighted "
                            "[NODE] sections with failover"),
        ConfigMapEntry("storage_spool", "str",
                       desc="partition-degrade spool directory: when "
                            "every upstream refuses, packed chunks "
                            "buffer here (under the tenant storage "
                            "quota) and replay on heal via the mmap + "
                            "offset-sidecar path"),
    ]

    def init(self, instance, engine) -> None:
        self._engine = engine
        self._reader = None
        self._writer = None
        # one connection per output instance: concurrent flush coroutines
        # must not interleave writes or steal each other's acks
        self._lock = asyncio.Lock()
        # upstream HA (flb_upstream_ha.c): weighted nodes + failover
        self._ha = None
        self._node = None
        self._cur_breaker = None
        self._cur_target = None
        if self.upstream:
            from ..core.upstream import parse_upstream_file

            self._ha = parse_upstream_file(self.upstream)
        self._spool: Optional[ForwardSpool] = None
        if self.storage_spool:
            self._spool = ForwardSpool(self.storage_spool)
        self._replay_task = None
        self._replay_failures = 0
        self._quota_seq = 0
        # ids already sent once (bounded): a re-entry means the engine
        # is retrying a chunk the wire already saw — a RESEND, counted
        # distinctly from first sends so dashboards can tell loss-driven
        # retries from volume
        self._sent_ids: dict = {}
        self._ack_rtts: list = []
        self.n_acks_waited = 0
        self.n_acks_lost = 0
        self.n_resends = 0
        self.n_spooled = 0
        self.n_replayed = 0
        self._iname = instance.display_name
        m = engine.metrics
        self._m_waited = m.counter(
            "fluentbit", "forward", "acks_waited_total",
            "Forward flushes that waited for a chunk ack", ("instance",))
        self._m_lost = m.counter(
            "fluentbit", "forward", "acks_lost_total",
            "Acks that timed out or mismatched (flush retried)",
            ("instance",))
        self._m_resends = m.counter(
            "fluentbit", "forward", "resends_total",
            "Chunks re-sent with an already-used chunk id", ("instance",))
        self._m_spooled = m.counter(
            "fluentbit", "forward", "spooled_chunks_total",
            "Chunks degraded to the partition spool", ("instance",))
        self._m_replayed = m.counter(
            "fluentbit", "forward", "replayed_chunks_total",
            "Spooled chunks replayed and acked after heal", ("instance",))
        self._m_rtt = m.histogram(
            "fluentbit", "forward", "ack_rtt_seconds",
            "Send → ack round-trip per chunk", ("instance",))
        self._m_breaker = m.gauge(
            "fluentbit", "forward", "breaker_state",
            "Per-upstream breaker state (0 closed / 1 half-open / "
            "2 open)", ("upstream",))

    def exit(self) -> None:
        if self._replay_task is not None:
            try:
                self._replay_task.cancel()
            except RuntimeError:
                pass  # loop already closed at engine teardown
            self._replay_task = None
        if self._writer is not None:
            close_quietly(self._writer)
            self._reader = self._writer = None

    # -- connection -----------------------------------------------------

    def _breaker_for(self, host: str, port: int):
        name = f"forward:{host}:{port}"
        br = self._engine.guard.breaker(name)
        self._m_breaker.set(br.state_code(), (name,))
        return br

    async def _connect(self):
        if self._writer is not None and not self._writer.is_closing():
            return
        from ..core.tls import open_connection

        host, port = self.host, self.port
        self._node = None
        if self._ha is not None:
            self._node = self._ha.pick()
            host, port = self._node.host, self._node.port
        self._cur_target = f"forward:{host}:{port}"
        brk = self._breaker_for(host, port)
        self._cur_breaker = brk
        if not brk.allow():
            # a breaker refusal is not fresh evidence of failure —
            # don't let the error path re-arm the cooldown forever
            self._cur_breaker = None
            raise ConnectionError(
                f"forward: breaker open for {host}:{port}")
        self._reader, self._writer = await open_connection(
            self.instance, host, port, timeout=10
        )
        if self.shared_key:
            await self._handshake()

    def _conn_failed(self) -> None:
        """Error-path bookkeeping: tear the socket, mark the node down
        (HA failover on the next pick), record breaker evidence."""
        if self._writer is not None:
            close_quietly(self._writer)
        self._reader = self._writer = None
        if self._ha is not None and self._node is not None:
            self._ha.mark_down(self._node)
        if self._cur_breaker is not None:
            self._cur_breaker.record_failure()
            self._m_breaker.set(self._cur_breaker.state_code(),
                                (self._cur_target,))
            self._cur_breaker = None

    def _conn_ok(self) -> None:
        if self._ha is not None and self._node is not None:
            self._ha.mark_up(self._node)
        if self._cur_breaker is not None:
            self._cur_breaker.record_ok()
            self._m_breaker.set(self._cur_breaker.state_code(),
                                (self._cur_target,))
            self._cur_breaker = None

    async def _handshake(self) -> None:
        if _fp.ACTIVE:
            # an aggregator that accepts the dial but never finishes
            # auth — the failure shape of a half-up peer
            _fp.fire("forward.handshake")
        u = Unpacker()
        helo = await self._read_msg(u)
        if not (isinstance(helo, list) and helo and helo[0] == "HELO"):
            raise ConnectionError("forward: expected HELO")
        nonce = helo[1].get("nonce", b"")
        nonce = nonce if isinstance(nonce, bytes) else str(nonce).encode()
        hostname = self.self_hostname or socket.gethostname()
        salt = os.urandom(16)
        digest = hashlib.sha512(
            salt + hostname.encode() + nonce + self.shared_key.encode()
        ).hexdigest()
        self._writer.write(packb(["PING", hostname, salt, digest, "", ""]))
        await io_deadline(self._writer.drain(), 10)
        pong = await self._read_msg(u)
        if not (isinstance(pong, list) and len(pong) >= 2 and pong[0] == "PONG"
                and pong[1]):
            raise ConnectionError("forward: handshake rejected")

    async def _read_msg(self, u: Unpacker):
        while True:
            try:
                return u.unpack()
            except OutOfData:
                data = await io_deadline(self._reader.read(65536))
                if not data:
                    raise ConnectionError("forward: peer closed")
                u.feed(data)

    # -- framing --------------------------------------------------------

    def _packed_entries(self, data: bytes) -> tuple:
        """V2 events buffer → (entry stream, count, record END offsets).

        The END offsets feed the spool's record-offset sidecar
        (core/sidecar.py) so a partition-degraded chunk replays without
        re-walking its msgpack payload."""
        from ..codec.events import iter_events

        out = bytearray()
        n = 0
        ends = []
        for ev in iter_events(data):
            ts = ev.timestamp
            if self.time_as_integer:
                ts = int(ev.ts_float)
            elif isinstance(ts, float):
                ts = EventTime.from_float(ts)
            out += packb([ts, ev.body])
            ends.append(len(out))
            n += 1
        return bytes(out), n, ends

    def _frame(self, tag: str, blob: bytes, n: int,
               chunk_id: Optional[str], tenant, priority) -> bytes:
        option = {"size": n, "fluent_signal": 1}
        payload = blob
        if (self.compress or "").lower() == "gzip":
            payload = gzip.compress(blob)
            option["compressed"] = "gzip"
        if tenant is not None:
            option["tenant"] = tenant
        if priority is not None:
            option["priority"] = int(priority)
        if chunk_id is not None:
            option["chunk"] = chunk_id
        return packb([tag, payload, option])

    def _note_sent(self, chunk_id: str) -> bool:
        """True on FIRST send of this id; False marks a resend. LRU-
        bounded — eviction only ever under-counts resends."""
        if chunk_id in self._sent_ids:
            self._sent_ids[chunk_id] = True
            return False
        if len(self._sent_ids) >= 4096:
            for k in list(self._sent_ids)[:256]:
                del self._sent_ids[k]
        self._sent_ids[chunk_id] = True
        return True

    # -- delivery -------------------------------------------------------

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        chunk = FLUSH_CHUNK.get()
        async with self._lock:
            return await self._flush_locked(data, tag, chunk)

    async def _flush_locked(self, data: bytes, tag: str,
                            chunk) -> FlushResult:
        blob, n, ends = self._packed_entries(data)
        if n == 0:
            return FlushResult.OK
        tenant = getattr(chunk, "qos_tenant", None) \
            if chunk is not None else None
        priority = getattr(chunk, "priority", None) \
            if chunk is not None else None
        chunk_id = None
        if self.require_ack_response:
            chunk_id = stable_chunk_id(tag, blob)
            if not self._note_sent(chunk_id):
                self.n_resends += 1
                self._m_resends.inc(1, (self._iname,))
        wire = self._frame(tag, blob, n, chunk_id, tenant, priority)
        budget = max(2, len(self._ha.nodes)) if self._ha is not None \
            else 2
        attempt = 0
        while True:
            attempt += 1
            try:
                await self._connect()
                await self._send_chunk(wire, chunk_id)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self._conn_failed()
                if attempt >= budget:
                    break
                # full-jitter backoff between in-flush failover
                # attempts (core/scheduler.py) — resend-not-duplicate:
                # the retry reuses the SAME chunk_id, so a delivery
                # whose ack was lost dedups at the aggregator
                await asyncio.sleep(
                    backoff_full_jitter(0.05, 0.5, attempt))
                continue
            self._conn_ok()
            return FlushResult.OK
        return self._degrade(tag, blob, ends, n, chunk_id,
                             tenant, priority)

    async def _send_chunk(self, wire: bytes,
                          chunk_id: Optional[str]) -> None:
        if _fp.ACTIVE:
            # connection torn mid-stream, before any frame byte (RST)
            _fp.fire("forward.conn_reset")
            d = _fp.fire("forward.partial_write")
            if d and d[0] == "partial":
                # frame truncated after n bytes, then the link dies:
                # the receiver must discard the torn msgpack tail
                # without absorbing
                self._writer.write(wire[: max(1, int(d[1]))])
                await io_deadline(self._writer.drain())
                raise ConnectionError("forward: injected partial write")
        self._writer.write(wire)
        await io_deadline(self._writer.drain())
        if chunk_id is None:
            return
        u = Unpacker()
        self.n_acks_waited += 1
        self._m_waited.inc(1, (self._iname,))
        t0 = time.monotonic()
        try:
            ack = await asyncio.wait_for(
                self._read_msg(u), timeout=self.ack_timeout
            )
        except asyncio.TimeoutError:
            # TCP-alive-but-hung peer: surfaced as a connection error
            # so the caller fails over exactly like a dial failure
            self.n_acks_lost += 1
            self._m_lost.inc(1, (self._iname,))
            raise
        if not (isinstance(ack, dict) and ack.get("ack") == chunk_id):
            self.n_acks_lost += 1
            self._m_lost.inc(1, (self._iname,))
            raise ConnectionError("forward: ack mismatch")
        rtt = time.monotonic() - t0
        self._ack_rtts.append(rtt)
        if len(self._ack_rtts) > 256:
            del self._ack_rtts[:128]
        self._m_rtt.observe(rtt, (self._iname,))
        if _fp.ACTIVE:
            try:
                _fp.fire("forward.dup_delivery")
            except _fp.FailpointError:
                # ambiguous-ack shape: the SAME frame delivered again
                # after a successful ack — the aggregator's ledger must
                # absorb it zero times (its ack is consumed here so it
                # cannot be mistaken for the next chunk's)
                self.n_resends += 1
                self._m_resends.inc(1, (self._iname,))
                self._writer.write(wire)
                await io_deadline(self._writer.drain())
                await asyncio.wait_for(
                    self._read_msg(u), timeout=self.ack_timeout
                )

    # -- partition degrade + heal replay --------------------------------

    def _degrade(self, tag: str, blob: bytes, ends, n: int,
                 chunk_id: Optional[str], tenant, priority
                 ) -> FlushResult:
        """Every upstream refused within this flush's budget. With a
        spool configured, buffer the packed chunk on disk — gated by
        the tenant's storage quota — and hand delivery to the heal
        replay; otherwise RETRY through the engine's backoff."""
        if self._spool is None:
            return FlushResult.RETRY
        self._quota_seq += 1
        quota_id = f"fwd-spool:{self._iname}:{self._quota_seq}"
        shim = SimpleNamespace(id=quota_id, qos_tenant=tenant,
                               priority=priority)
        verdict = self._engine.qos.admit_storage(None, shim, len(blob))
        if verdict == 2:  # SHED: quota says no disk — the chunk stays
            # in memory and the engine's retry loop keeps ownership
            self._engine.qos.release_storage(shim)
            return FlushResult.RETRY
        self._spool.put(tag, blob, ends, {
            "tag": tag, "chunk": chunk_id, "tenant": tenant,
            "priority": priority, "quota_id": quota_id,
        })
        self.n_spooled += 1
        self._m_spooled.inc(1, (self._iname,))
        self._ensure_replay()
        return FlushResult.OK

    def _ensure_replay(self) -> None:
        if self._replay_task is None or self._replay_task.done():
            self._replay_task = asyncio.get_running_loop().create_task(
                self._replay_spool())

    async def _replay_spool(self) -> None:
        """Heal replay: drain the partition spool in spool order, each
        chunk mmap'd + framed from its sidecars (ForwardSpool.load) and
        sent with its ORIGINAL chunk-id — a replay that races a
        pre-partition delivery dedups at the aggregator's ledger."""
        spool = self._spool
        while True:
            files = spool.pending()
            if not files:
                self._replay_failures = 0
                return
            progressed = False
            for f in files:
                got = spool.load(f)
                if got is None:
                    # unframeable husk (torn payload + no usable
                    # sidecar): nothing can be replayed from it
                    spool.drop(f)
                    continue
                blob, n, meta = got
                cid = meta.get("chunk")
                wire = self._frame(meta.get("tag") or "", blob, n, cid,
                                   meta.get("tenant"),
                                   meta.get("priority"))
                if cid is not None and not self._note_sent(cid):
                    self.n_resends += 1
                    self._m_resends.inc(1, (self._iname,))
                async with self._lock:
                    try:
                        await self._connect()
                        await self._send_chunk(wire, cid)
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError):
                        self._conn_failed()
                        break
                    self._conn_ok()
                qid = meta.get("quota_id")
                if qid:
                    self._engine.qos.release_storage(
                        SimpleNamespace(id=qid))
                spool.drop(f)
                self.n_replayed += 1
                self._m_replayed.inc(1, (self._iname,))
                progressed = True
            if progressed:
                # ANY drained chunk this round counts: a mid-list
                # failure after progress must not inflate the backoff
                self._replay_failures = 0
            else:
                self._replay_failures += 1
                # replay is the heal path — cap the idle gap low so a
                # flaky-but-up upstream still drains the spool quickly
                await asyncio.sleep(backoff_full_jitter(
                    0.1, 1.0, self._replay_failures))

    # -- health ---------------------------------------------------------

    def ack_p50(self) -> Optional[float]:
        if not self._ack_rtts:
            return None
        s = sorted(self._ack_rtts)
        return s[len(s) // 2]

    def health_block(self) -> dict:
        out = {
            "role": "client",
            "acks_waited": self.n_acks_waited,
            "acks_lost": self.n_acks_lost,
            "resends": self.n_resends,
            "spooled": self.n_spooled,
            "replayed": self.n_replayed,
        }
        upstreams = {}
        if self._ha is not None:
            for node in self._ha.nodes:
                upstreams[f"{node.host}:{node.port}"] = \
                    node.breaker.state_name()
        else:
            br = self._engine.guard.breaker(
                f"forward:{self.host}:{self.port}")
            upstreams[f"{self.host}:{self.port}"] = br.state_name()
        out["upstreams"] = upstreams
        if self._spool is not None:
            out["spool_pending"] = len(self._spool.pending())
        p50 = self.ack_p50()
        if p50 is not None:
            out["ack_p50_ms"] = round(p50 * 1000.0, 3)
        return out
