"""Round-3 tail part 3: kafka_rest + nrlogs outputs, blob input,
podman_metrics input.

Reference: plugins/out_kafka_rest (Confluent REST Proxy
/topics/{topic} vnd.kafka.json.v2), plugins/out_nrlogs (New Relic Logs
API with license/api key), plugins/in_blob (glob scan emitting whole
files as blob-type records for blob-capable outputs), and
plugins/in_podman_metrics (container metrics from the podman state +
cgroup v2 accounting files).
"""

from __future__ import annotations

import asyncio
import gzip
import json
import logging
import os
import time
from typing import Dict, List, Optional

from ..codec.chunk import EVENT_TYPE_BLOBS, EVENT_TYPE_METRICS
from ..codec.events import decode_events, encode_event, now_event_time
from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, InputPlugin, OutputPlugin, registry
from .inputs_exporters import _counter, _gauge
from .outputs_http_based import _HttpDeliveryOutput, _dumps

log = logging.getLogger("flb.misc3")


@registry.register
class KafkaRestOutput(_HttpDeliveryOutput):
    """plugins/out_kafka_rest: Confluent REST Proxy producer."""

    name = "kafka_rest"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=8082),
        ConfigMapEntry("topic", "str", default="fluent-bit"),
        ConfigMapEntry("message_key", "str"),
        ConfigMapEntry("time_key", "str", default="@timestamp"),
        ConfigMapEntry("include_tag_key", "bool", default=False),
        ConfigMapEntry("tag_key", "str", default="_flb-key"),
    ]

    def _uri(self) -> str:
        return f"/topics/{self.topic}"

    def _content_type(self) -> str:
        return "application/vnd.kafka.json.v2+json"

    def format(self, data: bytes, tag: str) -> bytes:
        records = []
        for ev in decode_events(data):
            value = dict(ev.body) if isinstance(ev.body, dict) else {}
            value[self.time_key] = ev.ts_float
            if self.include_tag_key:
                value[self.tag_key] = tag
            rec: Dict[str, object] = {"value": value}
            if self.message_key:
                rec["key"] = self.message_key
            records.append(rec)
        return _dumps({"records": records}).encode()


@registry.register
class NrlogsOutput(_HttpDeliveryOutput):
    """plugins/out_nrlogs: New Relic Logs API — gzip JSON batches with
    the license_key (X-License-Key) or api_key (X-Insert-Key)."""

    name = "nrlogs"
    config_map = [
        ConfigMapEntry("host", "str", default="log-api.newrelic.com"),
        ConfigMapEntry("port", "int", default=443),
        ConfigMapEntry("api_key", "str"),
        ConfigMapEntry("license_key", "str"),
        ConfigMapEntry("base_uri", "str", default="/log/v1"),
        ConfigMapEntry("compress", "str", default="gzip"),
    ]

    def init(self, instance, engine) -> None:
        if not (self.api_key or self.license_key):
            raise ValueError("nrlogs: api_key or license_key required")
        if self.api_key and self.license_key:
            raise ValueError(
                "nrlogs: set either api_key or license_key, not both")
        # reference hardcodes FLB_IO_TLS toward the real endpoint
        if "tls" not in instance.properties and \
                "newrelic.com" in (self.host or ""):
            instance.set("tls", "on")

    def _uri(self) -> str:
        return self.base_uri or "/log/v1"

    def _headers(self) -> List[str]:
        out = []
        if self.license_key:
            out.append(f"X-License-Key: {self.license_key}")
        else:
            out.append(f"X-Insert-Key: {self.api_key}")
        if (self.compress or "").lower() == "gzip":
            out.append("Content-Encoding: gzip")
        return out

    def format(self, data: bytes, tag: str) -> bytes:
        logs = []
        for ev in decode_events(data):
            attrs = dict(ev.body) if isinstance(ev.body, dict) else {}
            message = attrs.pop("log", None) or attrs.pop("message", "")
            logs.append({
                "timestamp": int(ev.ts_float * 1000),
                "message": str(message),
                "attributes": {**attrs, "source": tag},
            })
        body = _dumps([{"logs": logs}]).encode()
        if (self.compress or "").lower() == "gzip":
            body = gzip.compress(body)
        return body


@registry.register
class BlobInput(InputPlugin):
    """plugins/in_blob: glob-scan a directory and emit whole files as
    blob-type records (``{"path", "size", "data"}``) once each — the
    blob delivery feed for blob-capable outputs (reference
    src/flb_input_blob.c typed append)."""

    name = "blob"
    description = "emit whole files as blob records"
    collect_interval = 2.0
    config_map = [
        ConfigMapEntry("path", "str"),
        ConfigMapEntry("scan_refresh_interval", "time", default="2"),
        ConfigMapEntry("max_blob_size", "str", default="8M"),
    ]

    def init(self, instance, engine) -> None:
        if not self.path:
            raise ValueError("blob: path is required")
        from ..core.config import parse_size

        self.collect_interval = float(self.scan_refresh_interval or 2)
        self._max = parse_size(self.max_blob_size)
        self._seen: Dict[str, tuple] = {}     # path → emitted signature
        self._pending: Dict[str, tuple] = {}  # path → last scan's sig

    def collect(self, engine) -> None:
        import glob as _glob

        for path in sorted(_glob.glob(self.path)):
            try:
                st = os.stat(path)
            except OSError:
                self._pending.pop(path, None)
                continue
            sig = (st.st_ino, st.st_size, st.st_mtime_ns)
            if self._seen.get(path) == sig:
                self._pending.pop(path, None)
                continue
            # quiescence gate: a file mid-copy changes between scans —
            # emit only once the signature holds across TWO scans, so
            # partial blobs never reach blob-capable outputs
            if self._pending.get(path) != sig:
                self._pending[path] = sig
                continue
            del self._pending[path]
            if st.st_size > self._max:
                log.warning("blob: %s exceeds max_blob_size, skipped",
                            path)
                self._seen[path] = sig
                continue
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            self._seen[path] = sig
            payload = packb({"path": path, "size": len(data),
                             "data": data})
            engine.input_event_append(
                self.instance, self.instance.tag, payload,
                EVENT_TYPE_BLOBS, n_records=1,
            )


@registry.register
class PodmanMetricsInput(InputPlugin):
    """plugins/in_podman_metrics: per-container cpu/memory from the
    podman state file + cgroup v2 accounting."""

    name = "podman_metrics"
    description = "podman container metrics (cgroup v2)"
    config_map = [
        ConfigMapEntry("scrape_interval", "time", default="30"),
        ConfigMapEntry("path.config", "str",
                       default="/var/lib/containers/storage/overlay-"
                               "containers/containers.json"),
        ConfigMapEntry("path.sysfs", "str", default="/sys/fs/cgroup"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.scrape_interval or 30)

    def _containers(self) -> List[dict]:
        with open(self.path_config) as f:
            return json.load(f)

    def _cgroup_stats(self, cid: str) -> Optional[dict]:
        """cgroup v2 layout: .../libpod-<id>.scope/ memory.current +
        cpu.stat (falls back to a flat libpod dir)."""
        bases = [
            os.path.join(self.path_sysfs, "machine.slice",
                         f"libpod-{cid}.scope"),
            os.path.join(self.path_sysfs, "system.slice",
                         f"libpod-{cid}.scope"),
            os.path.join(self.path_sysfs, f"libpod-{cid}.scope"),
        ]
        for base in bases:
            try:
                with open(os.path.join(base, "memory.current")) as f:
                    mem = int(f.read().strip())
                cpu_us = 0
                with open(os.path.join(base, "cpu.stat")) as f:
                    for line in f:
                        if line.startswith("usage_usec"):
                            cpu_us = int(line.split()[1])
                return {"memory": mem, "cpu_us": cpu_us}
            except OSError:
                continue
        return None

    def collect(self, engine) -> None:
        try:
            containers = self._containers()
        except (OSError, ValueError) as e:
            log.debug("podman_metrics: no container state: %s", e)
            return
        mem, cpu = [], []
        for c in containers:
            cid = c.get("id", "")
            names = c.get("names") or [cid[:12]]
            stats = self._cgroup_stats(cid)
            if stats is None:
                continue
            labels = (cid[:12], names[0])
            mem.append((labels, stats["memory"]))
            cpu.append((labels, stats["cpu_us"] / 1e6))
        if not mem:
            return
        keys = ("id", "name")
        entries = [
            _gauge("container_memory_usage_bytes",
                   "Container memory usage.", mem, keys),
            _counter("container_cpu_usage_seconds_total",
                     "Container CPU usage.", cpu, keys),
        ]
        payload = {"meta": {"ts": time.time()}, "metrics": entries}
        engine.input_event_append(
            self.instance, self.instance.tag, packb(payload),
            EVENT_TYPE_METRICS, n_records=len(entries),
        )
