"""in_tail — follow files, emit lines as log records.

Reference: plugins/in_tail (tail.c, tail_file.c line processing,
tail_scan_glob.c path scanning, tail_db.c sqlite offset persistence,
rotation via inode tracking in tail_fs_inotify.c/tail_fs_stat.c).
Watching is event-driven by default (``inotify_watcher on`` — raw
Linux inotify over ctypes: file watches gate which files are read
each tick, directory watches pick up new files immediately instead of
waiting out ``refresh_interval``), with the reference's stat-polling
fallback when inotify is unavailable or disabled:

- ``path``: comma-separated globs, re-scanned every ``refresh_interval``
- per-file offset + inode tracking; rotation = inode change under the
  same name (old fd drained to EOF first), truncation = size < offset
- ``db``: sqlite file persisting (path, inode, offset) across restarts
  (tail_db.c semantics)
- ``parser``: run each line through a named parser (structured fields +
  time); otherwise records are ``{key: line}``
- ``tag``: a ``*`` in the tag expands to the file path with separators
  mapped to dots (the reference's tag expansion)
- ``skip_long_lines``: lines above ``buffer_max_size`` are dropped with
  a warning instead of blocking the file
"""

from __future__ import annotations

import glob as _glob
import logging
import os
from typing import Dict, List

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry, parse_size
from ..core.plugin import InputPlugin, registry

log = logging.getLogger("flb.tail")


class _TailFile:
    __slots__ = ("path", "fd", "inode", "offset", "pending", "skipping",
                 "skip_anchor", "decoder")

    def __init__(self, path: str, inode: int, offset: int = 0):
        self.path = path
        self.fd = None
        self.inode = inode
        self.offset = offset
        self.pending = b""
        self.skipping = False  # discarding an oversized line's remainder
        self.skip_anchor = 0   # the discarded line's start offset
        self.decoder = None    # incremental input-encoding decoder


class _AutoUtf16Decoder:
    """unicode.encoding=auto: sniff the BOM, fall back to UTF-16LE for
    BOM-less streams (Python's own 'utf-16' codec raises on a missing
    BOM even with errors='replace')."""

    def __init__(self, errors: str = "replace"):
        self._errors = errors
        self._inner = None
        self._head = b""

    def decode(self, data: bytes, final: bool = False) -> str:
        import codecs

        if self._inner is None:
            self._head += data
            if len(self._head) < 2 and not final:
                return ""
            if self._head.startswith(codecs.BOM_UTF16_BE):
                name, skip = "utf-16-be", 2
            elif self._head.startswith(codecs.BOM_UTF16_LE):
                name, skip = "utf-16-le", 2
            else:
                name, skip = "utf-16-le", 0
            self._inner = codecs.getincrementaldecoder(name)(self._errors)
            data, self._head = self._head[skip:], b""
        return self._inner.decode(data, final)


class _Inotify:
    """Linux inotify over the raw syscalls (ctypes — inotify needs no
    library): the tail_fs_inotify.c role. Non-blocking; ``events()``
    drains whatever the kernel queued since the last call."""

    IN_MODIFY = 0x00000002
    IN_ATTRIB = 0x00000004
    IN_MOVED_TO = 0x00000080
    IN_CREATE = 0x00000100
    IN_DELETE_SELF = 0x00000400
    IN_MOVE_SELF = 0x00000800
    IN_Q_OVERFLOW = 0x00004000
    IN_IGNORED = 0x00008000

    FILE_MASK = IN_MODIFY | IN_ATTRIB | IN_DELETE_SELF | IN_MOVE_SELF
    DIR_MASK = IN_CREATE | IN_MOVED_TO

    def __init__(self):
        import ctypes

        self._libc = ctypes.CDLL(None, use_errno=True)
        fd = self._libc.inotify_init1(os.O_NONBLOCK)
        if fd < 0:
            raise OSError("inotify_init1 failed")
        self.fd = fd

    def add_watch(self, path: str, mask: int) -> int:
        """→ watch descriptor, or -1 (unwatchable path)."""
        return self._libc.inotify_add_watch(self.fd, path.encode(), mask)

    def rm_watch(self, wd: int) -> None:
        """Free the kernel watch (stale watches on rotated-away inodes
        otherwise accumulate toward fs.inotify.max_user_watches)."""
        self._libc.inotify_rm_watch(self.fd, wd)

    def events(self):
        """Drain pending events → [(wd, mask, name)]."""
        import struct as _struct

        out = []
        while True:
            try:
                data = os.read(self.fd, 65536)
            except (BlockingIOError, OSError):
                break
            off = 0
            while off + 16 <= len(data):
                # NATIVE byte order: the kernel writes host-endian
                wd, mask, _cookie, ln = _struct.unpack_from(
                    "=iIII", data, off)
                name = data[off + 16: off + 16 + ln].split(b"\0", 1)[0]
                out.append((wd, mask, name.decode("utf-8", "replace")))
                off += 16 + ln
        return out

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


@registry.register
class TailInput(InputPlugin):
    name = "tail"
    description = "follow files and emit appended lines"
    collect_interval = 0.25
    config_map = [
        ConfigMapEntry("path", "clist"),
        ConfigMapEntry("inotify_watcher", "bool", default=True,
                       desc="event-driven file watching (Linux inotify; "
                            "off = pure stat polling)"),
        ConfigMapEntry("exclude_path", "clist"),
        ConfigMapEntry("path_key", "str"),
        ConfigMapEntry("key", "str", default="log"),
        ConfigMapEntry("refresh_interval", "time", default="60"),
        ConfigMapEntry("read_from_head", "bool", default=False),
        ConfigMapEntry("parser", "str"),
        ConfigMapEntry("db", "str"),
        ConfigMapEntry("db.sync", "str", default="normal"),
        ConfigMapEntry("buffer_max_size", "str", default="32k"),
        ConfigMapEntry("skip_long_lines", "bool", default=False),
        ConfigMapEntry("rotate_wait", "time", default="5"),
        ConfigMapEntry("multiline.parser", "clist",
                       desc="concatenate lines with a multiline parser"),
        ConfigMapEntry("unicode.encoding", "str",
                       desc="UTF-16LE | UTF-16BE | auto → convert to "
                            "UTF-8 (reference simdutf path)"),
        ConfigMapEntry("generic.encoding", "str",
                       desc="ShiftJIS/UHC/GBK/GB18030/Big5/Win866-1256 "
                            "→ convert to UTF-8 (reference src/unicode)"),
    ]

    # reference src/unicode/ conversion tables ↔ Python codec names
    _ENCODINGS = {
        "utf-16le": "utf-16-le", "utf-16be": "utf-16-be",
        "auto": "auto",  # BOM sniff with LE fallback (see _AutoUtf16)
        "shiftjis": "shift_jis", "shift_jis": "shift_jis",
        "sjis": "shift_jis", "uhc": "cp949", "gbk": "gbk",
        "gb18030": "gb18030", "big5": "big5",
        "win866": "cp866", "win874": "cp874", "win1250": "cp1250",
        "win1251": "cp1251", "win1252": "cp1252", "win1253": "cp1253",
        "win1254": "cp1254", "win1255": "cp1255", "win1256": "cp1256",
    }

    def init(self, instance, engine) -> None:
        if not self.path:
            raise ValueError("tail: path is required")
        self._engine = engine
        self._files: Dict[str, _TailFile] = {}
        self._since_scan = float("inf")  # force a scan on first collect
        self._max_line = parse_size(self.buffer_max_size)
        self._parser = None
        if self.parser:
            self._parser = (engine.parsers if engine else {}).get(self.parser)
            if self._parser is None:
                raise ValueError(f"tail: unknown parser {self.parser!r}")
        self._ml_streams: Dict[str, object] = {}  # path → multiline stream
        if self.multiline_parser and engine is not None:
            from ..multiline import create_stream

            # fail fast on unknown parser names (whole list)
            create_stream(self.multiline_parser, engine.ml_parsers,
                          lambda *_: None)
        # input-encoding conversion (flb_unicode_convert /
        # src/unicode/flb_conv.c): lines decode incrementally per file
        # (multi-byte sequences may straddle read boundaries) and
        # re-emit as UTF-8
        self._codec = None
        enc = (self.unicode_encoding or self.generic_encoding or "")
        if enc:
            codec = self._ENCODINGS.get(enc.strip().lower())
            if codec is None:
                raise ValueError(f"tail: unsupported encoding {enc!r}")
            import codecs as _codecs

            if codec == "auto":
                self._codec = _AutoUtf16Decoder
            else:
                self._codec = _codecs.getincrementaldecoder(codec)
        # inotify (tail_fs_inotify.c role): event-driven readiness —
        # between refresh scans only MODIFIED files are read instead of
        # stat-polling every file every tick. Missing/unsupported →
        # silent stat fallback (the reference does the same off-Linux).
        self._ino = None
        self._wd_file: Dict[int, str] = {}
        self._wd_dir: Dict[int, str] = {}
        self._watched_files: Dict[str, int] = {}
        self._watched_dirs: Dict[str, int] = {}
        if self.inotify_watcher:
            try:
                self._ino = _Inotify()
            except (OSError, AttributeError):
                self._ino = None
        self._db = None
        self._dirty: Dict[str, tuple] = {}
        if self.db:
            from ..core.sqldb import open_db

            # shared-handle wrapper (flb_sqldb): two tail inputs on the
            # same db path share one serialized connection
            self._db = open_db(self.db)
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS in_tail_files ("
                "path TEXT PRIMARY KEY, inode INTEGER, offset INTEGER)"
            )

    def drain(self, engine) -> None:
        """Engine shutdown: emit any pending multiline groups so the
        final record of each stream survives a restart."""
        for path in list(self._ml_streams):
            self._drop_ml_stream(path, engine)

    def exit(self) -> None:
        for tf in self._files.values():
            if tf.fd is not None:
                try:
                    tf.fd.close()
                except OSError:
                    pass
        if self._ino is not None:
            self._ino.close()
        if self._db is not None:
            self._checkpoint()  # final offsets before close
            self._db.close()

    # -- inotify plumbing --

    def _watch_path(self, path: str) -> None:
        if self._ino is None or path in self._watched_files:
            return
        wd = self._ino.add_watch(path, _Inotify.FILE_MASK)
        if wd >= 0:
            self._wd_file[wd] = path
            self._watched_files[path] = wd

    def _watch_dirs(self) -> None:
        """Watch glob parent dirs (static, non-glob dirnames) and the
        dirs of discovered files: CREATE/MOVED_TO there triggers an
        immediate re-scan instead of waiting out refresh_interval."""
        if self._ino is None:
            return
        dirs = set()
        for pat in self.path or []:
            d = os.path.dirname(pat) or "."
            if not _glob.has_magic(d) and os.path.isdir(d):
                dirs.add(d)
        dirs.update(os.path.dirname(p) or "." for p in self._files)
        for d in dirs:
            if d in self._watched_dirs:
                continue
            wd = self._ino.add_watch(d, _Inotify.DIR_MASK)
            if wd >= 0:
                self._wd_dir[wd] = d
                self._watched_dirs[d] = wd

    def _rewatch(self, path: str) -> None:
        """After rotation the old wd follows the RENAMED inode; drop it
        and watch the path's new inode."""
        if self._ino is None:
            return
        wd = self._watched_files.pop(path, None)
        if wd is not None:
            self._wd_file.pop(wd, None)
            self._ino.rm_watch(wd)
        self._watch_path(path)

    def _poll_inotify(self):
        """→ (modified file paths, any-dir-event, overflow). IN_IGNORED
        prunes the wd maps (the kernel freed the watch — deleted dir or
        rotated-away inode), so a recreated directory re-watches instead
        of being shadowed by its dead entry. IN_Q_OVERFLOW means events
        were dropped: the caller must fall back to reading everything."""
        modified = set()
        dir_event = False
        overflow = False
        for wd, mask, _name in self._ino.events():
            if mask & _Inotify.IN_Q_OVERFLOW:
                overflow = True
                continue
            if mask & _Inotify.IN_IGNORED:
                path = self._wd_file.pop(wd, None)
                if path is not None:
                    self._watched_files.pop(path, None)
                d = self._wd_dir.pop(wd, None)
                if d is not None:
                    self._watched_dirs.pop(d, None)
                    dir_event = True  # dir may have been recreated
                continue
            path = self._wd_file.get(wd)
            if path is not None:
                modified.add(path)
            elif wd in self._wd_dir:
                dir_event = True
        return modified, dir_event, overflow

    # -- scanning --

    def _scan(self, initial: bool = False) -> None:
        excluded = set()
        for pat in self.exclude_path or []:
            excluded.update(_glob.glob(pat))
        for pat in self.path:
            for path in sorted(_glob.glob(pat)):
                if path in excluded or path in self._files:
                    continue
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                # read_from_head governs files present at STARTUP;
                # files appearing later are always read from 0 (the
                # reference's tail_scan semantics — skipping to
                # st_size would silently drop their initial content)
                offset = 0 if (self.read_from_head or not initial) \
                    else st.st_size
                inode = st.st_ino
                if self._db is not None:
                    rows = self._db.query(
                        "SELECT inode, offset FROM in_tail_files WHERE path=?",
                        (path,),
                    )
                    row = rows[0] if rows else None
                    if row is not None and row[0] == inode:
                        offset = min(row[1], st.st_size)
                    elif row is not None:
                        offset = 0  # rotated while we were away
                self._files[path] = _TailFile(path, inode, offset)

    def _persist(self, tf: _TailFile) -> None:
        """Mark the offset dirty; the batch at the end of each collect
        pass commits once (not one fsync per tailed file).

        The persisted offset excludes the buffered partial-line fragment
        (tf.pending) so a crash+resume re-reads the fragment whole
        instead of emitting its tail as a corrupt record — the
        reference's resumable-offset semantics (in_tail/tail_db.c,
        flb_tail_file_db_offset subtracts the unconsumed buffer). While
        an oversized line is being discarded the resumable point is that
        line's start — a restart re-reads and re-skips it whole rather
        than emitting its tail as a corrupt record."""
        if self._db is not None:
            if self._codec is not None:
                # converted streams: pending holds UTF-8 bytes whose
                # length differs from the raw file bytes, so the raw
                # read offset is the only exact resume point (a
                # mid-line fragment at crash time is re-read as its
                # tail — documented divergence for converted inputs)
                off = tf.offset
            else:
                off = tf.skip_anchor if tf.skipping \
                    else tf.offset - len(tf.pending)
            self._dirty[tf.path] = (tf.inode, off)

    def _checkpoint(self) -> None:
        if self._db is None or not self._dirty:
            return
        rows = [(path, ino, off)
                for path, (ino, off) in self._dirty.items()]
        self._dirty.clear()
        self._db.executemany(
            "INSERT INTO in_tail_files (path, inode, offset) "
            "VALUES (?, ?, ?) ON CONFLICT(path) DO UPDATE SET "
            "inode=excluded.inode, offset=excluded.offset",
            rows,
        )

    # -- reading --

    def collect(self, engine) -> None:
        initial = self._since_scan == float("inf")
        self._since_scan += self.collect_interval
        scan_due = self._since_scan >= self.refresh_interval
        # stat mode reads every file every tick but still scans only on
        # the refresh cadence (a per-tick re-glob of broad patterns is
        # pure I/O waste); inotify mode reads only modified files
        # between refreshes
        read_all = self._ino is None or scan_due
        targets = None
        if self._ino is not None:
            modified, dir_event, overflow = self._poll_inotify()
            if overflow:
                # the kernel dropped events: trust nothing this tick
                read_all = True
                scan_due = True
            if dir_event and not scan_due:
                # something appeared in a watched dir: re-scan NOW
                before = set(self._files)
                self._scan()
                modified |= set(self._files) - before
            if not read_all:
                targets = [self._files[p] for p in modified
                           if p in self._files]
        if scan_due:
            self._scan(initial=initial)
            self._since_scan = 0.0
        if read_all:
            targets = list(self._files.values())
        if self._ino is not None:
            for path in self._files:
                self._watch_path(path)
            self._watch_dirs()
        for tf in targets or ():
            self._read_file(tf, engine)
        self._checkpoint()
        # flush multiline groups that waited past their flush window
        for path, (st, groups) in list(self._ml_streams.items()):
            if st.timed_out():
                st.flush()
                if groups:
                    done = list(groups)
                    groups.clear()
                    self._emit_texts(path, self._tag_for(path), done, engine)

    def _read_file(self, tf: _TailFile, engine) -> None:
        try:
            st = os.stat(tf.path)
        except OSError:
            st = None  # deleted; drain the open fd below then drop
        if tf.fd is None:
            try:
                tf.fd = open(tf.path, "rb")
                tf.fd.seek(tf.offset)
            except OSError:
                self._files.pop(tf.path, None)
                self._drop_ml_stream(tf.path, engine)
                return
        # truncation: file shrank under the same inode
        if st is not None and st.st_ino == tf.inode and st.st_size < tf.offset:
            tf.fd.seek(0)
            tf.offset = 0
            tf.pending = b""
            tf.skipping = False
            tf.skip_anchor = 0
            tf.decoder = None
        self._drain_fd(tf, engine)
        # rotation: name now points at a different inode — finish the old
        # file (drained above), then follow the new one from offset 0
        if st is not None and st.st_ino != tf.inode:
            try:
                tf.fd.close()
            except OSError:
                pass
            tf.fd = None
            tf.inode = st.st_ino
            tf.offset = 0
            tf.pending = b""
            tf.skipping = False
            tf.skip_anchor = 0
            tf.decoder = None
            self._rewatch(tf.path)  # old wd follows the renamed inode
            self._drain_fd(tf, engine, reopen=True)
        elif st is None:
            try:
                tf.fd.close()
            except OSError:
                pass
            self._files.pop(tf.path, None)
            wd = self._watched_files.pop(tf.path, None)
            if wd is not None:
                self._wd_file.pop(wd, None)
                if self._ino is not None:
                    self._ino.rm_watch(wd)
            self._drop_ml_stream(tf.path, engine)
        self._persist(tf)

    def _drain_fd(self, tf: _TailFile, engine, reopen: bool = False) -> None:
        if reopen:
            try:
                tf.fd = open(tf.path, "rb")
            except OSError:
                return
        while True:
            chunk = tf.fd.read(65536)
            if not chunk:
                break
            tf.offset += len(chunk)
            if self._codec is not None:
                # convert to UTF-8 before line splitting (the reference
                # converts the read buffer ahead of process_content);
                # the incremental decoder carries split multi-byte
                # sequences across reads
                if tf.decoder is None:
                    tf.decoder = self._codec(errors="replace")
                chunk = tf.decoder.decode(chunk).encode("utf-8")
                if not chunk:
                    continue
            if tf.skipping:
                # discard up to (and including) the oversized line's
                # terminating newline so its tail never becomes a record
                nl = chunk.find(b"\n")
                if nl < 0:
                    continue
                chunk = chunk[nl + 1 :]
                tf.skipping = False
            data = tf.pending + chunk
            lines = data.split(b"\n")
            tf.pending = lines.pop()
            if len(tf.pending) > self._max_line:
                if self.skip_long_lines:
                    log.warning("tail: dropping long line in %s", tf.path)
                    tf.skip_anchor = tf.offset - len(tf.pending)
                    tf.pending = b""
                    tf.skipping = True
                # else: keep buffering (reference blocks the file; we
                # keep growing the pending buffer)
            if lines:
                self._emit_lines(tf, lines, engine)

    def _ml_stream(self, path: str):
        from ..multiline import create_stream

        entry = self._ml_streams.get(path)
        if entry is None:
            groups: List[str] = []
            st = create_stream(
                self.multiline_parser,
                self._engine.ml_parsers if self._engine else None,
                lambda text, ctx: groups.append(text),
            )
            entry = (st, groups)
            self._ml_streams[path] = entry
        return entry

    def _drop_ml_stream(self, path: str, engine) -> None:
        """Flush + forget the multiline stream of a dropped file."""
        entry = self._ml_streams.pop(path, None)
        if entry is None:
            return
        st, groups = entry
        st.flush()
        if groups:
            self._emit_texts(path, self._tag_for(path), list(groups), engine)

    def _emit_lines(self, tf: _TailFile, lines: List[bytes], engine) -> None:
        tag = self._tag_for(tf.path)
        decoded = [raw.rstrip(b"\r").decode("utf-8", "replace")
                   for raw in lines]
        if self.multiline_parser:
            # concatenate through the per-file multiline stream first
            st, groups = self._ml_stream(tf.path)
            docker = self.multiline_parser[0].lower() == "docker"
            for line in decoded:
                if docker:
                    # docker mode consumes the JSON 'log' content: the
                    # 16K-split fragments are closed by a trailing \n IN
                    # THE CONTENT, which line splitting cannot see
                    import json as _json

                    try:
                        obj = _json.loads(line) if line else None
                    except ValueError:
                        obj = None
                    content = obj.get("log") if isinstance(obj, dict) else None
                    if isinstance(content, str):
                        st.feed(content)
                    elif line:
                        st.flush()
                        groups.append(line)
                else:
                    # blank lines must reach the state machine — they
                    # close groups whose rules do not match empty
                    st.feed(line)
            decoded = list(groups)
            groups.clear()
        self._emit_texts(tf.path, tag, decoded, engine)

    def _emit_texts(self, path: str, tag: str, texts: List[str],
                    engine) -> None:
        out = bytearray()
        n = 0
        for line in texts:
            if not line:
                continue
            if len(line) > self._max_line and self.skip_long_lines:
                log.warning("tail: dropping long line in %s", path)
                continue
            body = None
            ts = None
            if self._parser is not None:
                got = self._parser.do(line)
                if got is not None:
                    body, ts = got
            if body is None:
                body = {self.key or "log": line}
            if self.path_key:
                body[self.path_key] = path
            out += encode_event(
                body, ts if ts not in (None, 0) else now_event_time()
            )
            n += 1
        if n:
            engine.input_log_append(self.instance, tag, bytes(out), n)

    def _tag_for(self, path: str) -> str:
        tag = self.instance.tag or "tail.0"
        if "*" in tag:
            expanded = path.lstrip("/").replace("/", ".")
            tag = tag.replace("*", expanded)
        return tag
