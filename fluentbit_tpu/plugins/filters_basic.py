"""Basic filters: record_modifier, modify, nest, expect, stdout, throttle.

Reference: plugins/filter_record_modifier, filter_modify (1669 LoC
conditional set/remove/rename/copy), filter_nest (nest/lift),
filter_expect (test assertions), filter_stdout, filter_throttle
(sliding-window rate limit).
"""

from __future__ import annotations

import json
import re
import sys
import time
from typing import Any, List, Optional

from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor


def _modified(events):
    for ev in events:
        ev.raw = None  # body changed: raw span invalid
    return (FilterResult.MODIFIED, events)


@registry.register
class RecordModifierFilter(FilterPlugin):
    """plugins/filter_record_modifier: append fixed fields, allowlist or
    removelist keys."""

    name = "record_modifier"
    config_map = [
        ConfigMapEntry("record", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("remove_key", "str", multiple=True),
        ConfigMapEntry("allowlist_key", "str", multiple=True),
        ConfigMapEntry("whitelist_key", "str", multiple=True),
        ConfigMapEntry("uuid_key", "str"),
    ]

    def init(self, instance, engine) -> None:
        self._appends = [(k, v) for k, v in (r for r in self.record)]
        self._allow = set(self.allowlist_key) | set(self.whitelist_key)
        self._remove = set(self.remove_key)

    def filter(self, events, tag, engine):
        if not (self._appends or self._allow or self._remove or self.uuid_key):
            return (FilterResult.NOTOUCH, events)
        import uuid
        for ev in events:
            if self._allow:
                ev.body = {k: v for k, v in ev.body.items() if k in self._allow}
            for k in self._remove:
                ev.body.pop(k, None)
            for k, v in self._appends:
                ev.body[k] = v
            if self.uuid_key:
                ev.body[self.uuid_key] = str(uuid.uuid4())
        return _modified(events)


@registry.register
class ModifyFilter(FilterPlugin):
    """plugins/filter_modify: conditional set/add/remove/rename/copy rules.

    Conditions (subset mirroring modify.c): Key_exists, Key_does_not_exist,
    Key_value_equals, Key_value_does_not_equal, Key_value_matches,
    No_key_matches, Key_value_does_not_match.
    """

    name = "modify"
    config_map = [
        ConfigMapEntry("set", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("add", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("remove", "str", multiple=True),
        ConfigMapEntry("remove_wildcard", "str", multiple=True),
        ConfigMapEntry("remove_regex", "str", multiple=True),
        ConfigMapEntry("rename", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("hard_rename", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("copy", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("hard_copy", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("condition", "slist", multiple=True),
    ]

    def init(self, instance, engine) -> None:
        self._conditions = []
        for cond in self.condition:
            parts = cond if isinstance(cond, list) else str(cond).split()
            if not parts:
                continue
            self._conditions.append((parts[0].lower(), parts[1] if len(parts) > 1 else None,
                                     parts[2] if len(parts) > 2 else None))

    def _conds_met(self, body: dict) -> bool:
        for op, a, b in self._conditions:
            if op == "key_exists":
                if a not in body:
                    return False
            elif op == "key_does_not_exist":
                if a in body:
                    return False
            elif op == "key_value_equals":
                if str(body.get(a)) != b:
                    return False
            elif op == "key_value_does_not_equal":
                if str(body.get(a)) == b:
                    return False
            elif op == "key_value_matches":
                v = body.get(a)
                if v is None or not re.search(b, str(v)):
                    return False
            elif op == "key_value_does_not_match":
                v = body.get(a)
                if v is not None and re.search(b, str(v)):
                    return False
            elif op == "no_key_matches":
                if any(re.search(a, k) for k in body):
                    return False
        return True

    def filter(self, events, tag, engine):
        any_touched = False
        for ev in events:
            body = ev.body
            if self._conditions and not self._conds_met(body):
                continue
            touched = False
            for k, v in self.set:
                body[k] = v
                touched = True
            for k, v in self.add:
                if k not in body:
                    body[k] = v
                    touched = True
            for k in self.remove:
                if k in body:
                    del body[k]
                    touched = True
            for pat in self.remove_wildcard:
                prefix = pat.rstrip("*")
                for k in [k for k in body if k.startswith(prefix)]:
                    del body[k]
                    touched = True
            for pat in self.remove_regex:
                for k in [k for k in body if re.search(pat, k)]:
                    del body[k]
                    touched = True
            for old, new in self.rename:
                if old in body and new not in body:
                    body[new] = body.pop(old)
                    touched = True
            for old, new in self.hard_rename:
                if old in body:
                    body[new] = body.pop(old)
                    touched = True
            for old, new in self.copy:
                if old in body and new not in body:
                    body[new] = body[old]
                    touched = True
            for old, new in self.hard_copy:
                if old in body:
                    body[new] = body[old]
                    touched = True
            if touched:
                ev.raw = None
                any_touched = True
        if not any_touched:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, events)


@registry.register
class NestFilter(FilterPlugin):
    """plugins/filter_nest: nest keys under a map, or lift a nested map."""

    name = "nest"
    config_map = [
        ConfigMapEntry("operation", "str", default="nest"),
        ConfigMapEntry("wildcard", "str", multiple=True),
        ConfigMapEntry("nest_under", "str"),
        ConfigMapEntry("nested_under", "str"),
        ConfigMapEntry("add_prefix", "str", default=""),
        ConfigMapEntry("remove_prefix", "str", default=""),
    ]

    def filter(self, events, tag, engine):
        op = (self.operation or "nest").lower()
        any_touched = False
        for ev in events:
            body = ev.body
            touched = False
            if op == "nest" and self.nest_under:
                moved = {}
                for pat in self.wildcard:
                    prefix = pat.rstrip("*")
                    exact = "*" not in pat
                    for k in list(body):
                        if (k == pat) if exact else k.startswith(prefix):
                            moved[self.add_prefix + k] = body.pop(k)
                if moved:
                    target = body.setdefault(self.nest_under, {})
                    if isinstance(target, dict):
                        target.update(moved)
                    else:
                        body[self.nest_under] = moved
                    touched = True
            elif op == "lift" and self.nested_under:
                nested = body.pop(self.nested_under, None)
                if isinstance(nested, dict):
                    for k, v in nested.items():
                        nk = self.add_prefix + k
                        if self.remove_prefix and nk.startswith(self.remove_prefix):
                            nk = nk[len(self.remove_prefix):]
                        body[nk] = v
                    touched = True
                elif nested is not None:
                    body[self.nested_under] = nested
            if touched:
                ev.raw = None
                any_touched = True
        return (FilterResult.MODIFIED, events) if any_touched else (FilterResult.NOTOUCH, events)


@registry.register
class ExpectFilter(FilterPlugin):
    """plugins/filter_expect: inline assertions on record shape; action
    'warn', 'exit' (stop engine) or 'result_key' marks the record."""

    name = "expect"
    config_map = [
        ConfigMapEntry("key_exists", "str", multiple=True),
        ConfigMapEntry("key_not_exists", "str", multiple=True),
        ConfigMapEntry("key_val_is_null", "str", multiple=True),
        ConfigMapEntry("key_val_is_not_null", "str", multiple=True),
        ConfigMapEntry("key_val_eq", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("action", "str", default="warn"),
    ]

    def init(self, instance, engine) -> None:
        self.failures = 0
        # compile accessors once — this runs on the synchronous ingest path
        self._exists = [(k, RecordAccessor(k)) for k in self.key_exists]
        self._not_exists = [(k, RecordAccessor(k)) for k in self.key_not_exists]
        self._is_null = [(k, RecordAccessor(k)) for k in self.key_val_is_null]
        self._not_null = [(k, RecordAccessor(k)) for k in self.key_val_is_not_null]
        self._eq = [(k, RecordAccessor(k), v) for k, v in self.key_val_eq]

    def _check(self, body: dict) -> Optional[str]:
        for k, ra in self._exists:
            if ra.get(body, _MISSING) is _MISSING:
                return f"key_exists {k}"
        for k, ra in self._not_exists:
            if ra.get(body, _MISSING) is not _MISSING:
                return f"key_not_exists {k}"
        for k, ra in self._is_null:
            if ra.get(body, _MISSING) is not None:
                return f"key_val_is_null {k}"
        for k, ra in self._not_null:
            v = ra.get(body, _MISSING)
            if v is None or v is _MISSING:
                return f"key_val_is_not_null {k}"
        for k, ra, expected in self._eq:
            if str(ra.get(body)) != expected:
                return f"key_val_eq {k}"
        return None

    def filter(self, events, tag, engine):
        for ev in events:
            fail = self._check(ev.body)
            if fail is not None:
                self.failures += 1
                if self.action == "exit":
                    engine.request_stop()
                elif self.action == "result_key":
                    ev.body["matched"] = False
                    ev.raw = None
        return (FilterResult.NOTOUCH, events)


_MISSING = object()


@registry.register
class StdoutFilter(FilterPlugin):
    """plugins/filter_stdout: print records as they pass (debug)."""

    name = "stdout"

    def filter(self, events, tag, engine):
        for ev in events:
            sys.stdout.write(f"[{ev.ts_float:.9f}, {json.dumps(ev.body, default=str)}]\n")
        return (FilterResult.NOTOUCH, events)


@registry.register
class ThrottleFilter(FilterPlugin):
    """plugins/filter_throttle: sliding-window rate limit (records/window)."""

    name = "throttle"
    config_map = [
        ConfigMapEntry("rate", "double", default=1.0),
        ConfigMapEntry("window", "int", default=5),
        ConfigMapEntry("interval", "time", default="1s"),
        ConfigMapEntry("print_status", "bool", default="false"),
    ]

    def init(self, instance, engine) -> None:
        self._window: List[int] = [0] * max(1, int(self.window))
        self._slot_start = time.monotonic()
        self._idx = 0

    def _advance(self) -> None:
        now = time.monotonic()
        while now - self._slot_start >= self.interval:
            self._slot_start += self.interval
            self._idx = (self._idx + 1) % len(self._window)
            self._window[self._idx] = 0

    def filter(self, events, tag, engine):
        self._advance()
        limit = self.rate * len(self._window)
        kept = []
        dropped = False
        for ev in events:
            if sum(self._window) < limit:
                self._window[self._idx] += 1
                kept.append(ev)
            else:
                dropped = True
        if not dropped:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, kept)
