"""Remaining breadth: out_nats, in_kmsg, in_docker_events.

Reference: plugins/out_nats (NATS text protocol CONNECT/PUB),
plugins/in_kmsg (/dev/kmsg kernel log), plugins/in_docker_events
(docker daemon /events over the unix socket). The sampling processor
moved to processor_sampling.py (probabilistic + tail modes).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Dict

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import (
    FlushResult,
    InputPlugin,
    OutputPlugin,
    registry,
)
from ..core.upstream import close_quietly
from .outputs_basic import format_json_lines

log = logging.getLogger("flb.misc")


@registry.register
class NatsOutput(OutputPlugin):
    """plugins/out_nats: publish each record as JSON on subject=tag
    (the NATS text protocol: INFO/CONNECT/PUB/+OK)."""

    name = "nats"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=4222),
    ]

    def init(self, instance, engine) -> None:
        self._reader = None
        self._writer = None
        # one shared connection: concurrent flushes must not race the
        # INFO/CONNECT handshake or interleave writes
        self._lock = asyncio.Lock()

    async def _connect(self):
        if self._writer is not None and not self._writer.is_closing():
            return
        from ..core.tls import open_connection

        self._reader, self._writer = await open_connection(
            self.instance, self.host, self.port, timeout=10
        )
        info = await asyncio.wait_for(self._reader.readline(), 10)
        if not info.startswith(b"INFO"):
            raise ConnectionError("nats: expected INFO")
        self._writer.write(b'CONNECT {"verbose":false}\r\n')
        await io_deadline(self._writer.drain(), 10)

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        async with self._lock:
            return await self._flush_locked(data, tag)

    async def _service_incoming(self) -> None:
        """Answer server PINGs and surface -ERR (a real broker drops
        the connection after unanswered pings)."""
        while True:
            try:
                line = await asyncio.wait_for(self._reader.readline(),
                                              0.005)
            except asyncio.TimeoutError:
                return
            if not line:
                raise ConnectionError("nats: peer closed")
            if line.startswith(b"PING"):
                self._writer.write(b"PONG\r\n")
                await io_deadline(self._writer.drain(), 10)
            elif line.startswith(b"-ERR"):
                raise ConnectionError(
                    f"nats: {line.decode(errors='replace').strip()}"
                )

    async def _flush_locked(self, data: bytes, tag: str) -> FlushResult:
        try:
            await self._connect()
            await self._service_incoming()
            for line in format_json_lines(data).splitlines():
                payload = line.encode()
                self._writer.write(
                    f"PUB {tag} {len(payload)}\r\n".encode()
                    + payload + b"\r\n"
                )
            await asyncio.wait_for(self._writer.drain(), 30)
            await self._service_incoming()  # catch -ERR for this publish
        except (OSError, ConnectionError, asyncio.TimeoutError):
            if self._writer is not None:
                close_quietly(self._writer)
            self._writer = None
            return FlushResult.RETRY
        return FlushResult.OK


@registry.register
class KmsgInput(InputPlugin):
    """plugins/in_kmsg: the kernel ring buffer via /dev/kmsg
    ('<pri>,<seq>,<usec_since_boot>,<flags>;message')."""

    name = "kmsg"
    collect_interval = 0.25
    config_map = [
        ConfigMapEntry("file", "str", default="/dev/kmsg"),
    ]

    def init(self, instance, engine) -> None:
        self._fd = None
        try:
            self._fd = os.open(self.file, os.O_RDONLY | os.O_NONBLOCK)
            # boot epoch so usec-since-boot maps to wall time
            with open("/proc/uptime") as f:
                uptime = float(f.read().split()[0])
            self._boot = time.time() - uptime
        except OSError as e:
            raise RuntimeError(f"kmsg: cannot open {self.file}: {e}")

    def exit(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass

    def collect(self, engine) -> None:
        out = bytearray()
        n = 0
        while True:
            try:
                raw = os.read(self._fd, 8192)
            except BlockingIOError:
                break
            except OSError:
                break
            if not raw:
                break
            line = raw.decode("utf-8", "replace").rstrip("\n")
            head, _, msg = line.partition(";")
            parts = head.split(",")
            body: Dict[str, object] = {"msg": msg}
            try:
                prival = int(parts[0])
                body["priority"] = prival & 7
                body["facility"] = prival >> 3
                body["sequence"] = int(parts[1])
                ts = self._boot + int(parts[2]) / 1e6
            except (ValueError, IndexError):
                ts = None
            out += encode_event(
                body, ts if ts else now_event_time()
            )
            n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)


@registry.register
class DockerEventsInput(InputPlugin):
    """plugins/in_docker_events: stream the daemon's /events JSON feed
    over the unix socket."""

    name = "docker_events"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("unix_path", "str", default="/var/run/docker.sock"),
        ConfigMapEntry("reconnect.retry_interval", "time", default="1"),
    ]

    async def start_server(self, engine) -> None:
        while True:
            try:
                await self._stream(engine)
            except (OSError, asyncio.IncompleteReadError) as e:
                log.debug("docker_events: %s; reconnecting", e)
            await asyncio.sleep(self.reconnect_retry_interval or 1)

    async def _stream(self, engine) -> None:
        reader, writer = await asyncio.open_unix_connection(self.unix_path)
        try:
            writer.write(b"GET /events HTTP/1.1\r\nHost: docker\r\n\r\n")
            await writer.drain()
            # response headers
            chunked = False
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                if line.lower().startswith(b"transfer-encoding:") and \
                        b"chunked" in line.lower():
                    chunked = True
            # de-chunk EXACTLY (an event JSON may span chunk
            # boundaries), then split records on newlines
            pending = b""
            while True:
                if chunked:
                    size_line = await reader.readline()
                    if not size_line:
                        break
                    try:
                        size = int(size_line.strip() or b"0", 16)
                    except ValueError:
                        break
                    if size == 0:
                        break
                    data = await reader.readexactly(size)
                    await reader.readline()  # trailing CRLF
                else:
                    data = await reader.read(65536)
                    if not data:
                        break
                pending += data
                *lines, pending = pending.split(b"\n")
                for raw in lines:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        body = json.loads(raw)
                    except ValueError:
                        continue
                    if isinstance(body, dict):
                        engine.input_log_append(
                            self.instance, self.instance.tag,
                            encode_event(body, now_event_time()), 1,
                        )
        finally:
            writer.close()
