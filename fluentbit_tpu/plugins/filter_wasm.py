"""filter_wasm on the from-scratch WASM interpreter (wasmrt).

Reference: plugins/filter_wasm/filter_wasm.c + src/wasm/flb_wasm.c
(WAMR embed). Per record (JSON event format, filter_wasm.c:131-183):
the body is JSON-encoded, tag + record are copied into guest linear
memory (wasm_runtime_module_dup_data), and

    function_name(tag_ptr, tag_len, sec, nsec, rec_ptr, rec_len) -> i32

returns a guest pointer to a NUL-terminated JSON string that REPLACES
the record body (original timestamp kept). NULL (0) or an empty string
skips (drops) the record; invalid returned JSON leaves the whole chunk
untouched (the reference's on_error path). Modules must be
self-contained — WASI imports are rejected at load (in_exec_wasi stays
gated for the same reason).
"""

from __future__ import annotations

import json
import logging
from typing import List

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..wasmrt import Module, Trap, WasmError

log = logging.getLogger("flb.wasm")


@registry.register
class WasmFilter(FilterPlugin):
    name = "wasm"
    description = "WASM filter (from-scratch MVP interpreter)"
    config_map = [
        ConfigMapEntry("wasm_path", "str"),
        ConfigMapEntry("function_name", "str"),
        ConfigMapEntry("event_format", "str", default="json"),
        ConfigMapEntry("accessible_paths", "clist"),  # accepted; no WASI
        ConfigMapEntry("wasm_heap_size", "size", default="8192k"),
        ConfigMapEntry("wasm_stack_size", "size", default="8192k"),
    ]

    def init(self, instance, engine) -> None:
        if not self.wasm_path:
            raise ValueError("wasm filter requires 'wasm_path'")
        if not self.function_name:
            raise ValueError("wasm filter requires 'function_name'")
        if (self.event_format or "json").lower() != "json":
            raise ValueError(
                "wasm filter: only event_format json is supported")
        with open(self.wasm_path, "rb") as f:
            self._binary = f.read()
        try:
            self._module = self._instantiate()
        except Exception as e:
            # the unvalidated decoder can surface raw Python errors
            # (IndexError/struct.error) on corrupt files — all of them
            # mean the same thing at init: unloadable module
            raise ValueError(f"wasm filter: cannot load "
                             f"{self.wasm_path}: {e}")
        exp = self._module.exports.get(self.function_name)
        if exp is None or exp[0] != "func":
            raise ValueError(
                f"wasm filter: function {self.function_name!r} not "
                f"exported by {self.wasm_path}")

    def _instantiate(self) -> Module:
        """wasm_heap_size caps linear memory (grow + dup_data);
        wasm_stack_size maps onto the call-depth bound (each frame is
        roughly a few KB of guest shadow stack in toolchain output)."""
        depth = max(16, min(4096, int(self.wasm_stack_size or 0) // 4096
                            or 256))
        return Module(self._binary,
                      max_memory_bytes=int(self.wasm_heap_size or 0),
                      max_call_depth=depth)

    def filter(self, events: list, tag: str, engine) -> tuple:
        mod = self._module
        out: List[LogEvent] = []
        modified = False
        tag_b = tag.encode("utf-8")
        for ev in events:
            if ev.is_group_start() or ev.is_group_end():
                out.append(ev)
                continue
            rec_json = json.dumps(ev.body, separators=(",", ":"),
                                  default=str).encode("utf-8")
            ts = ev.ts_float
            sec = int(ts)
            nsec = int((ts - sec) * 1e9)
            mod.reset_heap()
            tag_ptr = mod.dup_data(tag_b)
            rec_ptr = mod.dup_data(rec_json)
            try:
                rets = mod.call(self.function_name,
                                [tag_ptr, len(tag_b), sec, nsec,
                                 rec_ptr, len(rec_json)])
                ptr = rets[0] if rets else 0
                if not ptr:
                    modified = True  # NULL → skip record
                    continue
                ret_str = mod.read_cstr(ptr)
            except Exception as e:
                # wasmrt does no load-time validation, so a hostile
                # module can surface raw Python errors (IndexError on
                # stack underflow, struct.error) alongside Trap —
                # every per-call failure keeps the record and
                # reinstantiates (guest state may be mid-mutation:
                # shadow stack pointer, heap metadata)
                log.error("wasm function %r failed: %s",
                          self.function_name, e)
                out.append(ev)
                try:
                    self._module = mod = self._instantiate()
                except (WasmError, Trap):
                    log.exception("wasm reinstantiate failed")
                continue
            if not ret_str:
                modified = True  # empty string → skip record
                continue
            try:
                new_body = json.loads(ret_str.decode("utf-8"))
                if not isinstance(new_body, dict):
                    raise ValueError("not a JSON object")
            except (ValueError, UnicodeDecodeError):
                # reference on_error: broken returned JSON leaves the
                # whole chunk untouched
                log.error("wasm function %r returned invalid JSON",
                          self.function_name)
                return (FilterResult.NOTOUCH, events)
            if new_body == ev.body:
                out.append(ev)
                continue
            out.append(LogEvent(ev.timestamp, new_body, ev.metadata,
                                raw=None))
            modified = True
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)
