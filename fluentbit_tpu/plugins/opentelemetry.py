"""OpenTelemetry — OTLP/HTTP (JSON encoding) input + output, 4 signals.

Reference: plugins/in_opentelemetry (OTLP server for
logs/metrics/traces/profiles, opentelemetry.c) and
plugins/out_opentelemetry (4640 LoC OTLP export for all four signals).
This build speaks the OTLP/HTTP **JSON** encoding (a standard encoding
per the OpenTelemetry protocol spec; the protobuf binary encoding is
gated — no protoc-generated schemas are vendored) on:

- ``/v1/logs``    → log events (V2 records with otlp metadata)
- ``/v1/traces``  → typed traces payloads (codec.telemetry, the
  ctraces-equivalent model), event_type "traces" chunks
- ``/v1/metrics`` → the internal cmetrics-like snapshot, event_type
  "metrics" chunks (every metrics-capable output consumes them)
- ``/v1/development/profiles`` (and ``/v1/profiles``) → typed profiles
  payloads (cprofiles equivalent), event_type "profiles" chunks

Mapping for logs: each logRecord → one pipeline record; resource +
scope attributes land in the event metadata under ``otlp`` so group
identity survives round trips; ``timeUnixNano`` ↔ the event timestamp;
``body.stringValue`` → ``{"message": ...}``, kvlist bodies merge as
fields.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Optional

from ..codec.chunk import (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS,
                           EVENT_TYPE_PROFILES, EVENT_TYPE_TRACES)
from ..codec.events import encode_event, iter_events
from ..codec.msgpack import EventTime, packb
from ..codec.telemetry import (any_value_to_py, decode_otlp_metrics,
                               decode_otlp_profiles, decode_otlp_traces,
                               dict_to_kvlist, encode_otlp_metrics,
                               encode_otlp_profiles, encode_otlp_traces,
                               is_profiles_payload, is_traces_payload,
                               kvlist_to_dict, py_to_any_value)
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from ..core.upstream import close_quietly

log = logging.getLogger("flb.otlp")


SEVERITIES = {1: "trace", 5: "debug", 9: "info", 13: "warn", 17: "error",
              21: "fatal"}


def decode_otlp_logs(payload: dict):
    """OTLP ExportLogsServiceRequest JSON → [(ts_ns, body, metadata)]."""
    out = []
    for rl in payload.get("resourceLogs", []):
        resource_attrs = kvlist_to_dict(
            (rl.get("resource") or {}).get("attributes", []))
        for sl in rl.get("scopeLogs", []):
            scope = sl.get("scope") or {}
            for rec in sl.get("logRecords", []):
                ts = int(rec.get("timeUnixNano")
                         or rec.get("observedTimeUnixNano") or 0)
                body: Dict[str, Any] = {}
                b = any_value_to_py(rec.get("body", {}))
                if isinstance(b, dict):
                    body.update(b)
                elif b is not None:
                    body["message"] = b
                attrs = kvlist_to_dict(rec.get("attributes", []))
                body.update(attrs)
                sev_num = rec.get("severityNumber")
                sev_text = rec.get("severityText")
                if (sev_text or sev_num) and "severity" not in body:
                    body["severity"] = sev_text or SEVERITIES.get(
                        int(sev_num), str(sev_num))
                meta = {"otlp": {"resource": resource_attrs,
                                 "scope": {"name": scope.get("name", ""),
                                           "version": scope.get("version",
                                                                "")}}}
                out.append((ts, body, meta))
    return out


def encode_otlp_logs(events, tag: str) -> dict:
    """Pipeline events → ExportLogsServiceRequest JSON (one resource per
    distinct otlp.resource metadata, default tagged resource)."""
    groups: Dict[str, dict] = {}
    for ev in events:
        meta = ev.metadata or {}
        otlp = meta.get("otlp", {}) if isinstance(meta, dict) else {}
        resource = otlp.get("resource") or {"service.name": tag}
        scope = otlp.get("scope") or {"name": "fluentbit_tpu"}
        key = json.dumps([resource, scope], sort_keys=True, default=str)
        g = groups.setdefault(key, {"resource": resource, "scope": scope,
                                    "records": []})
        body = dict(ev.body) if isinstance(ev.body, dict) else {}
        sev_text = str(body.pop("severity", ""))
        ts = ev.timestamp
        if isinstance(ts, EventTime):
            # exact: float64 loses ~100ns at current epochs
            ns = ts.sec * 10**9 + ts.nsec
        else:
            ns = int(ev.ts_float * 1e9)
        rec = {
            "timeUnixNano": str(ns),
            "body": {"kvlistValue": {"values": dict_to_kvlist(body)}}
            if len(body) != 1 or "message" not in body
            else {"stringValue": str(body["message"])},
            "attributes": [],
        }
        if sev_text:
            rec["severityText"] = sev_text
        g["records"].append(rec)
    return {"resourceLogs": [
        {"resource": {"attributes": dict_to_kvlist(g["resource"])},
         "scopeLogs": [{"scope": g["scope"],
                        "logRecords": g["records"]}]}
        for g in groups.values()
    ]}


_SIGNAL_PATHS = {
    "/v1/logs": "logs",
    "/v1/traces": "traces",
    "/v1/metrics": "metrics",
    "/v1/profiles": "profiles",
    "/v1/development/profiles": "profiles",
}


@registry.register
class OpentelemetryInput(InputPlugin):
    name = "opentelemetry"
    description = "OTLP/HTTP server (logs/traces/metrics/profiles, JSON)"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=4318),
        ConfigMapEntry("tag_from_uri", "bool", default=True),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    def _ingest(self, engine, signal: str, payload: dict, tag: str) -> None:
        if signal == "logs":
            records = decode_otlp_logs(payload)
            from ..codec.events import now_event_time

            buf = bytearray()
            for ts_ns, rec_body, meta in records:
                # no timestamp on the record → receive time
                # (the reference server's fallback)
                ts = (EventTime(ts_ns // 10**9, ts_ns % 10**9)
                      if ts_ns else now_event_time())
                buf += encode_event(rec_body, ts, meta)
            if records:
                engine.input_log_append(
                    self.instance, tag, bytes(buf), len(records)
                )
            return
        if signal == "metrics":
            snaps, n = decode_otlp_metrics(payload)
            if n:
                engine.input_event_append(
                    self.instance, tag,
                    b"".join(packb(s) for s in snaps),
                    EVENT_TYPE_METRICS, n_records=n,
                )
            return
        if signal == "traces":
            typed, n = decode_otlp_traces(payload)
            etype = EVENT_TYPE_TRACES
        else:
            typed, n = decode_otlp_profiles(payload)
            etype = EVENT_TYPE_PROFILES
        if n:
            engine.input_event_append(
                self.instance, tag, packb(typed), etype, n_records=n
            )

    async def start_server(self, engine) -> None:
        import asyncio

        from ..core.tls import server_context
        from .net_http import http_response, read_http_request

        async def handle(reader, writer):
            try:
                while True:
                    req = await read_http_request(reader)
                    if req is None:
                        break
                    method, uri, headers, body = req
                    path = uri.split("?")[0]
                    signal = _SIGNAL_PATHS.get(path)
                    if method != "POST" or signal is None:
                        code = 404 if method == "POST" else 400
                        writer.write(http_response(code, b"{}",
                                                   "application/json"))
                        await writer.drain()
                        continue
                    try:
                        payload = json.loads(body)
                        tag = path.strip("/").replace("/", ".") \
                            if self.tag_from_uri else self.instance.tag
                        self._ingest(engine, signal, payload, tag)
                    except Exception:
                        # any structurally invalid payload is the
                        # client's error: answer 400, keep the conn
                        log.debug("otlp %s decode failed", signal,
                                  exc_info=True)
                        writer.write(http_response(400, b"{}",
                                                   "application/json"))
                        await writer.drain()
                        continue
                    writer.write(http_response(
                        200, b'{"partialSuccess":{}}', "application/json"))
                    await writer.drain()
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                close_quietly(writer)

        server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()


from .outputs_http_based import _HttpDeliveryOutput


@registry.register
class OpentelemetryOutput(_HttpDeliveryOutput):
    """OTLP/HTTP exporter for all four signals. Shares the HTTP
    delivery base (TLS, timeouts, 408/429 retry classification — OTLP
    backpressure must RETRY, not drop). Each chunk carries one
    event_type, and the payload shape is self-describing (typed traces
    payloads hold resourceSpans, metrics snapshots hold a metrics list,
    profiles hold resourceProfiles), so the flush routes to the
    matching signal URI — the reference's per-signal endpoints
    (out_opentelemetry logs/metrics/traces/profiles_uri options)."""

    name = "opentelemetry"
    description = "OTLP/HTTP exporter (logs/traces/metrics/profiles)"
    event_types = (EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES,
                   EVENT_TYPE_PROFILES)
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=4318),
        ConfigMapEntry("logs_uri", "str", default="/v1/logs"),
        ConfigMapEntry("traces_uri", "str", default="/v1/traces"),
        ConfigMapEntry("metrics_uri", "str", default="/v1/metrics"),
        ConfigMapEntry("profiles_uri", "str",
                       default="/v1/development/profiles"),
        ConfigMapEntry("header", "slist", multiple=True, slist_max_split=1),
    ]

    def _uri(self) -> str:
        return self.logs_uri or "/v1/logs"

    def _headers(self) -> List[str]:
        out = []
        for pair in self.header or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                out.append(f"{parts[0]}: {parts[1]}")
        return out

    def _classify(self, data: bytes):
        """(signal, payload list) from a chunk's self-describing bytes."""
        from ..codec.msgpack import Unpacker
        from ..core.metrics import is_metrics_payload

        try:
            objs = list(Unpacker(data))
        except Exception:
            return "logs", None
        if objs and all(is_traces_payload(o) for o in objs):
            return "traces", objs
        if objs and all(is_profiles_payload(o) for o in objs):
            return "profiles", objs
        if objs and all(is_metrics_payload(o) for o in objs):
            return "metrics", objs
        return "logs", None

    def _encode(self, signal: str, objs, data: bytes, tag: str) -> bytes:
        if signal == "traces":
            body = encode_otlp_traces(objs)
        elif signal == "profiles":
            body = encode_otlp_profiles(objs)
        elif signal == "metrics":
            body = encode_otlp_metrics(objs)
        else:
            body = encode_otlp_logs(list(iter_events(data)), tag)
        return json.dumps(body, separators=(",", ":"),
                          default=str).encode()

    def format(self, data: bytes, tag: str) -> bytes:
        """Wire payload for the chunk (test-formatter unit)."""
        signal, objs = self._classify(data)
        return self._encode(signal, objs, data, tag)

    async def flush(self, data: bytes, tag: str, engine):
        # classify ONCE; the unpacked objects feed the encoder directly
        signal, objs = self._classify(data)
        uri = {
            "traces": self.traces_uri,
            "metrics": self.metrics_uri,
            "profiles": self.profiles_uri,
        }.get(signal, self.logs_uri)
        return await self._post(self._encode(signal, objs, data, tag),
                                uri=uri)
