"""OpenTelemetry logs — OTLP/HTTP (JSON encoding) input + output.

Reference: plugins/in_opentelemetry (OTLP server for
logs/metrics/traces, opentelemetry.c) and plugins/out_opentelemetry
(4640 LoC OTLP export). This build speaks the OTLP/HTTP **JSON**
encoding for the logs signal on ``/v1/logs`` (the protobuf binary
encoding and the metrics/traces signals are gated — no protoc-generated
schemas are vendored; OTLP/JSON is a standard encoding per the
OpenTelemetry protocol spec).

Mapping: each logRecord → one pipeline record; resource + scope
attributes land in the event metadata under ``otlp`` so group identity
survives round trips; ``timeUnixNano`` ↔ the event timestamp;
``body.stringValue`` → ``{"message": ...}``, kvlist bodies merge as
fields.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Optional

from ..codec.events import encode_event, iter_events
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry

log = logging.getLogger("flb.otlp")


# ---------------------------------------------------------- value mapping

def any_value_to_py(v: dict) -> Any:
    if not isinstance(v, dict):
        return v
    if "stringValue" in v:
        return v["stringValue"]
    if "intValue" in v:
        return int(v["intValue"])
    if "doubleValue" in v:
        return float(v["doubleValue"])
    if "boolValue" in v:
        return bool(v["boolValue"])
    if "arrayValue" in v:
        return [any_value_to_py(x)
                for x in v["arrayValue"].get("values", [])]
    if "kvlistValue" in v:
        return kvlist_to_dict(v["kvlistValue"].get("values", []))
    if "bytesValue" in v:
        import base64

        try:
            return base64.b64decode(v["bytesValue"])
        except (ValueError, TypeError):
            return v["bytesValue"]
    return None


def kvlist_to_dict(kvs: List[dict]) -> Dict[str, Any]:
    return {kv.get("key", ""): any_value_to_py(kv.get("value", {}))
            for kv in kvs}


def py_to_any_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, (list, tuple)):
        return {"arrayValue": {"values": [py_to_any_value(x) for x in v]}}
    if isinstance(v, dict):
        return {"kvlistValue": {"values": dict_to_kvlist(v)}}
    if isinstance(v, bytes):
        import base64

        # proto3 JSON mapping: bytes fields are base64 text
        return {"bytesValue": base64.b64encode(v).decode("ascii")}
    return {"stringValue": str(v)}


def dict_to_kvlist(d: Dict[str, Any]) -> List[dict]:
    return [{"key": k, "value": py_to_any_value(v)} for k, v in d.items()]


SEVERITIES = {1: "trace", 5: "debug", 9: "info", 13: "warn", 17: "error",
              21: "fatal"}


def decode_otlp_logs(payload: dict):
    """OTLP ExportLogsServiceRequest JSON → [(ts_ns, body, metadata)]."""
    out = []
    for rl in payload.get("resourceLogs", []):
        resource_attrs = kvlist_to_dict(
            (rl.get("resource") or {}).get("attributes", []))
        for sl in rl.get("scopeLogs", []):
            scope = sl.get("scope") or {}
            for rec in sl.get("logRecords", []):
                ts = int(rec.get("timeUnixNano")
                         or rec.get("observedTimeUnixNano") or 0)
                body: Dict[str, Any] = {}
                b = any_value_to_py(rec.get("body", {}))
                if isinstance(b, dict):
                    body.update(b)
                elif b is not None:
                    body["message"] = b
                attrs = kvlist_to_dict(rec.get("attributes", []))
                body.update(attrs)
                sev_num = rec.get("severityNumber")
                sev_text = rec.get("severityText")
                if (sev_text or sev_num) and "severity" not in body:
                    body["severity"] = sev_text or SEVERITIES.get(
                        int(sev_num), str(sev_num))
                meta = {"otlp": {"resource": resource_attrs,
                                 "scope": {"name": scope.get("name", ""),
                                           "version": scope.get("version",
                                                                "")}}}
                out.append((ts, body, meta))
    return out


def encode_otlp_logs(events, tag: str) -> dict:
    """Pipeline events → ExportLogsServiceRequest JSON (one resource per
    distinct otlp.resource metadata, default tagged resource)."""
    groups: Dict[str, dict] = {}
    for ev in events:
        meta = ev.metadata or {}
        otlp = meta.get("otlp", {}) if isinstance(meta, dict) else {}
        resource = otlp.get("resource") or {"service.name": tag}
        scope = otlp.get("scope") or {"name": "fluentbit_tpu"}
        key = json.dumps([resource, scope], sort_keys=True, default=str)
        g = groups.setdefault(key, {"resource": resource, "scope": scope,
                                    "records": []})
        body = dict(ev.body) if isinstance(ev.body, dict) else {}
        sev_text = str(body.pop("severity", ""))
        ts = ev.timestamp
        if isinstance(ts, EventTime):
            # exact: float64 loses ~100ns at current epochs
            ns = ts.sec * 10**9 + ts.nsec
        else:
            ns = int(ev.ts_float * 1e9)
        rec = {
            "timeUnixNano": str(ns),
            "body": {"kvlistValue": {"values": dict_to_kvlist(body)}}
            if len(body) != 1 or "message" not in body
            else {"stringValue": str(body["message"])},
            "attributes": [],
        }
        if sev_text:
            rec["severityText"] = sev_text
        g["records"].append(rec)
    return {"resourceLogs": [
        {"resource": {"attributes": dict_to_kvlist(g["resource"])},
         "scopeLogs": [{"scope": g["scope"],
                        "logRecords": g["records"]}]}
        for g in groups.values()
    ]}


@registry.register
class OpentelemetryInput(InputPlugin):
    name = "opentelemetry"
    description = "OTLP/HTTP server (logs signal, JSON encoding)"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=4318),
        ConfigMapEntry("tag_from_uri", "bool", default=True),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    async def start_server(self, engine) -> None:
        import asyncio

        from ..core.tls import server_context
        from .net_http import http_response, read_http_request

        async def handle(reader, writer):
            try:
                while True:
                    req = await read_http_request(reader)
                    if req is None:
                        break
                    method, uri, headers, body = req
                    path = uri.split("?")[0]
                    if method != "POST" or path not in ("/v1/logs",):
                        code = 404 if method == "POST" else 400
                        writer.write(http_response(code, b"{}",
                                                   "application/json"))
                        await writer.drain()
                        continue
                    try:
                        payload = json.loads(body)
                        records = decode_otlp_logs(payload)
                    except Exception:
                        # any structurally invalid payload is the
                        # client's error: answer 400, keep the conn
                        writer.write(http_response(400, b"{}",
                                                   "application/json"))
                        await writer.drain()
                        continue
                    tag = "v1.logs" if self.tag_from_uri else \
                        self.instance.tag
                    from ..codec.events import now_event_time

                    buf = bytearray()
                    for ts_ns, rec_body, meta in records:
                        # no timestamp on the record → receive time
                        # (the reference server's fallback)
                        ts = (EventTime(ts_ns // 10**9, ts_ns % 10**9)
                              if ts_ns else now_event_time())
                        buf += encode_event(rec_body, ts, meta)
                    if records:
                        engine.input_log_append(
                            self.instance, tag, bytes(buf), len(records)
                        )
                    writer.write(http_response(
                        200, b'{"partialSuccess":{}}', "application/json"))
                    await writer.drain()
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        import asyncio

        server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()


from .outputs_http_based import _HttpDeliveryOutput


@registry.register
class OpentelemetryOutput(_HttpDeliveryOutput):
    """Shares the HTTP delivery base (TLS, timeouts, 408/429 retry
    classification — OTLP backpressure must RETRY, not drop)."""

    name = "opentelemetry"
    description = "OTLP/HTTP exporter (logs signal, JSON encoding)"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=4318),
        ConfigMapEntry("logs_uri", "str", default="/v1/logs"),
        ConfigMapEntry("header", "slist", multiple=True, slist_max_split=1),
    ]

    def _uri(self) -> str:
        return self.logs_uri or "/v1/logs"

    def _headers(self) -> List[str]:
        out = []
        for pair in self.header or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                out.append(f"{parts[0]}: {parts[1]}")
        return out

    def format(self, data: bytes, tag: str) -> bytes:
        return json.dumps(
            encode_otlp_logs(list(iter_events(data)), tag),
            separators=(",", ":"), default=str,
        ).encode()
