"""Webhook-style outputs: slack, logdna, td.

Reference: plugins/out_slack (incoming-webhook POST of record text),
plugins/out_logdna (LogDNA ingest API), plugins/out_td (Treasure Data
import API). All ride the shared HTTP delivery base.
"""

from __future__ import annotations

import time
from typing import List

from ..codec.events import decode_events
from ..core.config import ConfigMapEntry
from ..core.plugin import registry
from ..utils import base64_encode, compress
from .outputs_http_based import _HttpDeliveryOutput, _dumps


@registry.register
class SlackOutput(_HttpDeliveryOutput):
    """plugins/out_slack: records rendered into a webhook text block."""

    name = "slack"
    config_map = [
        ConfigMapEntry("webhook", "str",
                       desc="full webhook URL or path (host/port split "
                            "for plain-http test endpoints)"),
        ConfigMapEntry("host", "str", default="hooks.slack.com"),
        ConfigMapEntry("port", "int", default=443),
    ]

    def init(self, instance, engine) -> None:
        if not self.webhook:
            raise ValueError("slack: webhook is required")
        if self.webhook.startswith(("http://", "https://")):
            from urllib.parse import urlsplit

            u = urlsplit(self.webhook)  # handles IPv6 + schemes
            self.host = u.hostname or self.host
            self.port = u.port or (80 if u.scheme == "http" else 443)
            self._path = u.path or "/"
            if u.scheme == "https" and "tls" not in instance.properties:
                # https implies TLS: never post the secret webhook path
                # in cleartext (core.tls reads the instance property)
                instance.set("tls", "on")
        else:
            self._path = self.webhook if self.webhook.startswith("/") \
                else "/" + self.webhook

    def _uri(self) -> str:
        return self._path

    def format(self, data: bytes, tag: str) -> bytes:
        lines = [
            f"[{ev.ts_float:.6f}] {tag}: {_dumps(ev.body)}"
            for ev in decode_events(data)
        ]
        return _dumps({"text": "```" + "\n".join(lines) + "```"}).encode()


@registry.register
class LogdnaOutput(_HttpDeliveryOutput):
    """plugins/out_logdna: ingest API (lines array + basic-auth key)."""

    name = "logdna"
    config_map = [
        ConfigMapEntry("api_key", "str"),
        ConfigMapEntry("logdna_host", "str", default="logs.logdna.com"),
        ConfigMapEntry("logdna_port", "int", default=443),
        ConfigMapEntry("hostname", "str", default="fluentbit-tpu"),
        ConfigMapEntry("app", "str"),
        ConfigMapEntry("host", "str"),
        ConfigMapEntry("port", "int", default=0),
    ]

    def init(self, instance, engine) -> None:
        if not self.api_key:
            raise ValueError("logdna: api_key is required")
        # host/port fall back to the logdna_* pair (test endpoints
        # override host/port directly)
        if not self.host:
            self.host = self.logdna_host
        if not self.port:
            self.port = self.logdna_port
        # TLS on by default: the reference hardcodes FLB_IO_TLS for
        # out_logdna (never send the api_key in cleartext); explicit
        # `tls off` remains available for local stub endpoints
        if "tls" not in instance.properties:
            instance.set("tls", "on")

    def _uri(self) -> str:
        from ..utils import uri_encode

        host = uri_encode(self.hostname or "", safe="")
        return f"/logs/ingest?hostname={host}&now={int(time.time())}"

    def _headers(self) -> List[str]:
        cred = base64_encode(f"{self.api_key}:".encode()).decode()
        return [f"Authorization: Basic {cred}"]

    def format(self, data: bytes, tag: str) -> bytes:
        lines = []
        for ev in decode_events(data):
            body = ev.body if isinstance(ev.body, dict) else {}
            line = body.get("log") or body.get("message") or _dumps(body)
            entry = {
                "timestamp": int(ev.ts_float * 1000),
                "line": str(line),
                "app": self.app or tag,
                "meta": body,
            }
            lines.append(entry)
        return _dumps({"lines": lines}).encode()


@registry.register
class TdOutput(_HttpDeliveryOutput):
    """plugins/out_td: Treasure Data import — msgpack.gz payloads with
    the TD1 apikey header."""

    name = "td"
    config_map = [
        ConfigMapEntry("api", "str", desc="TD API key"),
        ConfigMapEntry("database", "str"),
        ConfigMapEntry("table", "str"),
        ConfigMapEntry("host", "str", default="api.treasuredata.com"),
        ConfigMapEntry("port", "int", default=443),
    ]

    def init(self, instance, engine) -> None:
        if not (self.api and self.database and self.table):
            raise ValueError("td: api + database + table are required")
        # reference out_td hardcodes FLB_IO_TLS; same default here
        if "tls" not in instance.properties:
            instance.set("tls", "on")

    def _uri(self) -> str:
        return (f"/v3/table/import/{self.database}/{self.table}"
                f"/msgpack.gz")

    def _content_type(self) -> str:
        return "application/gzip"

    def _headers(self) -> List[str]:
        return [f"Authorization: TD1 {self.api}"]

    def format(self, data: bytes, tag: str) -> bytes:
        from ..codec.msgpack import packb

        out = bytearray()
        for ev in decode_events(data):
            body = dict(ev.body) if isinstance(ev.body, dict) else {}
            body["time"] = int(ev.ts_float)
            out += packb(body)
        return compress("gzip", bytes(out))
