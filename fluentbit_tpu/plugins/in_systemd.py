"""in_systemd — journald reader input.

Reference: plugins/in_systemd/systemd.c (sd_journal-based). The same
surface is served by the from-scratch journal-file reader
(`utils/journal.py`): every new journal entry becomes a record whose
body maps field names to values (systemd.c:340-380), with
``lowercase`` and ``strip_underscores`` transforms (systemd.c:160-200),
``systemd_filter`` KEY=value matches combined by ``systemd_filter_type``
and/or, a dynamic tag — ``*`` in the tag replaced by the entry's
``_SYSTEMD_UNIT`` (tag_compose, systemd.c:34-66) — and the record
timestamp from ``_SOURCE_REALTIME_TIMESTAMP`` when present, else the
entry's own realtime. ``read_from_tail`` skips the backlog;
``db`` persists per-file consumed positions (the sd_journal cursor
role) so a restart resumes where it stopped.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Tuple

from ..codec.events import EventTime, encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry
from ..utils.journal import (
    JournalError,
    JournalFile,
    peek_header,
    scan_journal_dir,
)

log = logging.getLogger("flb.systemd")

_DEFAULT_PATHS = ("/var/log/journal", "/run/log/journal")


@registry.register
class SystemdInput(InputPlugin):
    name = "systemd"
    description = "Systemd (Journal) reader"
    collect_interval = 1.0
    threaded_capable = True
    config_map = [
        ConfigMapEntry("path", "str"),
        ConfigMapEntry("max_fields", "int", default=8000),
        ConfigMapEntry("max_entries", "int", default=5000),
        ConfigMapEntry("systemd_filter_type", "str", default="and"),
        ConfigMapEntry("systemd_filter", "slist", multiple=True,
                       slist_max_split=0),
        ConfigMapEntry("read_from_tail", "bool", default=False),
        ConfigMapEntry("lowercase", "bool", default=False),
        ConfigMapEntry("strip_underscores", "bool", default=False),
        ConfigMapEntry("db", "str"),
    ]

    def init(self, instance, engine) -> None:
        self._ins = instance
        if self.path:
            if not os.path.isdir(self.path):
                raise ValueError(
                    f"systemd: journal path {self.path!r} not found")
            self._root = self.path
        else:
            self._root = next(
                (p for p in _DEFAULT_PATHS if os.path.isdir(p)), None)
            if self._root is None:
                raise ValueError(
                    "systemd: no journal directory found (set 'path')")
        ftype = (self.systemd_filter_type or "and").lower()
        if ftype not in ("and", "or"):
            raise ValueError(
                "systemd: systemd_filter_type must be 'and' or 'or'")
        self._filter_and = ftype == "and"
        self._filters: List[Tuple[str, str]] = []
        for f in self.systemd_filter or []:
            text = f if isinstance(f, str) else " ".join(f)
            key, sep, value = text.partition("=")
            if not sep:
                raise ValueError(f"systemd: bad systemd_filter {f!r}")
            self._filters.append((key.strip(), value.strip()))
        self._dynamic_tag = "*" in (instance.tag or "")
        # consumed-entry counts keyed by the file's file_id, which
        # survives journald rotation renames (a fresh file after
        # rotation gets a new id and starts at 0; the archived file
        # keeps its id and its cursor) — the sd_journal cursor role
        self._pos: Dict[str, int] = {}
        if self.db and os.path.isfile(self.db):
            try:
                with open(self.db, "r", encoding="utf-8") as f:
                    self._pos = {str(k): int(v)
                                 for k, v in json.load(f).items()}
            except (OSError, ValueError):
                log.warning("systemd: could not load db %s", self.db)
        elif self.read_from_tail:
            for path in scan_journal_dir(self._root):
                try:
                    file_id, n_entries = peek_header(path)
                    self._pos[file_id] = n_entries
                except (JournalError, OSError) as e:
                    log.warning("systemd: %s", e)

    def _persist(self) -> None:
        if not self.db:
            return
        try:
            tmp = self.db + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._pos, f)
            os.replace(tmp, self.db)
        except OSError:
            log.warning("systemd: could not persist db %s", self.db)

    def _matches(self, fields: Dict[str, str]) -> bool:
        if not self._filters:
            return True
        hits = (fields.get(k) == v for k, v in self._filters)
        return all(hits) if self._filter_and else any(hits)

    def _tag_for(self, fields: Dict[str, str]) -> str:
        tag = self._ins.tag or "systemd"
        if not self._dynamic_tag:
            return tag
        unit = fields.get("_SYSTEMD_UNIT", "unknown")
        return tag.replace("*", unit)

    def collect(self, engine) -> None:
        budget = max(1, int(self.max_entries))
        changed = False
        for path in scan_journal_dir(self._root):
            if budget <= 0:
                break
            try:
                # header-only freshness probe: idle files (the usual
                # archived majority) never load their body
                file_id, n_entries = peek_header(path)
                skip = self._pos.get(file_id, 0)
                if n_entries <= skip:
                    continue
                jf = JournalFile(path)
            except (JournalError, OSError) as e:
                log.debug("systemd: %s: %s", path, e)
                continue
            groups: Dict[str, List[bytes]] = {}
            consumed = 0
            # per-entry containment: one corrupt object must neither
            # discard already-decoded entries nor stall the cursor —
            # the bad entry is skipped (logged) and reading goes on
            it = jf.entries(skip=skip, max_entries=budget)
            while True:
                try:
                    entry = next(it)
                except StopIteration:
                    break
                except JournalError as e:
                    log.warning("systemd: %s (skipping one entry)", e)
                    consumed += 1
                    break  # the iterator's position is unrecoverable
                consumed += 1
                fields: Dict[str, str] = {}
                for k, v in entry.fields[:int(self.max_fields)]:
                    fields[k] = v
                if not self._matches(fields):
                    continue
                tag = self._tag_for(fields)
                ts = self._timestamp(entry, fields)
                body = self._transform(fields)
                groups.setdefault(tag, []).append(
                    encode_event(body, ts))
            budget -= consumed
            self._pos[jf.file_id] = skip + consumed
            changed = True
            jf.close()  # release the mmap before the next tick
            for tag, bufs in groups.items():
                engine.input_log_append(
                    self._ins, tag, b"".join(bufs), len(bufs))
        if changed:
            self._persist()

    @staticmethod
    def _timestamp(entry, fields: Dict[str, str]):
        src = fields.get("_SOURCE_REALTIME_TIMESTAMP")
        usec = None
        if src and src.isdigit():
            usec = int(src)
        elif entry.realtime:
            usec = entry.realtime
        if usec is None:
            return now_event_time()
        return EventTime(usec // 1_000_000, (usec % 1_000_000) * 1000)

    def _transform(self, fields: Dict[str, str]) -> Dict[str, str]:
        out = {}
        for k, v in fields.items():
            if self.strip_underscores:
                k = k.lstrip("_")
            if self.lowercase:
                k = k.lower()
            out[k] = v
        return out
