"""filter_nightfall — Nightfall DLP scan + redaction.

Reference: plugins/filter_nightfall/nightfall.c +
nightfall_api.c. Per chunk (sampled at ``sampling_rate``), every
record's scannable fields (strings and non-bool integers, map keys
included) are extracted in stack-DFS order (nightfall_api.c
extract_map_fields/extract_array_fields), joined as ``"<key> <value>"``
when a scalar value sits under a string key (the key gives the scanner
context), and POSTed to the ``/v3/scan`` endpoint
(https://docs.nightfall.ai/reference/scanpayloadv3) as
``{"payload": [...], "policyUUIDs": [policy_id]}`` with Bearer auth.
The response carries one findings array per payload item; each
finding's ``location.byteRange`` is redacted with ``*`` in the same
DFS walk (nightfall.c maybe_redact_field — integers with findings are
replaced whole by ``"******"``, string ranges are star-filled with the
key-context offset subtracted, nightfall.c:374-384).

Divergences from the reference, both deliberate:
- ``api_url`` is configurable (default ``https://api.nightfall.ai``)
  so the filter is testable against a local stub; the reference
  hardcodes the host (nightfall.h FLB_FILTER_NIGHTFALL_API_URL).
- the reference packs its integer replacement string with a trailing
  NUL (``msgpack_pack_str_with_body(.., "******", 7)``); we emit the
  six asterisks only.
"""

from __future__ import annotations

import json
import logging
import random
from typing import List, Optional, Tuple
from urllib.parse import urlsplit

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry

log = logging.getLogger("flb.nightfall")


def _scannable(v) -> bool:
    # MSGPACK_OBJECT_STR / POSITIVE_INTEGER / NEGATIVE_INTEGER only;
    # bools and floats pass through unscanned (nightfall_api.c:232-247)
    return isinstance(v, str) or (isinstance(v, int)
                                  and not isinstance(v, bool))


def _extract(obj, out: List[Tuple[object, Optional[str]]]) -> None:
    """DFS-collect scannable fields as (value, key_context) in the
    exact order the reference's explicit stack walk visits them."""
    if isinstance(obj, (list, tuple)):
        for item in obj:
            if isinstance(item, (dict, list, tuple)):
                _extract(item, out)
            elif _scannable(item):
                out.append((item, None))
    elif isinstance(obj, dict):
        for k, v in obj.items():
            if _scannable(k):
                out.append((k, None))
            if isinstance(v, (dict, list, tuple)):
                _extract(v, out)
            elif _scannable(v):
                out.append((v, k if isinstance(k, str) else None))


@registry.register
class NightfallFilter(FilterPlugin):
    name = "nightfall"
    description = "scan records for sensitive data via the Nightfall API"
    config_map = [
        ConfigMapEntry("nightfall_api_key", "str"),
        ConfigMapEntry("policy_id", "str"),
        ConfigMapEntry("sampling_rate", "double", default=1.0),
        ConfigMapEntry("api_url", "str",
                       default="https://api.nightfall.ai"),
        ConfigMapEntry("tls.debug", "int", default=0),
    ]

    def init(self, instance, engine) -> None:
        if not (0 < float(self.sampling_rate) <= 1):
            raise ValueError(
                "nightfall: invalid sampling rate, must be (0,1]")
        if not self.nightfall_api_key:
            raise ValueError("nightfall: invalid Nightfall API key")
        if not self.policy_id:
            raise ValueError("nightfall: invalid Nightfall policy ID")
        u = urlsplit(self.api_url)
        self._tls = u.scheme == "https"
        self._host = u.hostname or "api.nightfall.ai"
        self._port = u.port or (443 if self._tls else 80)

    # -- API round trip ------------------------------------------------

    def _scan(self, payload: List[Tuple[object, Optional[str]]]):
        """POST one record's fields; return per-field byte-range lists
        (nightfall_api.c process_response) or None on any failure."""
        items = []
        for value, key in payload:
            text = value if isinstance(value, str) else str(value)
            items.append(f"{key} {text}" if key is not None else text)
        body = json.dumps({"payload": items,
                           "policyUUIDs": [self.policy_id]}).encode()
        got = self._post("/v3/scan", body)
        if got is None:
            return None
        status, resp = got
        if status != 200:
            log.info("nightfall: scan HTTP status %d", status)
            return None
        try:
            findings_per_field = json.loads(resp)["findings"]
            ranges = []
            for findings in findings_per_field:
                ranges.append([
                    (int(f["location"]["byteRange"]["start"]),
                     int(f["location"]["byteRange"]["end"]))
                    for f in findings
                ])
            return ranges
        except (ValueError, KeyError, TypeError):
            return None

    def _post(self, path: str, body: bytes):
        from ..core.config import parse_bool
        from ..utils import sync_http_request

        verify = parse_bool(
            self.instance.properties.get("tls.verify", True))
        got = sync_http_request(
            self._host, self._port, "POST", path,
            {"Authorization": f"Bearer {self.nightfall_api_key}",
             "User-Agent": "Fluent-Bit",
             "Content-Type": "application/json"},
            body, tls=self._tls, tls_verify=verify)
        if got is None:
            return None
        status, _headers, resp = got
        return status, resp

    # -- redaction -----------------------------------------------------

    def _redact_value(self, value, key: Optional[str], ranges):
        if not ranges:
            return value, False
        if isinstance(value, int):
            # integers with any finding are replaced whole
            return "******", True
        raw = bytearray(value.encode("utf-8"))
        offset = len(key.encode("utf-8")) + 1 if key is not None else 0
        changed = False
        for start, end in ranges:
            start = max(0, start - offset)
            end = min(len(raw), end - offset)
            for i in range(start, end):
                changed = changed or raw[i] != 0x2A
                raw[i] = 0x2A  # '*'
        if not changed:
            # every range clamped empty (e.g. a finding entirely inside
            # the key-context prefix): nothing was redacted
            return value, False
        return raw.decode("utf-8", "replace"), True

    def _rebuild(self, obj, ranges, idx: List[int], touched: List[bool]):
        """Re-walk in extraction order, star-filling flagged fields."""
        if isinstance(obj, (list, tuple)):
            out = []
            for item in obj:
                if isinstance(item, (dict, list, tuple)):
                    out.append(self._rebuild(item, ranges, idx, touched))
                elif _scannable(item):
                    r = ranges[idx[0]] if idx[0] < len(ranges) else []
                    idx[0] += 1
                    new, did = self._redact_value(item, None, r)
                    touched[0] |= did
                    out.append(new)
                else:
                    out.append(item)
            return out
        if isinstance(obj, dict):
            out = {}
            for k, v in obj.items():
                nk = k
                if _scannable(k):
                    r = ranges[idx[0]] if idx[0] < len(ranges) else []
                    idx[0] += 1
                    nk, did = self._redact_value(k, None, r)
                    touched[0] |= did
                    if nk != k and nk in out:
                        # two sensitive keys star-filled to the same
                        # string: suffix instead of silently dropping
                        # a field (msgpack maps in the reference can
                        # hold duplicates; Python dicts cannot)
                        base, i = nk, 2
                        while nk in out:
                            nk = f"{base}~{i}"
                            i += 1
                if isinstance(v, (dict, list, tuple)):
                    out[nk] = self._rebuild(v, ranges, idx, touched)
                elif _scannable(v):
                    r = ranges[idx[0]] if idx[0] < len(ranges) else []
                    idx[0] += 1
                    key_ctx = k if isinstance(k, str) else None
                    nv, did = self._redact_value(v, key_ctx, r)
                    touched[0] |= did
                    out[nk] = nv
                else:
                    out[nk] = v
            return out
        return obj

    def filter(self, events: list, tag: str, engine) -> tuple:
        # per-chunk sampling gate, like the reference's rand() check at
        # the top of cb_nightfall_filter (nightfall.c:487)
        if random.random() > float(self.sampling_rate):
            return (FilterResult.NOTOUCH, events)
        # ONE batched scan per chunk (the reference scans per record,
        # nightfall.c:511 — batching keeps the exact per-record DFS
        # payload order while bounding the blocking API round trips
        # the synchronous filter chain must wait on to one per chunk)
        slices = []  # (event, start, count) into the combined payload
        combined: List[Tuple[object, Optional[str]]] = []
        for ev in events:
            payload: List[Tuple[object, Optional[str]]] = []
            _extract(ev.body, payload)
            slices.append((ev, len(combined), len(payload)))
            combined.extend(payload)
        if not combined:
            return (FilterResult.NOTOUCH, events)
        all_ranges = self._scan(combined)
        if all_ranges is None or not any(all_ranges):
            return (FilterResult.NOTOUCH, events)
        out = []
        modified = False
        for ev, start, count in slices:
            ranges = all_ranges[start:start + count]
            if not any(ranges):
                out.append(ev)
                continue
            touched = [False]
            body = self._rebuild(ev.body, ranges, [0], touched)
            if touched[0]:
                modified = True
                out.append(LogEvent(ev.timestamp, body, ev.metadata,
                                    raw=None))
            else:
                out.append(ev)
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)
