"""TCP/UDP inputs + outputs.

Reference: plugins/in_tcp (newline-framed JSON or raw lines over a TCP
listener), plugins/in_udp (same per datagram), plugins/out_tcp and
plugins/out_udp (deliver formatted records to a remote socket). The
reference's event-loop + coroutine I/O (src/flb_io.c) maps onto asyncio
streams running on the engine loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import FlushResult, InputPlugin, OutputPlugin, registry
from .outputs_basic import format_json_lines

log = logging.getLogger("flb.net")


def _json_body_records(line: str, key: str):
    """A line → list of record bodies (format json: must be a map or an
    array of maps; format none handled by caller)."""
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    if isinstance(obj, dict):
        return [obj]
    if isinstance(obj, list) and all(isinstance(o, dict) for o in obj):
        return obj
    return None


class _LineServerInput(InputPlugin):
    """Shared line-framing logic for in_tcp / in_udp / in_unix_socket:
    payload split + the stream/datagram handlers themselves (one copy
    of the framing loop for every transport)."""

    server_task_needed = True

    async def _handle_stream(self, reader, writer, engine) -> None:
        """Connection loop: buffer, emit complete lines at each
        separator, flush the trailing partial on close."""
        pending = b""
        read_size = int(getattr(self, "chunk_size", None) or 32768)
        try:
            while True:
                data = await reader.read(read_size)
                if not data:
                    break
                pending += data
                sep = (self.separator or "\n").encode()
                if sep in pending:
                    head, _, pending = pending.rpartition(sep)
                    self._emit_payload(engine, head)
        finally:
            if pending.strip():
                self._emit_payload(engine, pending)
            writer.close()

    def _datagram_protocol(self, engine):
        import asyncio

        plugin = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                plugin._emit_payload(engine, data)

        return Proto

    def _emit_payload(self, engine, data: bytes) -> None:
        fmt = (self.format or "json").lower()
        out = bytearray()
        n = 0
        for raw in data.split(self.separator.encode() if self.separator else b"\n"):
            if not raw.strip():
                continue
            line = raw.decode("utf-8", "replace")
            if fmt == "none":
                bodies = [{self.source_key or "log": line}]
            else:
                bodies = _json_body_records(line, self.source_key or "log")
                if bodies is None:
                    log.debug("%s: malformed JSON line dropped", self.name)
                    continue
            for body in bodies:
                out += encode_event(body, now_event_time())
                n += 1
        if n:
            engine.input_log_append(self.instance, self.instance.tag,
                                    bytes(out), n)


@registry.register
class TcpInput(_LineServerInput):
    name = "tcp"
    description = "TCP listener for JSON / raw lines"
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=5170),
        ConfigMapEntry("format", "str", default="json"),
        ConfigMapEntry("separator", "str"),
        ConfigMapEntry("source_key", "str", default="log"),
        ConfigMapEntry("chunk_size", "size", default="32k"),
    ]

    def init(self, instance, engine) -> None:
        self._server = None
        self.bound_port: Optional[int] = None

    async def start_server(self, engine) -> None:
        from ..core.tls import server_context

        async def handle(reader, writer):
            await self._handle_stream(reader, writer, engine)

        self._server = await asyncio.start_server(
            handle, self.listen, self.port,
            ssl=server_context(self.instance),
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]
        async with self._server:
            await self._server.serve_forever()


@registry.register
class UdpInput(_LineServerInput):
    name = "udp"
    description = "UDP listener for JSON / raw lines"
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=5170),
        ConfigMapEntry("format", "str", default="json"),
        ConfigMapEntry("separator", "str"),
        ConfigMapEntry("source_key", "str", default="log"),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    async def start_server(self, engine) -> None:
        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            self._datagram_protocol(engine),
            local_addr=(self.listen, self.port),
        )
        self.bound_port = transport.get_extra_info("sockname")[1]
        try:
            await asyncio.Event().wait()  # run until cancelled
        finally:
            transport.close()


class _SocketOutput(OutputPlugin):
    """Connection-reusing TCP client base (upstream pool of size 1 —
    src/flb_upstream.c keepalive semantics)."""

    def init(self, instance, engine) -> None:
        self._writer = None

    async def _connect(self):
        if self._writer is not None and not self._writer.is_closing():
            return self._writer
        from ..core.tls import open_connection

        reader, writer = await open_connection(
            self.instance, self.host, self.port, timeout=10
        )
        self._reader = reader
        self._writer = writer
        return writer

    def _format(self, data: bytes) -> bytes:
        fmt = (self.format or "msgpack").lower()
        if fmt == "msgpack":
            return data
        text = format_json_lines(data, date_key=self.json_date_key or "date")
        if fmt == "json":
            return ("[" + text.replace("\n", ",") + "]").encode()
        return (text + "\n").encode()

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        try:
            writer = await self._connect()
            writer.write(self._format(data))
            await io_deadline(writer.drain())
        except OSError:
            self._writer = None
            return FlushResult.RETRY
        return FlushResult.OK


@registry.register
class TcpOutput(_SocketOutput):
    name = "tcp"
    description = "deliver records over a TCP socket"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=5170),
        ConfigMapEntry("format", "str", default="msgpack"),
        ConfigMapEntry("json_date_key", "str", default="date"),
    ]


@registry.register
class UdpOutput(OutputPlugin):
    name = "udp"
    description = "deliver records over UDP datagrams"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=5170),
        ConfigMapEntry("format", "str", default="json_lines"),
        ConfigMapEntry("json_date_key", "str", default="date"),
    ]

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        import socket

        fmt = (self.format or "json_lines").lower()
        if fmt == "msgpack":
            payloads = [data]
        else:
            text = format_json_lines(data, date_key=self.json_date_key or "date")
            payloads = [(l + "\n").encode() for l in text.splitlines()]
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            for p in payloads:
                s.sendto(p, (self.host, self.port))
            s.close()
        except OSError:
            return FlushResult.RETRY
        return FlushResult.OK
