"""Additional filters: type_converter, checklist, alter_size,
throttle_size, sysinfo.

Reference: plugins/filter_type_converter (int/uint/float/string casts
between keys), plugins/filter_checklist (lookup-list match → set
records/labels, CIDR/exact/partial modes — exact + file list here),
plugins/filter_alter_size (add N dummy records / remove N records),
plugins/filter_throttle_size (per-window byte budget; simplified
sliding window like filter_throttle), plugins/filter_sysinfo (append
host/os/version fields).
"""

from __future__ import annotations

import platform
import sys
import time
from typing import Dict, List, Optional

from ..codec.events import LogEvent, encode_event, now_event_time
from ..core.config import ConfigMapEntry, parse_size
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor


@registry.register
class TypeConverterFilter(FilterPlugin):
    name = "type_converter"
    description = "convert field types into new keys"
    config_map = [
        # <from_key> <to_key> <type>  (type: int|uint|float|string)
        ConfigMapEntry("int_key", "slist", multiple=True),
        ConfigMapEntry("uint_key", "slist", multiple=True),
        ConfigMapEntry("float_key", "slist", multiple=True),
        ConfigMapEntry("str_key", "slist", multiple=True),
    ]

    def init(self, instance, engine) -> None:
        self.rules = []  # (from, to, caster)

        def add(entries, caster):
            for e in entries or []:
                parts = e if isinstance(e, list) else str(e).split()
                if len(parts) < 2:
                    raise ValueError(f"type_converter: bad rule {e!r}")
                self.rules.append((parts[0], parts[1], caster))

        def to_int(v):
            return int(float(v))

        def to_uint(v):
            return abs(int(float(v)))

        add(self.int_key, to_int)
        add(self.uint_key, to_uint)
        add(self.float_key, float)
        add(self.str_key, str)
        if not self.rules:
            raise ValueError("type_converter: no conversion rules")

    def filter(self, events: list, tag: str, engine) -> tuple:
        out = []
        modified = False
        for ev in events:
            if not isinstance(ev.body, dict):
                out.append(ev)
                continue
            body = None
            for src, dst, caster in self.rules:
                if src in ev.body:
                    try:
                        value = caster(ev.body[src])
                    except (TypeError, ValueError):
                        continue
                    if body is None:
                        body = dict(ev.body)
                    body[dst] = value
            if body is None:
                out.append(ev)
            else:
                modified = True
                out.append(LogEvent(ev.timestamp, body, ev.metadata, raw=None))
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)


@registry.register
class ChecklistFilter(FilterPlugin):
    name = "checklist"
    description = "look up a field value in a list file and mark records"
    config_map = [
        ConfigMapEntry("file", "str"),
        ConfigMapEntry("lookup_key", "str"),
        ConfigMapEntry("record", "slist", multiple=True, slist_max_split=1),
        ConfigMapEntry("mode", "str", default="exact"),
        ConfigMapEntry("ignore_case", "bool", default=False),
        ConfigMapEntry("print_query_time", "bool", default=False),
    ]

    def init(self, instance, engine) -> None:
        if not self.file or not self.lookup_key:
            raise ValueError("checklist: file and lookup_key are required")
        self.ra = RecordAccessor(
            self.lookup_key if self.lookup_key.startswith("$")
            else "$" + self.lookup_key
        )
        self._set = set()
        with open(self.file, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    self._set.add(line.lower() if self.ignore_case else line)
        self._records = []
        for pair in self.record or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                self._records.append((parts[0], parts[1]))

    def filter(self, events: list, tag: str, engine) -> tuple:
        out = []
        modified = False
        for ev in events:
            v = self.ra.get(ev.body) if isinstance(ev.body, dict) else None
            hit = isinstance(v, str) and (
                (v.lower() if self.ignore_case else v) in self._set
            )
            if hit and self._records:
                body = dict(ev.body)
                for k, val in self._records:
                    body[k] = val
                out.append(LogEvent(ev.timestamp, body, ev.metadata, raw=None))
                modified = True
            else:
                out.append(ev)
        if not modified:
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, out)


@registry.register
class AlterSizeFilter(FilterPlugin):
    name = "alter_size"
    description = "add or remove records (test/tuning plugin)"
    config_map = [
        ConfigMapEntry("add", "int", default=0),
        ConfigMapEntry("remove", "int", default=0),
    ]

    def init(self, instance, engine) -> None:
        if self.add and self.remove:
            raise ValueError("alter_size: add and remove are exclusive")

    def filter(self, events: list, tag: str, engine) -> tuple:
        if self.remove:
            return (FilterResult.MODIFIED, events[self.remove:])
        if self.add:
            from ..codec.events import decode_events

            extra = b"".join(
                encode_event({"alter_size": "added"}, now_event_time())
                for _ in range(self.add)
            )
            return (FilterResult.MODIFIED, events + decode_events(extra))
        return (FilterResult.NOTOUCH, events)


@registry.register
class ThrottleSizeFilter(FilterPlugin):
    name = "throttle_size"
    description = "rate-limit by bytes per window"
    config_map = [
        ConfigMapEntry("rate", "str", default="1M"),
        ConfigMapEntry("window", "time", default="5"),
        ConfigMapEntry("log_field", "str", default="log"),
    ]

    def init(self, instance, engine) -> None:
        self._budget = parse_size(self.rate)
        self._window_start = time.monotonic()
        self._used = 0

    def filter(self, events: list, tag: str, engine) -> tuple:
        now = time.monotonic()
        if now - self._window_start >= self.window:
            self._window_start = now
            self._used = 0
        kept = []
        for ev in events:
            v = ev.body.get(self.log_field) if isinstance(ev.body, dict) else None
            size = len(v.encode("utf-8", "replace")) if isinstance(v, str) \
                else len(ev.raw or b"")
            if self._used + size <= self._budget:
                self._used += size
                kept.append(ev)
        if len(kept) == len(events):
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, kept)


@registry.register
class SysinfoFilter(FilterPlugin):
    name = "sysinfo"
    description = "append host/os information"
    config_map = [
        ConfigMapEntry("fluentbit_version_key", "str"),
        ConfigMapEntry("os_name_key", "str"),
        ConfigMapEntry("hostname_key", "str"),
        ConfigMapEntry("os_version_key", "str"),
        ConfigMapEntry("kernel_version_key", "str"),
    ]

    def init(self, instance, engine) -> None:
        import socket as _socket

        self._fields: Dict[str, str] = {}
        if self.fluentbit_version_key:
            from .. import __version__

            self._fields[self.fluentbit_version_key] = __version__
        if self.os_name_key:
            self._fields[self.os_name_key] = sys.platform
        if self.hostname_key:
            self._fields[self.hostname_key] = _socket.gethostname()
        if self.os_version_key:
            self._fields[self.os_version_key] = platform.version()
        if self.kernel_version_key:
            self._fields[self.kernel_version_key] = platform.release()
        if not self._fields:
            raise ValueError("sysinfo: no *_key options configured")

    def filter(self, events: list, tag: str, engine) -> tuple:
        out = []
        for ev in events:
            body = dict(ev.body) if isinstance(ev.body, dict) else ev.body
            if isinstance(body, dict):
                body.update(self._fields)
                out.append(LogEvent(ev.timestamp, body, ev.metadata, raw=None))
            else:
                out.append(ev)
        return (FilterResult.MODIFIED, out)
