"""filter_grep — keep/exclude records by regex on a record-accessor field.

Reference: plugins/filter_grep/grep.c. Rules are ``Regex <field> <pattern>``
(keep) and ``Exclude <field> <pattern>`` pairs. Three evaluation modes
(logical_op): legacy (first rule decides: Regex-miss ⇒ EXCLUDE,
Exclude-hit ⇒ EXCLUDE, Regex-hit ⇒ KEEP, fallthrough ⇒ KEEP,
grep.c:167-194), AND, OR (grep.c:250-284 — note the verdict uses the type
of the *last examined* rule, matching the reference exactly).

Execution: when the engine has the TPU ops layer enabled and every rule
pattern compiles to a DFA, matching runs vectorized on device via
fluentbit_tpu.ops.grep (chunk batch → keep mask); otherwise a CPU regex
path with identical semantics. Surviving records are re-emitted
byte-identical (raw span reuse).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor
from ..regex import FlbRegex

LEGACY, AND, OR = "legacy", "AND", "OR"


def _to_text(v) -> Optional[str]:
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return None


class Rule:
    __slots__ = ("is_exclude", "ra", "pattern", "regex")

    def __init__(self, is_exclude: bool, field: str, pattern: str):
        self.is_exclude = is_exclude
        self.ra = RecordAccessor(field)
        self.pattern = pattern
        # Ruby-semantics engine; .dfa is the device-executable table when
        # the pattern is DFA-expressible (fluentbit_tpu.ops.grep uses it)
        self.regex = FlbRegex(pattern)

    @property
    def dfa(self):
        return self.regex.dfa

    def match(self, body: dict) -> bool:
        val = _to_text(self.ra.get(body))
        if val is None:
            return False
        return self.regex.match(val)


@registry.register
class GrepFilter(FilterPlugin):
    name = "grep"
    description = "keep/exclude records matching regex patterns"
    config_map = [
        ConfigMapEntry("regex", "slist", multiple=True, slist_max_split=1,
                       desc="keep rule: <field> <pattern>"),
        ConfigMapEntry("exclude", "slist", multiple=True, slist_max_split=1,
                       desc="exclude rule: <field> <pattern>"),
        ConfigMapEntry("logical_op", "str", default="legacy"),
    ]

    def init(self, instance, engine) -> None:
        self.rules: List[Rule] = []
        # property order matters for legacy mode; reconstruct it
        for key, value in instance.properties.items():
            lk = key.lower()
            if lk in ("regex", "exclude"):
                parts = value.split(None, 1) if isinstance(value, str) else list(value)
                if len(parts) != 2:
                    raise ValueError(f"grep: invalid rule {value!r}")
                self.rules.append(Rule(lk == "exclude", parts[0], parts[1]))
        op = (self.logical_op or "legacy").lower()
        if op == "and":
            self.op = AND
        elif op == "or":
            self.op = OR
        else:
            self.op = LEGACY
        if self.op != LEGACY and self.rules:
            kinds = {r.is_exclude for r in self.rules}
            if len(kinds) > 1:
                raise ValueError("grep: AND/OR mode cannot mix Regex and Exclude rules")

    # -- verdicts (bit-exact vs grep.c) --

    def keep_record(self, body: dict) -> bool:
        if not self.rules:
            return True
        if self.op == LEGACY:
            for rule in self.rules:
                if rule.match(body):
                    return rule.is_exclude is False  # Exclude-hit→drop, Regex-hit→keep
                if not rule.is_exclude:
                    return False  # Regex-miss → exclude
            return True
        # AND/OR: compute 'found' with short-circuit, verdict by last rule's type
        found = False
        rule = self.rules[0]
        for rule in self.rules:
            found = rule.match(body)
            if self.op == OR and found:
                break
            if self.op == AND and not found:
                break
        if not rule.is_exclude:
            return found
        return not found

    def filter(self, events: list, tag: str, engine) -> tuple:
        kept = [ev for ev in events if self.keep_record(ev.body)]
        if len(kept) == len(events):
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, kept)
