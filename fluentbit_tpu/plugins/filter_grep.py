"""filter_grep — keep/exclude records by regex on a record-accessor field.

Reference: plugins/filter_grep/grep.c. Rules are ``Regex <field> <pattern>``
(keep) and ``Exclude <field> <pattern>`` pairs. Three evaluation modes
(logical_op): legacy (first rule decides: Regex-miss ⇒ EXCLUDE,
Exclude-hit ⇒ EXCLUDE, Regex-hit ⇒ KEEP, fallthrough ⇒ KEEP,
grep.c:167-194), AND, OR (grep.c:250-284 — note the verdict uses the type
of the *last examined* rule, matching the reference exactly).

Execution: when every rule pattern compiles to a DFA (and ``tpu.enable``
is on, jax present), matching runs vectorized on device via
fluentbit_tpu.ops.grep — field values are staged into a ``[R, B, L]``
batch, the fused DFA kernel produces the per-rule match matrix, and the
legacy/AND/OR verdict is applied as vector ops on the mask. Records whose
field overflows ``tpu_max_record_len`` (or batches smaller than
``tpu_batch_records``) resolve on the CPU path with identical semantics.
Surviving records are re-emitted byte-identical (raw span reuse).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor
from ..regex import FlbRegex

LEGACY, AND, OR = "legacy", "AND", "OR"

_LEN_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096)


def _len_bucket(n: int, cap: int) -> int:
    """Round a max value length up to a small bucket set (jit-stable
    shapes) without exceeding the configured cap."""
    for b in _LEN_BUCKETS:
        if n <= b:
            return min(b, cap)
    return cap


def _to_text(v) -> Optional[str]:
    """Only string values are regex-matchable — the reference's
    flb_ra_key_regex_match returns no-match for every non-STR msgpack
    type (src/flb_ra_key.c:418)."""
    if isinstance(v, str):
        return v
    return None


class Rule:
    __slots__ = ("is_exclude", "ra", "pattern", "regex")

    def __init__(self, is_exclude: bool, field: str, pattern: str):
        self.is_exclude = is_exclude
        self.ra = RecordAccessor(field)
        self.pattern = pattern
        # Ruby-semantics engine; .dfa is the device-executable table when
        # the pattern is DFA-expressible (fluentbit_tpu.ops.grep uses it)
        self.regex = FlbRegex(pattern)

    @property
    def dfa(self):
        return self.regex.dfa

    def match(self, body: dict) -> bool:
        val = _to_text(self.ra.get(body))
        if val is None:
            return False
        return self.regex.match(val)


def legacy_keep(rules, body: dict) -> bool:
    """First-rule-decides verdict (grep.c:167-194): Exclude-hit ⇒ drop,
    Regex-miss ⇒ drop, Regex-hit ⇒ keep, fallthrough ⇒ keep. Shared by
    filter_grep's legacy mode and filter_log_to_metrics' pre-filter
    (log_to_metrics.c grep_filter_data uses the identical logic)."""
    for rule in rules:
        if rule.match(body):
            return rule.is_exclude is False
        if not rule.is_exclude:
            return False
    return True


def parse_grep_rules(properties) -> List[Rule]:
    """Build the ordered rule list from regex/exclude properties
    (property order matters for legacy semantics)."""
    rules: List[Rule] = []
    for key, value in properties.items():
        lk = key.lower()
        if lk in ("regex", "exclude"):
            parts = value.split(None, 1) if isinstance(value, str) else list(value)
            if len(parts) != 2:
                raise ValueError(f"grep: invalid rule {value!r}")
            rules.append(Rule(lk == "exclude", parts[0], parts[1]))
    return rules


@registry.register
class GrepFilter(FilterPlugin):
    name = "grep"
    description = "keep/exclude records matching regex patterns"
    # the raw path is pure (rules are immutable after init; timing
    # counters take their own lock), so the engine may run it for
    # multiple inputs in parallel under per-input locks
    thread_safe_raw = True
    config_map = [
        ConfigMapEntry("regex", "slist", multiple=True, slist_max_split=1,
                       desc="keep rule: <field> <pattern>"),
        ConfigMapEntry("exclude", "slist", multiple=True, slist_max_split=1,
                       desc="exclude rule: <field> <pattern>"),
        ConfigMapEntry("logical_op", "str", default="legacy"),
        ConfigMapEntry("tpu.enable", "bool", default=True,
                       desc="vectorized device matching when rules allow"),
        ConfigMapEntry("tpu_batch_records", "int", default=32,
                       desc="min records per append to use the device path"),
        ConfigMapEntry("tpu_max_record_len", "int", default=512,
                       desc="field byte length staged on device; longer "
                            "values resolve on the CPU fallback"),
    ]

    def init(self, instance, engine) -> None:
        self.rules = parse_grep_rules(instance.properties)
        op = (self.logical_op or "legacy").lower()
        if op == "and":
            self.op = AND
        elif op == "or":
            self.op = OR
        else:
            self.op = LEGACY
        if self.op != LEGACY and self.rules:
            kinds = {r.is_exclude for r in self.rules}
            if len(kinds) > 1:
                raise ValueError("grep: AND/OR mode cannot mix Regex and Exclude rules")
        # probe (and first-build) the native scanner here, NOT on the
        # hot append path under the ingest lock — the one-time g++
        # compile must never stall ingest
        from .. import native as _native

        _native.available()
        # device program: all rules DFA-expressible + jax importable.
        # program_for is numpy-only (cheap); the backend transfer waits
        # on the attach controller so a slow/hung platform init never
        # blocks plugin init or ingest — records run the bit-exact CPU
        # path until the device is up (VERDICT r2: CLI was un-killable
        # for minutes inside eager jax init).
        import threading

        self._program = None
        self._native_tables = None
        self._native_filter = None
        self.raw_timings = {"extract_s": 0.0, "kernel_s": 0.0,
                            "compact_s": 0.0, "records": 0}
        self._tm_lock = threading.Lock()
        if self.tpu_enable and self.rules and all(r.dfa is not None for r in self.rules):
            try:
                from ..ops import device
                from ..ops.grep import program_for

                self._program = program_for(
                    tuple(r.pattern for r in self.rules), self.tpu_max_record_len
                )
                device.wait()  # bounded (FBTPU_ATTACH_WAIT_S, default 2s)
                self._program.try_ready()
            except Exception:
                self._program = None
            # host-side twin: one-pass C++ field-extract + DFA over
            # chunk bytes (simple top-level keys only). Serves the raw
            # path while the device attaches and whenever the attached
            # backend is the jax CPU fallback.
            if self._program is not None and all(
                not r.ra.parts for r in self.rules
            ):
                try:
                    self._native_tables = _native.GrepTables(
                        [(r.ra.head.encode("utf-8"), r.dfa)
                         for r in self.rules]
                    )
                except Exception:
                    self._native_tables = None
                # fused single-pass variant (extract + accel DFA +
                # verdict + compaction in one native call)
                try:
                    self._native_filter = _native.GrepFilterTables(
                        [(r.ra.head.encode("utf-8"), r.dfa, r.is_exclude)
                         for r in self.rules],
                        op=self.op,
                    )
                except Exception:
                    self._native_filter = None

    # -- verdicts (bit-exact vs grep.c) --

    def keep_record(self, body: dict) -> bool:
        if not self.rules:
            return True
        if self.op == LEGACY:
            return legacy_keep(self.rules, body)
        # AND/OR: compute 'found' with short-circuit, verdict by last rule's type
        found = False
        rule = self.rules[0]
        for rule in self.rules:
            found = rule.match(body)
            if self.op == OR and found:
                break
            if self.op == AND and not found:
                break
        if not rule.is_exclude:
            return found
        return not found

    # -- vectorized verdicts over the device match matrix --

    def keep_mask(self, mask: np.ndarray) -> np.ndarray:
        """mask[R, B] per-rule match matrix → keep[B], same semantics as
        keep_record (grep.c verdict logic applied as vector ops)."""
        B = mask.shape[1]
        if self.op == LEGACY:
            keep = np.ones(B, dtype=bool)
            undecided = np.ones(B, dtype=bool)
            for r, rule in enumerate(self.rules):
                m = mask[r]
                if rule.is_exclude:
                    keep &= ~(undecided & m)  # Exclude-hit → drop
                    undecided &= ~m
                else:
                    # a Regex rule decides every still-undecided record
                    keep = np.where(undecided, m, keep)
                    break
            return keep
        found = mask.any(axis=0) if self.op == OR else mask.all(axis=0)
        # AND/OR rules are all the same kind (enforced in init)
        return ~found if self.rules[0].is_exclude else found

    def _match_matrix_device(self, events: list) -> np.ndarray:
        """Stage field values, run the fused DFA kernel, resolve overflow
        rows on CPU. Returns mask[R, B] bool."""
        from ..ops.batch import assemble, bucket_size

        B = len(events)
        R = len(self.rules)
        # rules addressing the same field share one extraction + staging
        # pass (the staging loop is the hot-path bottleneck)
        by_path: dict = {}
        for r, rule in enumerate(self.rules):
            by_path.setdefault(rule.ra.pattern, (rule.ra, []))[1].append(r)
        Bp = bucket_size(B)
        L = self.tpu_max_record_len
        values: List[Optional[List[Optional[bytes]]]] = [None] * R
        batches = [None] * R
        for ra, idxs in by_path.values():
            vals: List[Optional[bytes]] = []
            for ev in events:
                v = _to_text(ra.get(ev.body))
                vals.append(v.encode("utf-8") if v is not None else None)
            staged = assemble(vals, L, Bp)
            for r in idxs:
                values[r] = vals
                batches[r] = staged
        batch = np.stack([b.batch for b in batches])
        lengths = np.stack([b.lengths for b in batches])
        mask = self._program.match(batch, lengths)
        mask = np.array(mask[:, :B])
        for r, brec in enumerate(batches):
            rule = self.rules[r]
            for i in brec.overflow:
                mask[r, i] = rule.regex.match(values[r][i])
        return mask

    def filter(self, events: list, tag: str, engine) -> tuple:
        if (
            self._program is not None
            and len(events) >= self.tpu_batch_records
            and self.rules
            and self._program.try_ready()
        ):
            keep = self.keep_mask(self._match_matrix_device(events))
            kept = [ev for ev, k in zip(events, keep) if k]
        else:
            kept = [ev for ev in events if self.keep_record(ev.body)]
        if len(kept) == len(events):
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, kept)

    # -- raw chunk-bytes path (no Python decode) --

    def can_filter_raw(self) -> bool:
        """True when matching can run straight off chunk bytes: native
        scanner present, every rule addresses a simple top-level key,
        and an engine is available — the one-pass C++ DFA (always, once
        tables are packed) or the device kernel (once attached)."""
        from .. import native

        return (
            self._program is not None
            and bool(self.rules)
            and all(not r.ra.parts for r in self.rules)
            and native.available()
            and (self._native_tables is not None
                 or self._program.try_ready())
        )

    def filter_raw(self, data: bytes, tag: str, engine, n_records=None):
        """Raw chunk-bytes matching → verdict → raw-span compaction.
        Returns (n_records, new_data) or None to decline (the engine
        then falls back to the decode path). Byte-identical surviving
        records — the grep contract (grep.c:286-392).

        Engine selection: the jax kernel runs when a non-CPU device is
        attached (the point of the build); the one-pass C++ DFA twin
        serves while the device is attaching and whenever jax would run
        on its own CPU backend (a table-driven C loop beats the
        sequential lax.scan there by orders of magnitude)."""
        import time as _time

        from .. import native
        from ..ops import device
        from ..ops.batch import bucket_size

        if not native.available():
            return None
        tm = self.raw_timings
        tm_lock = self._tm_lock
        # platform check FIRST: on a CPU-backend host try_ready() would
        # needlessly materialize the jax program that will never run
        use_native = self._native_tables is not None and (
            device.platform() == "cpu" or not self._program.try_ready()
        )
        if use_native and self._native_filter is not None:
            # fused path: extraction + prepass DFA + verdict + compaction
            # in ONE native pass; all-kept chunks return the input
            # buffer untouched (zero copies). The walk discovers the
            # record count, so the triple return lets the engine skip
            # its counting pre-pass entirely.
            t0 = _time.perf_counter()
            got = native.grep_filter(data, self._native_filter,
                                     n_hint=n_records)
            if got is None:
                return None
            n, n_keep, out = got
            with tm_lock:
                tm["kernel_s"] += _time.perf_counter() - t0
                tm["records"] += n
            return (n_keep, out, n)
        if use_native:
            t0 = _time.perf_counter()
            got = native.grep_match(
                data, self._native_tables, n_hint=n_records
            )
            if got is None:
                return None
            mask, offsets, n = got
            with tm_lock:
                tm["kernel_s"] += _time.perf_counter() - t0
        else:
            if n_records is not None and n_records < self.tpu_batch_records:
                return None  # small batches: decode path is cheaper
            by_key: dict = {}
            for r, rule in enumerate(self.rules):
                by_key.setdefault(rule.ra.head.encode("utf-8"), []).append(r)
            staged = {}
            offsets = None
            n = None
            t0 = _time.perf_counter()
            for key, idxs in by_key.items():
                got = native.stage_field(
                    data, key, self.tpu_max_record_len, None,
                    n_hint=n_records
                )
                if got is None:
                    return None
                batch, lengths, offs, count = got
                if n is None:
                    n, offsets = count, offs
                if len(by_key) > 1:
                    # stage_field returns views of a per-thread arena
                    # that the NEXT call overwrites — multi-key rule
                    # sets must copy each key's staging out first
                    batch, lengths = batch.copy(), lengths.copy()
                staged[key] = (batch, lengths)
            if n is None or n < self.tpu_batch_records:
                return None  # small batches: decode path is cheaper
            Bp = bucket_size(n)
            R = len(self.rules)
            # scan-length bucketing: the DFA scan is sequential in L, so
            # clamp to the longest staged value (rounded to a small bucket
            # set for jit shape stability) instead of always
            # tpu_max_record_len
            max_staged = max(
                (int(ln.max()) if ln.size else 0)
                for _, ln in staged.values()
            )
            L = _len_bucket(max(max_staged, 1), self.tpu_max_record_len)
            batch = np.zeros((R, Bp, L), dtype=np.uint8)
            lengths = np.full((R, Bp), -1, dtype=np.int32)
            for key, idxs in by_key.items():
                b, ln = staged[key]
                for r in idxs:
                    batch[r, :n] = b[:, :L]
                    lengths[r, :n] = ln
            with tm_lock:
                tm["extract_s"] += _time.perf_counter() - t0
            t0 = _time.perf_counter()
            mask = np.array(self._program.match(batch, lengths)[:, :n])
            with tm_lock:
                tm["kernel_s"] += _time.perf_counter() - t0
            # overflow rows (-2): decode just those records on the CPU
            overflow_rows = np.unique(np.nonzero(lengths[:, :n] == -2)[1])
            if overflow_rows.size:
                from ..codec.events import decode_events

                for b_idx in overflow_rows:
                    span = bytes(data[offsets[b_idx]: offsets[b_idx + 1]])
                    ev = decode_events(span)[0]
                    for r, rule in enumerate(self.rules):
                        if lengths[r, b_idx] == -2:
                            mask[r, b_idx] = rule.match(ev.body)
        with tm_lock:
            tm["records"] += n
        keep = self.keep_mask(mask)
        n_keep = int(keep.sum())
        if n_keep == n:
            return (n, data)
        if n_keep == 0:
            return (0, b"")
        t0 = _time.perf_counter()
        compacted = native.compact(data, offsets[: n + 1], keep)
        with tm_lock:
            tm["compact_s"] += _time.perf_counter() - t0
        if compacted is not None:
            return (n_keep, compacted)
        parts = [
            data[offsets[i]: offsets[i + 1]]
            for i in np.nonzero(keep)[0]
        ]
        return (n_keep, b"".join(parts))
