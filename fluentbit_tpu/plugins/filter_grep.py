"""filter_grep — keep/exclude records by regex on a record-accessor field.

Reference: plugins/filter_grep/grep.c. Rules are ``Regex <field> <pattern>``
(keep) and ``Exclude <field> <pattern>`` pairs. Three evaluation modes
(logical_op): legacy (first rule decides: Regex-miss ⇒ EXCLUDE,
Exclude-hit ⇒ EXCLUDE, Regex-hit ⇒ KEEP, fallthrough ⇒ KEEP,
grep.c:167-194), AND, OR (grep.c:250-284 — note the verdict uses the type
of the *last examined* rule, matching the reference exactly).

Execution: when every rule pattern compiles to a DFA (and ``tpu.enable``
is on, jax present), matching runs vectorized on device via
fluentbit_tpu.ops.grep — field values are staged into a ``[R, B, L]``
batch, the fused DFA kernel produces the per-rule match matrix, and the
legacy/AND/OR verdict is applied as vector ops on the mask. Records whose
field overflows ``tpu_max_record_len`` (or batches smaller than
``tpu_batch_records``) resolve on the CPU path with identical semantics.
Surviving records are re-emitted byte-identical (raw span reuse).
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

import numpy as np

from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from ..core.record_accessor import RecordAccessor
from ..regex import FlbRegex

log = logging.getLogger("flb")

LEGACY, AND, OR = "legacy", "AND", "OR"

_LEN_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096)


class _RawDecline(Exception):
    """Internal: a staging stage inside the pipelined raw path cannot
    serve this chunk — unwind and decline to the decode path."""


class ShardedTimings:
    """Per-thread timing shards for the raw path's hot-loop accounting.

    The previous shared dict + lock serialized every ingest worker on
    one mutex several times per chunk (the BENCH_r05 multi-input
    regression's lock half); adds now go to an uncontended thread-local
    shard and reads sum across shards. The mapping interface
    (iteration / item get / item set) keeps bench.py's reset-and-read
    usage working: item reads return the cross-shard sum, item writes
    are the RESET hook (bench zeroes between warmup and measurement)
    and store the value into every shard — meaningful for zero only.
    """

    _KEYS = ("extract_s", "kernel_s", "compact_s", "records")

    def __init__(self):
        import threading

        self._tls = threading.local()
        self._shards: list = []
        self._reg_lock = threading.Lock()  # shard registration (cold)

    def _shard(self) -> dict:
        d = getattr(self._tls, "d", None)
        if d is None:
            d = {k: 0 for k in self._KEYS}
            with self._reg_lock:
                self._shards.append(d)
            self._tls.d = d
        return d

    def add(self, key: str, value) -> None:
        self._shard()[key] += value

    def __iter__(self):
        return iter(self._KEYS)

    def __contains__(self, key) -> bool:
        return key in self._KEYS

    def __getitem__(self, key):
        with self._reg_lock:
            shards = list(self._shards)
        return sum(d[key] for d in shards)

    def __setitem__(self, key, value) -> None:
        with self._reg_lock:
            shards = list(self._shards)
        for d in shards:
            d[key] = value


def _len_bucket(n: int, cap: int) -> int:
    """Round a max value length up to a small bucket set (jit-stable
    shapes) without exceeding the configured cap."""
    for b in _LEN_BUCKETS:
        if n <= b:
            return min(b, cap)
    return cap


def _to_text(v) -> Optional[str]:
    """Only string values are regex-matchable — the reference's
    flb_ra_key_regex_match returns no-match for every non-STR msgpack
    type (src/flb_ra_key.c:418)."""
    if isinstance(v, str):
        return v
    return None


class Rule:
    __slots__ = ("is_exclude", "ra", "pattern", "regex")

    def __init__(self, is_exclude: bool, field: str, pattern: str):
        self.is_exclude = is_exclude
        self.ra = RecordAccessor(field)
        self.pattern = pattern
        # Ruby-semantics engine; .dfa is the device-executable table when
        # the pattern is DFA-expressible (fluentbit_tpu.ops.grep uses it)
        self.regex = FlbRegex(pattern)

    @property
    def dfa(self):
        return self.regex.dfa

    def match(self, body: dict) -> bool:
        val = _to_text(self.ra.get(body))
        if val is None:
            return False
        return self.regex.match(val)


def legacy_keep(rules, body: dict) -> bool:
    """First-rule-decides verdict (grep.c:167-194): Exclude-hit ⇒ drop,
    Regex-miss ⇒ drop, Regex-hit ⇒ keep, fallthrough ⇒ keep. Shared by
    filter_grep's legacy mode and filter_log_to_metrics' pre-filter
    (log_to_metrics.c grep_filter_data uses the identical logic)."""
    for rule in rules:
        if rule.match(body):
            return rule.is_exclude is False
        if not rule.is_exclude:
            return False
    return True


def legacy_keep_mask(rules, mask: np.ndarray) -> np.ndarray:
    """Vectorized ``legacy_keep`` over a per-rule match matrix
    ``mask[R, B]`` → ``keep[B]`` (grep.c:167-194 first-rule-decides as
    vector ops). Shared by filter_grep's device/native verdicts and
    filter_log_to_metrics' batched pre-filter."""
    B = mask.shape[1]
    keep = np.ones(B, dtype=bool)
    undecided = np.ones(B, dtype=bool)
    for r, rule in enumerate(rules):
        m = mask[r]
        if rule.is_exclude:
            keep &= ~(undecided & m)  # Exclude-hit → drop
            undecided &= ~m
        else:
            # a Regex rule decides every still-undecided record
            keep = np.where(undecided, m, keep)
            break
    return keep


def parse_grep_rules(properties) -> List[Rule]:
    """Build the ordered rule list from regex/exclude properties
    (property order matters for legacy semantics)."""
    rules: List[Rule] = []
    for key, value in properties.items():
        lk = key.lower()
        if lk in ("regex", "exclude"):
            parts = value.split(None, 1) if isinstance(value, str) else list(value)
            if len(parts) != 2:
                raise ValueError(f"grep: invalid rule {value!r}")
            rules.append(Rule(lk == "exclude", parts[0], parts[1]))
    return rules


@registry.register
class GrepFilter(FilterPlugin):
    name = "grep"
    description = "keep/exclude records matching regex patterns"
    # the raw path is pure (rules are immutable after init; timing
    # counters take their own lock), so the engine may run it for
    # multiple inputs in parallel under per-input locks
    thread_safe_raw = True
    config_map = [
        ConfigMapEntry("regex", "slist", multiple=True, slist_max_split=1,
                       desc="keep rule: <field> <pattern>"),
        ConfigMapEntry("exclude", "slist", multiple=True, slist_max_split=1,
                       desc="exclude rule: <field> <pattern>"),
        ConfigMapEntry("logical_op", "str", default="legacy"),
        ConfigMapEntry("tpu.enable", "bool", default=True,
                       desc="vectorized device matching when rules allow"),
        ConfigMapEntry("tpu_batch_records", "int", default=32,
                       desc="min records per append to use the device path"),
        ConfigMapEntry("tpu_max_record_len", "int", default=512,
                       desc="field byte length staged on device; longer "
                            "values resolve on the CPU fallback"),
        # fbtpu-shrink approximate mode (PERF.md "shrink"): run an
        # over-approximated (smaller) DFA as a first-pass mask on the
        # raw path and re-check admitted records exactly — output
        # stays byte-identical; only the hot table shrinks
        ConfigMapEntry("tpu_approx", "bool", default=False,
                       desc="approximate first-pass DFA mask + exact "
                            "recheck (also FBTPU_DFA_APPROX)"),
        ConfigMapEntry("tpu_approx_states", "int", default=64,
                       desc="state budget for the approximate DFA "
                            "(<=64 also unlocks the assoc kernel)"),
        ConfigMapEntry("tpu_approx_fp_budget", "double", default=0.5,
                       desc="measured false-positive budget: approx "
                            "mode self-disables when the mask's "
                            "measured FP rate exceeds this fraction"),
    ]

    def init(self, instance, engine) -> None:
        self.rules = parse_grep_rules(instance.properties)
        op = (self.logical_op or "legacy").lower()
        if op == "and":
            self.op = AND
        elif op == "or":
            self.op = OR
        else:
            self.op = LEGACY
        if self.op != LEGACY and self.rules:
            kinds = {r.is_exclude for r in self.rules}
            if len(kinds) > 1:
                raise ValueError("grep: AND/OR mode cannot mix Regex and Exclude rules")
        # probe (and first-build) the native scanner here, NOT on the
        # hot append path under the ingest lock — the one-time g++
        # compile must never stall ingest
        from .. import native as _native

        _native.available()
        # device program: all rules DFA-expressible + jax importable.
        # program_for is numpy-only (cheap); the backend transfer waits
        # on the attach controller so a slow/hung platform init never
        # blocks plugin init or ingest — records run the bit-exact CPU
        # path until the device is up (VERDICT r2: CLI was un-killable
        # for minutes inside eager jax init).
        import threading

        self._program = None
        self._native_tables = None
        self._native_filter = None
        self._mesh = None
        self._mesh_resolved = False
        self._mesh_on = False
        self._mesh_gen = None
        self.raw_timings = ShardedTimings()
        # per-worker copies of the read-only native tables (multi-input
        # scaling: no cross-thread sharing of the hot arrays)
        self._tls_tables = threading.local()
        if self.tpu_enable and self.rules and all(r.dfa is not None for r in self.rules):
            try:
                from ..ops import device
                from ..ops.grep import program_for

                self._program = program_for(
                    tuple(r.pattern for r in self.rules), self.tpu_max_record_len
                )
                device.wait()  # bounded (FBTPU_ATTACH_WAIT_S, default 2s)
                self._program.try_ready()
            except Exception:
                log.debug("grep device program unavailable; host path "
                          "serves", exc_info=True)
                self._program = None
            # host-side twin: one-pass C++ field-extract + DFA over
            # chunk bytes (simple top-level keys only). Serves the raw
            # path while the device attaches and whenever the attached
            # backend is the jax CPU fallback.
            if self._program is not None and all(
                not r.ra.parts for r in self.rules
            ):
                try:
                    self._native_tables = _native.GrepTables(
                        [(r.ra.head.encode("utf-8"), r.dfa)
                         for r in self.rules]
                    )
                except Exception:
                    log.warning("grep native table build failed; raw "
                                "staging path disabled", exc_info=True)
                    self._native_tables = None
                # fused single-pass variant (extract + accel DFA +
                # verdict + compaction in one native call)
                try:
                    self._native_filter = _native.GrepFilterTables(
                        [(r.ra.head.encode("utf-8"), r.dfa, r.is_exclude)
                         for r in self.rules],
                        op=self.op,
                    )
                except Exception:
                    log.warning("grep fused filter table build failed; "
                                "fused raw path disabled", exc_info=True)
                    self._native_filter = None
        self._init_approx(instance, engine)
        self._report_shrink(instance, engine)

    def _init_approx(self, instance, engine) -> None:
        """fbtpu-shrink approximate mode (opt-in, default off): build
        the over-approximated mask tables. Rules whose exact DFA
        already fits the state budget keep their exact tables in the
        mask set (mask == exact for them — still sound); if NO rule
        reduces, the mode stays off (pure overhead)."""
        import os as _os

        self._approx_tables = None
        self._approx_info = None
        self._approx_live = True
        # measured-FP window counters: bumped from parallel ingest
        # workers without a lock — increments may race and lose (benign
        # staleness, same stance as ShardedTimings), the budget trip
        # only needs the order of magnitude
        self._approx_seen = 0
        self._approx_fp = 0
        from ..regex.dfa import approx_env_states

        env_target = approx_env_states(self.tpu_approx_states)
        if not (self.tpu_approx or env_target is not None):
            return
        if self._native_tables is None:
            return
        target = env_target if env_target is not None \
            else self.tpu_approx_states
        from .. import native as _native
        from ..regex.dfa import approx_reduce

        try:
            reduced = [approx_reduce(r.dfa, target) for r in self.rules]
            if not any(rd is not None for rd in reduced):
                log.info("grep approx mode requested but every rule DFA "
                         "already fits %d states; exact path serves",
                         target)
                return
            self._approx_tables = _native.GrepTables(
                [(r.ra.head.encode("utf-8"),
                  rd if rd is not None else r.dfa)
                 for r, rd in zip(self.rules, reduced)])
            self._approx_info = [
                None if rd is None else {
                    "s_exact": rd.shrink.approx_of,
                    "s": rd.n_states,
                    "c": rd.n_classes,
                    "depth": rd.shrink.approx_depth,
                }
                for rd in reduced
            ]
            log.info("grep approx mask engaged (target %d states): %s",
                     target, self._approx_info)
        except Exception:
            log.warning("grep approximate-mask build failed; exact "
                        "path serves", exc_info=True)
            self._approx_tables = None

    def _report_shrink(self, instance, engine) -> None:
        """fluentbit_grep_shrink_* compile-outcome counters (the
        runtime admit/recheck/FP counters bump per chunk in
        _approx_match_raw)."""
        if engine is None or getattr(engine, "m_shrink_states", None) \
                is None:
            return
        # plugin-name label, matching the per-chunk admit/recheck
        # counters (_approx_match_raw) so one dashboard family reads
        label = (self.name,)
        elim_s = elim_c = 0
        for r in self.rules:
            st = getattr(r.dfa, "shrink", None) if r.dfa is not None \
                else None
            if st is not None:
                elim_s += st.states_eliminated
                elim_c += st.classes_eliminated
        if elim_s:
            engine.m_shrink_states.inc(elim_s, label)
        if elim_c:
            engine.m_shrink_classes.inc(elim_c, label)

    # -- verdicts (bit-exact vs grep.c) --

    def keep_record(self, body: dict) -> bool:
        if not self.rules:
            return True
        if self.op == LEGACY:
            return legacy_keep(self.rules, body)
        # AND/OR: compute 'found' with short-circuit, verdict by last rule's type
        found = False
        rule = self.rules[0]
        for rule in self.rules:
            found = rule.match(body)
            if self.op == OR and found:
                break
            if self.op == AND and not found:
                break
        if not rule.is_exclude:
            return found
        return not found

    # -- vectorized verdicts over the device match matrix --

    def keep_mask(self, mask: np.ndarray) -> np.ndarray:
        """mask[R, B] per-rule match matrix → keep[B], same semantics as
        keep_record (grep.c verdict logic applied as vector ops)."""
        if self.op == LEGACY:
            return legacy_keep_mask(self.rules, mask)
        found = mask.any(axis=0) if self.op == OR else mask.all(axis=0)
        # AND/OR rules are all the same kind (enforced in init)
        return ~found if self.rules[0].is_exclude else found

    def _lane(self):
        """This plugin's device fault domain (fbtpu-armor): every jit/
        pjit/shard_map launch goes through the process-global "grep"
        DeviceLane — breaker, launch deadline, bit-exact CPU fallback,
        mesh shrink/regrow (FAULTS.md "fbtpu-armor")."""
        ln = getattr(self, "_lane_obj", None)
        if ln is None:
            from ..ops import fault

            ln = self._lane_obj = fault.lane("grep")
        return ln

    def _host_mask(self, batch: np.ndarray, lengths: np.ndarray,
                   cnt: int) -> np.ndarray:
        """Bit-exact host twin of the kernel verdict over a staged
        segment — the DeviceLane fallback. Rows with length < 0
        (missing -1, overflow -2) stay False, exactly like the kernel;
        the caller's overflow decode then fixes -2 rows the same way it
        does after a device launch."""
        R = len(self.rules)
        mask = np.zeros((R, cnt), dtype=bool)
        for r, rule in enumerate(self.rules):
            ln = lengths[r]
            row = batch[r]
            rx = rule.regex
            for i in range(cnt):
                li = int(ln[i])
                if li >= 0:
                    mask[r, i] = rx.match(bytes(row[i, :li]).decode(
                        "utf-8", "surrogateescape"))
        return mask

    def _match_matrix_device(self, events: list) -> np.ndarray:
        """Stage field values, run the fused DFA kernel, resolve overflow
        rows on CPU. Returns mask[R, B] bool."""
        from ..ops.batch import assemble, bucket_size

        B = len(events)
        R = len(self.rules)
        # rules addressing the same field share one extraction + staging
        # pass (the staging loop is the hot-path bottleneck)
        by_path: dict = {}
        for r, rule in enumerate(self.rules):
            by_path.setdefault(rule.ra.pattern, (rule.ra, []))[1].append(r)
        L = self.tpu_max_record_len
        Bp = bucket_size(B, max_len=L)
        values: List[Optional[List[Optional[bytes]]]] = [None] * R
        batches = [None] * R
        for ra, idxs in by_path.values():
            vals: List[Optional[bytes]] = []
            for ev in events:
                v = _to_text(ra.get(ev.body))
                vals.append(v.encode("utf-8") if v is not None else None)
            staged = assemble(vals, L, Bp)
            for r in idxs:
                values[r] = vals
                batches[r] = staged
        batch = np.stack([b.batch for b in batches])
        lengths = np.stack([b.lengths for b in batches])
        lane = self._lane()
        mask = lane.run(
            lambda: np.asarray(self._program.match(batch, lengths)),
            lambda: self._host_mask(batch, lengths, batch.shape[1]),
        )
        mask = np.array(mask[:, :B])
        for r, brec in enumerate(batches):
            rule = self.rules[r]
            for i in brec.overflow:
                mask[r, i] = rule.regex.match(values[r][i])
        return mask

    def filter(self, events: list, tag: str, engine) -> tuple:
        if (
            self._program is not None
            and len(events) >= self.tpu_batch_records
            and self.rules
            and self._program.try_ready()
        ):
            keep = self.keep_mask(self._match_matrix_device(events))
            kept = [ev for ev, k in zip(events, keep) if k]
        else:
            kept = [ev for ev in events if self.keep_record(ev.body)]
        if len(kept) == len(events):
            return (FilterResult.NOTOUCH, events)
        return (FilterResult.MODIFIED, kept)

    # -- raw chunk-bytes path (no Python decode) --

    def _grep_mesh(self):
        """The device mesh the raw path shards across, or None.

        Resolved from ``FBTPU_MESH``: ``off``/``0`` never builds one;
        ``on``/``1``/``force`` builds it from whatever devices exist
        (the simulated-mesh lane — 8 virtual CPU devices under
        ``--xla_force_host_platform_device_count``); ``auto`` (default)
        engages only when a real accelerator with ≥2 devices attached —
        on a CPU backend the native fused matcher beats a partitioned
        lax.scan by orders of magnitude, so auto must never shadow it.

        The resolution only PINS once the attach controller reaches a
        terminal state (ready/failed-exhausted) — a chunk arriving
        mid-attach (or mid-RETRY, fbtpu-armor) must not permanently
        disable the mesh lane for the plugin's lifetime — and it pins
        per attach GENERATION: an attach that succeeds later (a retry
        attempt landing after chunks already flowed on CPU, or an
        ops-driven ``device.reattach_async``) re-opens the resolution
        and the mesh lane swaps in live. Once engaged, the mesh object
        itself comes from the "grep" DeviceLane, which shrinks it on
        device loss and regrows it when the breaker re-closes."""
        import os as _os

        from ..ops import device

        gen = device.generation()
        if self._mesh_resolved and gen > 0 \
                and getattr(self, "_mesh_gen", None) != gen:
            # new attach generation: the old verdict (pinned-off after
            # a failed attach, or a mesh over the previous backend) is
            # stale — re-resolve against the live device
            self._mesh_resolved = False
        if self._mesh_resolved:
            if getattr(self, "_mesh_on", False):
                self._mesh = self._lane().current_mesh()
            return self._mesh
        mode = _os.environ.get("FBTPU_MESH", "auto").lower()
        if self._program is None or mode in ("0", "off"):
            self._mesh_resolved = True
            self._mesh_gen = gen
            return None
        try:
            if mode in ("1", "on", "force"):
                if device.wait():
                    self._mesh = self._lane().current_mesh()
                    self._mesh_resolved = True
                elif device.failed():
                    log.warning("FBTPU_MESH=%s but device attach "
                                "exhausted its retries (%s); unsharded "
                                "path pinned until a re-attach "
                                "generation", mode,
                                device.status().get("error"))
                    self._mesh_resolved = True
                # else: still attaching/retrying — re-probe next chunk
            elif device.ready():
                if device.platform() != "cpu" \
                        and device.device_count() > 1:
                    self._mesh = self._lane().current_mesh()
                self._mesh_resolved = True
            elif device.failed():
                self._mesh_resolved = True
            else:
                device.attach_async()  # auto mid-attach: probe again
        except Exception:
            log.warning("grep mesh build failed; unsharded device "
                        "path serves", exc_info=True)
            self._mesh = None
            self._mesh_resolved = True
        if self._mesh_resolved:
            self._mesh_gen = gen
            self._mesh_on = self._mesh is not None
        return self._mesh

    def can_filter_raw(self) -> bool:
        """True when matching can run straight off chunk bytes: native
        scanner present, every rule addresses a simple top-level key,
        and an engine is available — the one-pass C++ DFA (always, once
        tables are packed) or the device kernel (once attached)."""
        from .. import native

        return (
            self._program is not None
            and bool(self.rules)
            and all(not r.ra.parts for r in self.rules)
            and native.available()
            and (self._native_tables is not None
                 or self._program.try_ready())
        )

    def filter_raw(self, data: bytes, tag: str, engine, n_records=None):
        """Raw chunk-bytes matching → verdict → raw-span compaction.
        Returns (n_records, new_data) or None to decline (the engine
        then falls back to the decode path). Byte-identical surviving
        records — the grep contract (grep.c:286-392).

        Engine selection: the jax kernel runs when a non-CPU device is
        attached (the point of the build); the one-pass C++ DFA twin
        serves while the device is attaching and whenever jax would run
        on its own CPU backend (a table-driven C loop beats the
        sequential lax.scan there by orders of magnitude)."""
        import time as _time

        from .. import native
        from ..ops import device

        if not native.available():
            return None
        tm = self.raw_timings
        # mesh first: when the partitioned pjit plane is engaged
        # (FBTPU_MESH — real multi-chip attach, or forced for the
        # simulated lane) it IS the device path, native serves staging
        mesh = self._grep_mesh()
        # platform check FIRST: on a CPU-backend host try_ready() would
        # needlessly materialize the jax program that will never run
        use_native = self._native_tables is not None and mesh is None and (
            device.platform() == "cpu" or not self._program.try_ready()
        )
        if use_native and self._approx_tables is not None \
                and self._approx_live:
            # fbtpu-shrink approximate mode: reduced-DFA first-pass
            # mask, then the EXACT tables re-check only the admitted
            # records — mask-False is definitive (the reduced machine
            # over-approximates the language), so the final mask is
            # exactly the exact chain's and every verdict downstream
            # is byte-identical
            t0 = _time.perf_counter()
            got = self._approx_match_raw(data, engine, n_records)
            if got is not None:
                mask, offsets, n = got
                tm.add("kernel_s", _time.perf_counter() - t0)
                tm.add("records", n)
                keep = self.keep_mask(mask)
                n_keep = int(keep.sum())
                if n_keep == n:
                    return (n, data)
                if n_keep == 0:
                    return (0, b"")
                t0 = _time.perf_counter()
                # by design: this compact sits on the host-native
                # approx branch (no device launch reachable when
                # use_native holds) — no verdict crossed PCIe here
                # fbtpu-lint: allow(device-host-roundtrip)
                compacted = native.compact(data, offsets[: n + 1], keep)
                tm.add("compact_s", _time.perf_counter() - t0)
                if compacted is not None:
                    return (n_keep, compacted)
                parts = [
                    data[offsets[i]: offsets[i + 1]]
                    for i in np.nonzero(keep)[0]
                ]
                return (n_keep, b"".join(parts))
            # approx mask unavailable this chunk: exact paths serve
        if use_native and self._native_filter is not None:
            # fused path: extraction + prepass DFA + verdict + compaction
            # in ONE native pass; all-kept chunks return the input
            # buffer untouched (zero copies). The walk discovers the
            # record count, so the triple return lets the engine skip
            # its counting pre-pass entirely.
            t0 = _time.perf_counter()
            got = native.grep_filter(
                data, self._local_tables("_native_filter"),
                n_hint=n_records)
            if got is None:
                return None
            n, n_keep, out = got
            tm.add("kernel_s", _time.perf_counter() - t0)
            tm.add("records", n)
            return (n_keep, out, n)
        if use_native:
            t0 = _time.perf_counter()
            got = native.grep_match(
                data, self._local_tables("_native_tables"),
                n_hint=n_records
            )
            if got is None:
                return None
            mask, offsets, n = got
            tm.add("kernel_s", _time.perf_counter() - t0)
        else:
            if n_records is not None and n_records < self.tpu_batch_records:
                return None  # small batches: decode path is cheaper
            got = self._jax_match_raw(data, n_records, mesh=mesh)
            if got is None:
                return None
            mask, offsets, n = got
        tm.add("records", n)
        keep = self.keep_mask(mask)
        n_keep = int(keep.sum())
        if n_keep == n:
            return (n, data)
        if n_keep == 0:
            return (0, b"")
        t0 = _time.perf_counter()
        compacted = native.compact(data, offsets[: n + 1], keep)
        tm.add("compact_s", _time.perf_counter() - t0)
        if compacted is not None:
            return (n_keep, compacted)
        parts = [
            data[offsets[i]: offsets[i + 1]]
            for i in np.nonzero(keep)[0]
        ]
        return (n_keep, b"".join(parts))

    def _approx_match_raw(self, data, engine, n_hint=None):
        """Approximate mask → exact recheck over chunk bytes.

        Returns the EXACT per-rule match matrix (mask[R, n] bool),
        offsets and n — or None to fall back to the plain exact paths.
        Soundness: the reduced DFAs over-approximate their rules'
        languages (regex.dfa.approx_reduce), so a record the mask
        rejects for rule r cannot match rule r exactly; only
        mask-admitted records pay the exact walk. The measured FP rate
        (admitted-but-exact-false) is tracked against
        ``tpu_approx_fp_budget``: a mask that stopped paying for
        itself self-disables instead of taxing every chunk."""
        from .. import native

        got = native.grep_match(
            data, self._local_tables("_approx_tables"), n_hint=n_hint)
        if got is None:
            return None
        amask, offsets, n = got
        union = amask.any(axis=0)
        n_adm = int(union.sum())
        mask = np.zeros(amask.shape, dtype=bool)
        n_true = 0
        if n_adm == n:
            # mask admitted everything: recheck the whole chunk via
            # the plain exact tables (no compaction detour)
            got2 = native.grep_match(
                data, self._local_tables("_native_tables"), n_hint=n)
            if got2 is None:
                return None
            mask = got2[0]
            n_true = int(mask.any(axis=0).sum())
        elif n_adm:
            # by design: the approx-mask exact-recheck gather runs
            # entirely on host bytes (use_native implies no device
            # launch this chunk) — compacting the admitted records is
            # what makes the reduced DFA pay for itself
            # fbtpu-lint: allow(device-host-roundtrip)
            sub = native.compact(data, offsets[: n + 1], union)
            if sub is None:
                idx0 = np.nonzero(union)[0]
                sub = b"".join(data[offsets[i]: offsets[i + 1]]
                               for i in idx0)
            got2 = native.grep_match(
                sub, self._local_tables("_native_tables"), n_hint=n_adm)
            if got2 is None or got2[2] != n_adm:
                return None
            emask = got2[0]
            mask[:, np.nonzero(union)[0]] = emask
            n_true = int(emask.any(axis=0).sum())
        # lock-free window counters (benign-staleness, see _init_approx)
        self._approx_seen += n
        self._approx_fp += n_adm - n_true
        if engine is not None and getattr(
                engine, "m_shrink_approx_admits", None) is not None:
            label = (self.name,)
            # admits are per (rule, record) — mask selectivity;
            # rechecks are per record (the union the exact walk pays)
            engine.m_shrink_approx_admits.inc(int(amask.sum()), label)
            engine.m_shrink_approx_rechecks.inc(n_adm, label)
            engine.m_shrink_approx_fp.inc(n_adm - n_true, label)
        if self._approx_seen >= 8192:
            fp_rate = self._approx_fp / max(self._approx_seen, 1)
            if fp_rate > self.tpu_approx_fp_budget:
                self._approx_live = False
                log.warning(
                    "grep approx mask disabled: measured FP rate %.3f "
                    "exceeds tpu_approx_fp_budget %.3f (over %d "
                    "records)", fp_rate, self.tpu_approx_fp_budget,
                    self._approx_seen)
                if engine is not None and getattr(
                        engine, "m_shrink_approx_disabled", None) \
                        is not None:
                    engine.m_shrink_approx_disabled.inc(1, (self.name,))
            else:
                # rolling window: decay instead of one-shot judgement
                self._approx_seen //= 2
                self._approx_fp //= 2
        return mask, offsets, n

    def _local_tables(self, attr: str):
        """This thread's private copy of a packed native table set (the
        multi-input scaling fix: concurrent ingest workers each walk
        their own arrays instead of hammering one shared set)."""
        tls = self._tls_tables
        t = getattr(tls, attr, None)
        if t is None:
            t = getattr(self, attr).thread_copy()
            setattr(tls, attr, t)
        return t

    def _jax_match_raw(self, data, n_records, mesh=None):
        """Device-kernel raw matching with double-buffered staging.

        The chunk's records split into fixed-size segments; host
        msgpack extraction (native.stage_field over the segment's byte
        span) of segment N+1 runs while segment N's kernel is in
        flight (jax async dispatch — core.chunk_batch.double_buffered),
        and each mask is forced one segment behind. On a real
        accelerator the staging walk hides behind the DFA scan; single-
        segment chunks degrade to the stage-then-match order.

        With ``mesh`` set, each segment launches through the
        explicitly partitioned pjit matcher instead: the batch axis is
        padded to the mesh size and sharded across devices, extraction
        stages STRAIGHT into the [R, Bp, L] transfer matrix
        (native.stage_field_into — the walk fans out across cores
        behind FBTPU_STAGE_THREADS, so per-device shards extract in
        parallel), and the staged buffers are donated to the kernel.
        The next segment's extraction overlaps the in-flight sharded
        launch exactly as on one device.
        Returns (mask[R, n], offsets[n+1], n) or None to decline."""
        import os as _os
        import time as _time

        from .. import native
        from ..core.chunk_batch import double_buffered, segment_bounds
        from ..ops.batch import bucket_size

        tm = self.raw_timings
        if not isinstance(data, bytes):
            data = bytes(data)
        # default matches a bucket_size rung exactly: a full segment
        # stages with ZERO pad rows (8192 would round up to the 16384
        # bucket and double every segment's staging + kernel work)
        seg = int(_os.environ.get("FBTPU_SEGMENT_RECORDS", "4096"))
        n = n_records
        offsets = None
        if n is None or n > seg:
            # segmentation (or an unknown count) needs the boundary
            # table up front; single-segment chunks with a known count
            # skip this walk and take the offsets the first
            # stage_field call discovers anyway
            offsets = native.scan_offsets(data)
            if offsets is None:
                return None
            n = len(offsets) - 1
        if n < self.tpu_batch_records:
            return None  # small batches: decline BEFORE staging/kernel
        by_key: dict = {}
        for r, rule in enumerate(self.rules):
            by_key.setdefault(rule.ra.head.encode("utf-8"), []).append(r)
        R = len(self.rules)
        Lmax = self.tpu_max_record_len
        bounds = segment_bounds(n, seg)
        multi = len(bounds) > 1
        extract_s = [0.0]
        lens_parts: list = []
        cnts: list = []
        offs_box = [offsets]  # filled by staging when not pre-scanned

        n_dev = mesh.devices.size if mesh is not None else 1

        def stages():
            for s, e in bounds:
                t0 = _time.perf_counter()
                cnt = e - s
                span = data if offs_box[0] is None \
                    else data[offs_box[0][s]: offs_box[0][e]]
                if mesh is not None:
                    # mesh staging: ONE jit-stable width (the sharded
                    # program wants one compiled shape, not per-chunk
                    # L buckets) and extraction lands straight in the
                    # [R, Bp, L] transfer matrix — no arena copy, the
                    # native pool splits the walk across cores
                    Bp = bucket_size(seg if multi else cnt,
                                     max_len=Lmax, multiple_of=n_dev)
                    batch = np.empty((R, Bp, Lmax), dtype=np.uint8)
                    lengths = np.full((R, Bp), -1, dtype=np.int32)
                    for key, idxs in by_key.items():
                        r0 = idxs[0]
                        # single-segment chunks take the boundary
                        # table straight from the staging walk (it
                        # computes one anyway) — never re-scan
                        want_offs = offs_box[0] is None
                        offs = np.empty(cnt + 1, dtype=np.int64) \
                            if want_offs else None
                        count = native.stage_field_into(
                            span, key, batch[r0], lengths[r0],
                            n_hint=cnt, offsets_out=offs)
                        if count is None or count != cnt:
                            raise _RawDecline
                        if want_offs:
                            offs_box[0] = offs
                        for r in idxs[1:]:
                            batch[r, :cnt] = batch[r0, :cnt]
                            lengths[r, :cnt] = lengths[r0, :cnt]
                    extract_s[0] += _time.perf_counter() - t0
                    yield batch, lengths, cnt
                    continue
                staged = {}
                max_staged = 1
                for key in by_key:
                    # stage straight into a caller-owned [cnt, Lmax]
                    # matrix: no arena round-trip, so multi-key rule
                    # sets keep ONE copy per key (the L-bucketed slice
                    # into the segment batch below) instead of two
                    want_offs = offs_box[0] is None
                    offs = np.empty(cnt + 1, dtype=np.int64) \
                        if want_offs else None
                    wide = np.empty((cnt, Lmax), dtype=np.uint8)
                    wlen = np.full((cnt,), -1, dtype=np.int32)
                    count = native.stage_field_into(
                        span, key, wide, wlen, n_hint=cnt,
                        offsets_out=offs)
                    if count is None or count != cnt:
                        raise _RawDecline
                    if want_offs:
                        # single-segment: the staging walk's boundary
                        # table serves overflow decode + compaction
                        # (same values whichever key discovered them)
                        offs_box[0] = offs
                    staged[key] = (wide, wlen)
                    mx = int(wlen[:cnt].max()) if cnt else 0
                    max_staged = max(max_staged, mx)
                # scan-length bucketing: the DFA scan is sequential in
                # L, so clamp to the longest staged value (rounded to a
                # small bucket set for jit shape stability)
                L = _len_bucket(max_staged, Lmax)
                # segment-uniform batch shape: one compile covers every
                # full segment of the chunk stream
                Bp = bucket_size(seg if multi else cnt, max_len=L)
                batch = np.zeros((R, Bp, L), dtype=np.uint8)
                lengths = np.full((R, Bp), -1, dtype=np.int32)
                for key, idxs in by_key.items():
                    b, ln = staged[key]
                    for r in idxs:
                        batch[r, :cnt] = b[:cnt, :L]
                        lengths[r, :cnt] = ln[:cnt]
                extract_s[0] += _time.perf_counter() - t0
                yield batch, lengths, cnt

        lane = self._lane()

        def dispatch(item):
            batch, lengths, cnt = item
            lens_parts.append(lengths[:, :cnt])
            cnts.append(cnt)
            if mesh is not None:
                # sharded launch through the device fault domain: the
                # launch closure re-stages (fresh device_put + donation)
                # on EVERY attempt — after a failed launch the donated
                # lengths buffer is consumed (deleted aval), so a retry
                # or fallback must read the host arrays, never the
                # device buffers. The counts-free variant skips the
                # per-segment psum the filter verdict never reads.
                # Forcing inside the launch keeps the deadline armed
                # over the whole execution AND preserves the staging
                # overlap (the worker forces while the caller stages
                # the next segment).
                def launch(b=batch, ln=lengths):
                    m = lane.current_mesh()
                    if m is None:
                        # mesh shrunk below 2 devices: serve unsharded
                        return np.asarray(self._program.dispatch(b, ln))
                    m_i32, _, _b2, _bp = self._program.dispatch_mesh(
                        m, b, ln, with_counts=False)
                    return np.asarray(m_i32).astype(bool)
            else:
                def launch(b=batch, ln=lengths):
                    return np.asarray(self._program.dispatch(b, ln))

            def fallback(b=batch, ln=lengths, c=cnt):
                return self._host_mask(b, ln, c)

            return lane.begin(launch, fallback)

        def collect(pending):
            # nothing is committed until here: the segment's verdict is
            # the device result OR the bit-exact host fallback, exactly
            # one of the two (fbtpu-armor)
            return lane.finish(pending)

        t_all = _time.perf_counter()
        try:
            masks = double_buffered(stages(), dispatch, collect)
        except _RawDecline:
            return None
        wall = _time.perf_counter() - t_all
        tm.add("extract_s", extract_s[0])
        tm.add("kernel_s", max(wall - extract_s[0], 0.0))
        offsets = offs_box[0]
        mask = np.concatenate(
            [np.asarray(m)[:, :c] for m, c in zip(masks, cnts)], axis=1)
        lengths = np.concatenate(lens_parts, axis=1)
        # overflow rows (-2): decode just those records on the CPU
        overflow_rows = np.unique(np.nonzero(lengths == -2)[1])
        if len(overflow_rows):
            from ..codec.events import decode_events

            for b_idx in overflow_rows:
                span = bytes(data[offsets[b_idx]: offsets[b_idx + 1]])
                ev = decode_events(span)[0]
                for r, rule in enumerate(self.rules):
                    if lengths[r, b_idx] == -2:
                        mask[r, b_idx] = rule.match(ev.body)
        return mask, offsets, n
