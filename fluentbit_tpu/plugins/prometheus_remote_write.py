"""Prometheus remote-write: server input + client output.

Reference: plugins/in_prometheus_remote_write (HTTP server decoding
snappy-compressed protobuf WriteRequest frames into cmetrics contexts
via cmt_decode_prometheus_remote_write.c) and
plugins/out_prometheus_remote_write (remote_write.c — encodes metrics
chunks with cmt_encode_prometheus_remote_write.c, POSTs with
``Content-Encoding: snappy`` + ``X-Prometheus-Remote-Write-Version:
0.1.0``). Both ends here speak the same wire schema via the from-scratch
``utils/snappy.py`` + ``utils/protobuf.py``:

    message WriteRequest { repeated TimeSeries timeseries = 1; }
    message TimeSeries   { repeated Label labels = 1;
                           repeated Sample samples = 2; }
    message Label        { string name = 1; string value = 2; }
    message Sample       { double value = 1; int64 timestamp = 2; }  # ms
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

from ..codec.chunk import EVENT_TYPE_METRICS
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, registry
from ..utils import protobuf as pb
from ..utils import snappy
from .net_http import HttpServerInputBase
from .outputs_basic import _metrics_payloads
from .outputs_http_based import _HttpDeliveryOutput

# ------------------------------------------------------ wire <-> series


def encode_write_request(series: List[Tuple[List[Tuple[str, str]],
                                            List[Tuple[float, int]]]]) -> bytes:
    """[(labels, [(value, ts_ms)])] → WriteRequest bytes."""
    out = bytearray()
    for labels, samples in series:
        ts_body = bytearray()
        # spec: "Labels MUST be sorted by name" — __name__ sorts first
        # naturally (underscores precede lowercase letters)
        for name, value in sorted(labels):
            lbl = bytearray()
            pb.write_string_field(1, name, lbl)
            pb.write_string_field(2, str(value), lbl)
            pb.write_message_field(1, bytes(lbl), ts_body)
        for value, ts_ms in samples:
            smp = bytearray()
            pb.write_double_field(1, float(value), smp)
            pb.write_varint_field(2, ts_ms & 0xFFFFFFFFFFFFFFFF
                                  if ts_ms < 0 else ts_ms, smp)
            pb.write_message_field(2, bytes(smp), ts_body)
        pb.write_message_field(1, bytes(ts_body), out)
    return bytes(out)


def decode_write_request(data: bytes) -> List[Tuple[Dict[str, str],
                                                    List[Tuple[float, int]]]]:
    """WriteRequest bytes → [(labels_dict, [(value, ts_ms)])]."""
    series = []
    for field, wt, body in pb.iter_fields(data):
        if field != 1 or wt != 2:
            continue
        labels: Dict[str, str] = {}
        samples: List[Tuple[float, int]] = []
        for f2, w2, val in pb.iter_fields(body):
            if f2 == 1 and w2 == 2:  # Label
                name = value = ""
                for f3, w3, v3 in pb.iter_fields(val):
                    if f3 == 1 and w3 == 2:
                        name = v3.decode("utf-8", "replace")
                    elif f3 == 2 and w3 == 2:
                        value = v3.decode("utf-8", "replace")
                if name:
                    labels[name] = value
            elif f2 == 2 and w2 == 2:  # Sample
                v = 0.0
                ts = 0
                for f3, w3, v3 in pb.iter_fields(val):
                    if f3 == 1 and w3 == 1:
                        v = pb.decode_double(v3)
                    elif f3 == 2 and w3 == 0:
                        ts = pb.to_int64(v3)
                samples.append((v, ts))
        series.append((labels, samples))
    return series


def payloads_to_series(payloads: List[dict]):
    """Internal metrics snapshots → remote-write timeseries. Histograms
    expand to the _bucket/_sum/_count convention (the same expansion
    cmt_encode_prometheus_remote_write.c performs)."""
    series = []
    for payload in payloads:
        for m in payload.get("metrics", []):
            fq = m.get("name", "")
            keys = tuple(m.get("labels", []))
            ts_ms = int(float(m.get("ts") or time.time()) * 1000)
            if m.get("type") == "histogram":
                buckets = m.get("buckets", [])
                for h in m.get("hist", []):
                    lv = tuple(str(x) for x in h.get("labels", []))
                    base = list(zip(keys, lv))
                    cum = 0
                    counts = h.get("counts", [])
                    from ..core.metrics import _fmt_float
                    for b, c in zip(buckets, counts):
                        cum += c
                        series.append((
                            [("__name__", fq + "_bucket")] + base
                            + [("le", _fmt_float(float(b)))],
                            [(float(cum), ts_ms)]))
                    if len(counts) > len(buckets):
                        cum += counts[-1]
                    series.append((
                        [("__name__", fq + "_bucket")] + base
                        + [("le", "+Inf")], [(float(cum), ts_ms)]))
                    series.append(([("__name__", fq + "_sum")] + base,
                                   [(float(h.get("sum", 0.0)), ts_ms)]))
                    series.append(([("__name__", fq + "_count")] + base,
                                   [(float(cum), ts_ms)]))
            else:
                for s in m.get("values", []):
                    lv = tuple(str(x) for x in s.get("labels", []))
                    series.append((
                        [("__name__", fq)] + list(zip(keys, lv)),
                        [(float(s.get("value", 0.0)), ts_ms)]))
    return series


def series_to_payload(series) -> dict:
    """Decoded timeseries → ONE internal metrics snapshot. Series group
    by metric name (__name__); the label-key set of the first series of
    a name defines the entry's label schema (remote write carries no
    type metadata — entries come back untyped, rendered as gauges,
    matching the reference decoder's cmt untyped context)."""
    entries: Dict[str, dict] = {}
    order: List[str] = []
    ts_s = time.time()
    for labels, samples in series:
        name = labels.get("__name__", "")
        if not name:
            continue
        rest = {k: v for k, v in labels.items() if k != "__name__"}
        entry = entries.get(name)
        if entry is None:
            entry = {"name": name, "type": "gauge", "desc": "",
                     "labels": sorted(rest.keys()), "ts": ts_s,
                     "values": []}
            entries[name] = entry
            order.append(name)
        keys = entry["labels"]
        for value, ts_ms in samples:
            entry["values"].append(
                {"labels": [rest.get(k, "") for k in keys],
                 "value": value})
            if ts_ms:
                entry["ts"] = ts_ms / 1000.0
    return {"meta": {"ts": ts_s},
            "metrics": [entries[n] for n in order]}


# ------------------------------------------------------------- input


@registry.register
class PrometheusRemoteWriteInput(HttpServerInputBase):
    """plugins/in_prometheus_remote_write: POST /api/v1/write server."""

    name = "prometheus_remote_write"
    description = "Prometheus remote-write server"
    decode_content = False  # snappy framing is part of the protocol
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=8080),
        ConfigMapEntry("uri", "str", default="/api/v1/write"),
        ConfigMapEntry("tag_from_uri", "bool", default=False),
    ]

    def handle_request(self, engine, method, path, headers, body):
        if method != "POST":
            return 405, b"method not allowed"
        want = self.uri or "/api/v1/write"
        if not self.tag_from_uri and path != want:
            return 404, b"not found"
        enc = (headers.get("content-encoding") or "snappy").lower()
        try:
            if enc == "snappy":
                body = snappy.decompress(body)
            elif enc in ("identity", ""):
                pass
            else:
                return 400, b"unsupported content-encoding"
            series = decode_write_request(body)
        except (snappy.SnappyError, pb.ProtobufError, ValueError):
            return 400, b"bad write request"
        if series:
            payload = series_to_payload(series)
            from ..codec.msgpack import packb
            tag = self.instance.tag
            if self.tag_from_uri and path.strip("/"):
                tag = path.strip("/").replace("/", ".")
            engine.input_event_append(
                self.instance, tag, packb(payload), EVENT_TYPE_METRICS,
                n_records=len(payload["metrics"]))
        # 204: the success status prometheus expects from a receiver
        return 204, b""


# ------------------------------------------------------------ output


@registry.register
class PrometheusRemoteWriteOutput(_HttpDeliveryOutput):
    """plugins/out_prometheus_remote_write."""

    name = "prometheus_remote_write"
    event_types = (EVENT_TYPE_METRICS,)
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=8080),
        ConfigMapEntry("uri", "str", default="/api/v1/write"),
        ConfigMapEntry("http_user", "str"),
        ConfigMapEntry("http_passwd", "str", default=""),
        ConfigMapEntry("add_label", "slist", multiple=True,
                       slist_max_split=1),
        ConfigMapEntry("header", "slist", multiple=True,
                       slist_max_split=1),
    ]

    def init(self, instance, engine) -> None:
        self._extra_labels = []
        for pair in self.add_label or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                self._extra_labels.append((parts[0], parts[1]))

    def _content_type(self) -> str:
        return "application/x-protobuf"

    def _headers(self) -> List[str]:
        hdrs = ["Content-Encoding: snappy",
                "X-Prometheus-Remote-Write-Version: 0.1.0"]
        if self.http_user:
            import base64
            cred = base64.b64encode(
                f"{self.http_user}:{self.http_passwd}".encode()).decode()
            hdrs.append(f"Authorization: Basic {cred}")
        for pair in self.header or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) == 2:
                hdrs.append(f"{parts[0]}: {parts[1]}")
        return hdrs

    def _format_payloads(self, payloads) -> bytes:
        series = payloads_to_series(payloads)
        if self._extra_labels:
            series = [(labels + self._extra_labels, samples)
                      for labels, samples in series]
        return snappy.compress(encode_write_request(series))

    def format(self, data: bytes, tag: str) -> bytes:
        return self._format_payloads(_metrics_payloads(data))

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        payloads = _metrics_payloads(data)
        if not payloads:
            return FlushResult.ERROR
        return await self._post(self._format_payloads(payloads))
