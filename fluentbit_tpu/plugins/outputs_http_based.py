"""HTTP-based telemetry outputs: es, opensearch, loki, splunk, datadog,
gelf, influxdb.

Reference: plugins/out_es (elasticsearch bulk API, es.c), out_opensearch,
plugins/out_loki (loki.c push API with label sets), plugins/out_splunk
(HEC events), plugins/out_datadog (v1 log intake), plugins/out_gelf
(Graylog GELF), plugins/out_influxdb (line protocol). Each plugin's
``format(data, tag)`` builds the exact wire payload (the unit the
reference exercises through its test-formatter harness,
src/flb_engine_dispatch.c:101-137); delivery rides a shared minimal
HTTP/1.1 client with optional TLS (core.tls — ``tls on`` +
``tls.verify/ca_file/crt_file/key_file`` instance properties).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, Tuple

from ..codec.events import decode_events
from ..codec.msgpack import EventTime
from ..core.config import ConfigMapEntry
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..core.upstream import close_quietly
from ..core.record_accessor import RecordAccessor


def _json_default(o):
    if isinstance(o, EventTime):
        return float(o)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)


def _dumps(obj) -> str:
    return json.dumps(obj, default=_json_default, separators=(",", ":"))


class _HttpDeliveryOutput(OutputPlugin):
    """Shared POST delivery; subclasses define format/uri/headers."""

    def format(self, data: bytes, tag: str) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def _uri(self) -> str:
        return getattr(self, "uri", None) or "/"

    def _content_type(self) -> str:
        return "application/json"

    def _headers(self) -> List[str]:
        return []

    CONNECT_TIMEOUT = 10.0  # net.connect_timeout default (flb_upstream)
    IO_TIMEOUT = 30.0

    def _use_http2(self) -> bool:
        """`http2 on` switches delivery to prior-knowledge h2c
        (reference: flb_http_client_http2.c is selected the same way
        via the client's protocol flags); parsed once at configure."""
        return bool(getattr(self.instance, "http2", False))

    async def _post_h2(self, body: bytes,
                       extra_headers: Optional[List[str]],
                       uri: Optional[str]) -> FlushResult:
        from ..core.http2 import Http2Client
        from ..core.tls import open_connection, tls_enabled

        writer = None
        try:
            reader, writer = await open_connection(
                self.instance, self.host, self.port,
                timeout=self.CONNECT_TIMEOUT,
            )
            scheme = "https" if tls_enabled(self.instance) else "http"
            client = Http2Client(reader, writer, scheme=scheme)
            headers = [("content-type", self._content_type())]
            for h in self._headers() + (extra_headers or []):
                if ":" in h:
                    k, v = h.split(":", 1)
                    headers.append((k.strip().lower(), v.strip()))
            status, _resp = await client.request(
                "POST", f"{self.host}:{self.port}",
                uri or self._uri(), headers, body,
                timeout=self.IO_TIMEOUT,
            )
        except (OSError, ConnectionError, ValueError, IndexError,
                asyncio.TimeoutError, asyncio.IncompleteReadError):
            # ValueError/IndexError: malformed HPACK/frames from the
            # peer — transient server misbehavior, retryable like the
            # HTTP/1 path's parse failures
            return FlushResult.RETRY
        finally:
            if writer is not None:
                close_quietly(writer)
        if 200 <= status < 300 or status in ok_statuses:
            return FlushResult.OK
        if status >= 500 or status in (408, 429):
            return FlushResult.RETRY
        return FlushResult.ERROR

    def _upstream(self):
        """Lazy per-plugin keepalive pool (flb_upstream equivalent;
        net.keepalive* instance properties tune it). With an http://
        ``proxy`` and a plain-HTTP target, the pool dials the proxy."""
        from ..core.upstream import Upstream

        host, port = self.host, self.port
        if self._plain_proxy():
            host, port = self.instance.proxy
        # worker pools run flushes on several OS threads — the lazy
        # init must not race (two pools → one leaks its sockets)
        import threading
        lock = getattr(self, "_pool_lock", None)
        if lock is None:
            lock = self.__dict__.setdefault("_pool_lock",
                                            threading.Lock())
        with lock:
            up = getattr(self, "_pool", None)
            if up is None or (up.host, up.port) != (host, port):
                if up is not None:
                    up.close()
                self._pool = up = Upstream(
                    self.instance, host, port,
                    connect_timeout=self.CONNECT_TIMEOUT)
            return up

    def _plain_proxy(self):
        """Proxy for a plain-http target → absolute-form requests."""
        from ..core.tls import tls_enabled
        return getattr(self.instance, "proxy", None) is not None \
            and not tls_enabled(self.instance)

    async def _post_via_connect(self, wire: bytes,
                                ok_statuses: tuple = ()) -> FlushResult:
        """TLS target behind an http proxy: CONNECT tunnel, then TLS
        handshake toward the origin, one-shot (no pooling across the
        tunnel — the reference marks https proxies FIXME; CONNECT is
        the portable subset)."""
        import ssl as _ssl

        from ..core.tls import client_context, client_server_hostname

        phost, pport = self.instance.proxy
        writer = None
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(phost, pport),
                self.CONNECT_TIMEOUT)
            auth = getattr(self.instance, "proxy_auth", None)
            auth_line = f"Proxy-Authorization: {auth}\r\n" if auth else ""
            writer.write(
                f"CONNECT {self.host}:{self.port} HTTP/1.1\r\n"
                f"Host: {self.host}:{self.port}\r\n"
                f"{auth_line}"
                f"Proxy-Connection: Keep-Alive\r\n\r\n".encode())
            await asyncio.wait_for(writer.drain(), self.IO_TIMEOUT)
            status_line = await asyncio.wait_for(reader.readline(),
                                                 self.IO_TIMEOUT)
            if b" 407" in status_line:
                # proxy auth misconfiguration will not heal on retry
                return FlushResult.ERROR
            if b" 200" not in status_line:
                return FlushResult.RETRY
            while True:  # drain CONNECT response headers
                line = await asyncio.wait_for(reader.readline(),
                                              self.IO_TIMEOUT)
                if line in (b"\r\n", b"\n", b""):
                    break
            ctx = client_context(self.instance) or \
                _ssl.create_default_context()
            sni = client_server_hostname(self.instance) or self.host
            await asyncio.wait_for(
                writer.start_tls(ctx, server_hostname=sni),
                self.IO_TIMEOUT)
            writer.write(wire)
            await asyncio.wait_for(writer.drain(), self.IO_TIMEOUT)
            status, _close, _drained = await self._read_response(reader)
        except (OSError, _ssl.SSLError, IndexError, ValueError,
                asyncio.TimeoutError, asyncio.IncompleteReadError):
            return FlushResult.RETRY
        finally:
            if writer is not None:
                close_quietly(writer)
        if 200 <= status < 300:
            return FlushResult.OK
        if status >= 500 or status in (408, 429):
            return FlushResult.RETRY
        return FlushResult.ERROR

    async def _post(self, body: bytes,
                    extra_headers: Optional[List[str]] = None,
                    uri: Optional[str] = None, verb: str = "POST",
                    ok_statuses: tuple = ()) -> FlushResult:
        if self._use_http2():
            return await self._post_h2(body, extra_headers, uri)
        from ..core.tls import tls_enabled
        proxied = getattr(self.instance, "proxy", None) is not None
        if proxied and tls_enabled(self.instance):
            headers = [
                f"{verb} {uri or self._uri()} HTTP/1.1",
                f"Host: {self.host}:{self.port}",
                f"Content-Length: {len(body)}",
                f"Content-Type: {self._content_type()}",
                "Connection: close",
            ] + self._headers() + (extra_headers or [])
            wire = ("\r\n".join(headers) + "\r\n\r\n").encode() + body
            return await self._post_via_connect(wire, ok_statuses)
        pool = self._upstream()
        # plain target behind a proxy: absolute-form request line +
        # Proxy-Connection (flb_http_client.c fmt_proxy)
        target = uri or self._uri()
        if proxied:
            target = f"http://{self.host}:{self.port}{target}"
        # per-request headers are passed in, never stashed on the
        # instance: concurrent flushes must not see each other's auth
        headers = [
            f"{verb} {target} HTTP/1.1",
            f"Host: {self.host}:{self.port}",
            f"Content-Length: {len(body)}",
            f"Content-Type: {self._content_type()}",
            "Connection: " + ("keep-alive" if pool.keepalive
                              else "close"),
        ] + self._headers() + (extra_headers or [])
        if proxied:
            headers.append("Proxy-Connection: Keep-Alive")
            auth = getattr(self.instance, "proxy_auth", None)
            if auth:
                headers.append(f"Proxy-Authorization: {auth}")
        wire = ("\r\n".join(headers) + "\r\n\r\n").encode() + body
        # one transparent redo when a REUSED keepalive connection turns
        # out dead mid-request (the normal keepalive race; reference
        # upstream does the same by dropping the stale conn)
        for _ in (0, 1):
            try:
                reader, writer, reused, uses = await pool.get()
            except (OSError, asyncio.TimeoutError):
                return FlushResult.RETRY
            responded = [False]  # any response byte seen?
            try:
                writer.write(wire)
                await asyncio.wait_for(writer.drain(), self.IO_TIMEOUT)
                status, conn_close, drained = await self._read_response(
                    reader, responded)
            except (OSError, IndexError, ValueError,
                    asyncio.TimeoutError, asyncio.IncompleteReadError):
                pool.release(reader, writer, reusable=False)
                if reused and not responded[0]:
                    # stale idle connection died BEFORE any response:
                    # safe to redo on a fresh dial. Once the server has
                    # started answering, the request may have been
                    # processed — no silent immediate re-send (the
                    # scheduler's RETRY owns at-least-once from here)
                    continue
                return FlushResult.RETRY
            pool.release(reader, writer,
                         reusable=drained and not conn_close,
                         use_count=uses)
            if 200 <= status < 300 or status in ok_statuses:
                return FlushResult.OK
            if status >= 500 or status in (408, 429):
                return FlushResult.RETRY
            return FlushResult.ERROR
        return FlushResult.RETRY

    async def _read_response(self, reader, responded=None):
        """(status, connection_close, fully_drained) — the body must be
        consumed for the connection to be reusable."""
        status_line = await asyncio.wait_for(reader.readline(),
                                             self.IO_TIMEOUT)
        if responded is not None and status_line:
            responded[0] = True
        status = int(status_line.split()[1])
        length = None
        chunked = False
        conn_close = False
        while True:
            line = await asyncio.wait_for(reader.readline(),
                                          self.IO_TIMEOUT)
            if line in (b"\r\n", b"\n", b""):
                break
            low = line.lower()
            if low.startswith(b"content-length:"):
                length = int(line.split(b":", 1)[1])
            elif low.startswith(b"transfer-encoding:") and \
                    b"chunked" in low:
                chunked = True
            elif low.startswith(b"connection:") and b"close" in low:
                conn_close = True
        drained = False
        if chunked:
            while True:
                size_line = await asyncio.wait_for(
                    reader.readline(), self.IO_TIMEOUT)
                # chunk extensions ("c;name=val") are legal — size is
                # everything before the first ';'
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    # consume optional trailers through the blank line
                    while True:
                        line = await asyncio.wait_for(
                            reader.readline(), self.IO_TIMEOUT)
                        if line in (b"\r\n", b"\n", b""):
                            break
                    break
                await asyncio.wait_for(
                    reader.readexactly(size + 2), self.IO_TIMEOUT)
            drained = True
        elif length is not None:
            await asyncio.wait_for(reader.readexactly(length),
                                   self.IO_TIMEOUT)
            drained = True
        # no length + not chunked: body runs to EOF — not reusable
        return status, conn_close, drained

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        return await self._post(self.format(data, tag))

    def exit(self) -> None:
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.close()  # parked keepalive sockets must not leak


@registry.register
class EsOutput(_HttpDeliveryOutput):
    """plugins/out_es: Elasticsearch _bulk API."""

    name = "es"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=9200),
        ConfigMapEntry("index", "str", default="fluent-bit"),
        ConfigMapEntry("type", "str", default="_doc"),
        ConfigMapEntry("logstash_format", "bool", default=False),
        ConfigMapEntry("logstash_prefix", "str", default="logstash"),
        ConfigMapEntry("logstash_dateformat", "str", default="%Y.%m.%d"),
        ConfigMapEntry("time_key", "str", default="@timestamp"),
        ConfigMapEntry("time_key_format", "str",
                       default="%Y-%m-%dT%H:%M:%S"),
        ConfigMapEntry("include_tag_key", "bool", default=False),
        ConfigMapEntry("tag_key", "str", default="_flb-key"),
        ConfigMapEntry("generate_id", "bool", default=False),
        ConfigMapEntry("suppress_type_name", "bool", default=False),
    ]

    def _index_for(self, ts: float) -> str:
        if self.logstash_format:
            day = time.strftime(self.logstash_dateformat, time.gmtime(ts))
            return f"{self.logstash_prefix}-{day}"
        return self.index

    def _uri(self) -> str:
        return "/_bulk"

    def _content_type(self) -> str:
        return "application/x-ndjson"

    def format(self, data: bytes, tag: str) -> bytes:
        lines: List[str] = []
        for ev in decode_events(data):
            ts = ev.ts_float
            action: Dict[str, Any] = {"_index": self._index_for(ts)}
            if not self.suppress_type_name:
                action["_type"] = self.type
            if self.generate_id:
                import hashlib

                action["_id"] = hashlib.sha1(
                    (ev.raw or _dumps(ev.body).encode())
                ).hexdigest()
            body = dict(ev.body)
            body[self.time_key] = time.strftime(
                self.time_key_format, time.gmtime(ts)
            ) + f".{int((ts % 1) * 1000):03d}Z"
            if self.include_tag_key:
                body[self.tag_key] = tag
            lines.append(_dumps({"create": action}))
            lines.append(_dumps(body))
        return ("\n".join(lines) + "\n").encode()


@registry.register
class OpensearchOutput(EsOutput):
    """plugins/out_opensearch: same bulk wire format as out_es."""

    name = "opensearch"


@registry.register
class LokiOutput(_HttpDeliveryOutput):
    """plugins/out_loki: push API — streams keyed by label sets."""

    name = "loki"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=3100),
        ConfigMapEntry("uri", "str", default="/loki/api/v1/push"),
        ConfigMapEntry("labels", "clist", default="job=fluent-bit"),
        ConfigMapEntry("label_keys", "clist"),
        ConfigMapEntry("line_format", "str", default="json"),
        ConfigMapEntry("drop_single_key", "bool", default=False),
        ConfigMapEntry("tenant_id", "str"),
    ]

    def _headers(self) -> List[str]:
        return [f"X-Scope-OrgID: {self.tenant_id}"] if self.tenant_id else []

    def init(self, instance, engine) -> None:
        # accessors depend only on config: build once, not per record
        self._label_ras = []
        for lk in self.label_keys or []:
            key = lk[1:] if lk.startswith("$") else lk
            self._label_ras.append(
                (key.replace(".", "_"), RecordAccessor("$" + key))
            )
        self._static = {}
        for pair in self.labels or []:
            if "=" in pair:
                k, v = pair.split("=", 1)
                self._static[k.strip()] = v.strip().strip('"')

    def format(self, data: bytes, tag: str) -> bytes:
        streams: Dict[tuple, List[list]] = {}
        for ev in decode_events(data):
            labels = dict(self._static)
            for name, ra in self._label_ras:
                v = ra.get(ev.body)
                if v is not None:
                    labels[name] = str(v)
            body = ev.body
            if self.drop_single_key and isinstance(body, dict) \
                    and len(body) == 1:
                line = str(next(iter(body.values())))
            elif (self.line_format or "json") == "key_value":
                line = " ".join(f"{k}={_dumps(v)}" for k, v in body.items())
            else:
                line = _dumps(body)
            ns = str(int(ev.ts_float * 1e9))
            streams.setdefault(tuple(sorted(labels.items())), []).append(
                [ns, line]
            )
        payload = {"streams": [
            {"stream": dict(k), "values": v} for k, v in streams.items()
        ]}
        return _dumps(payload).encode()


@registry.register
class SplunkOutput(_HttpDeliveryOutput):
    """plugins/out_splunk: HEC event endpoint."""

    name = "splunk"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=8088),
        ConfigMapEntry("splunk_token", "str"),
        ConfigMapEntry("event_source", "str"),
        ConfigMapEntry("event_sourcetype", "str"),
        ConfigMapEntry("event_index", "str"),
        ConfigMapEntry("event_key", "str"),
        ConfigMapEntry("splunk_send_raw", "bool", default=False),
    ]

    def _uri(self) -> str:
        return "/services/collector/event"

    def _headers(self) -> List[str]:
        return ([f"Authorization: Splunk {self.splunk_token}"]
                if self.splunk_token else [])

    def init(self, instance, engine) -> None:
        # static-config accessor: build once, not per flush
        self._event_ra = (RecordAccessor(self.event_key)
                          if self.event_key else None)

    def format(self, data: bytes, tag: str) -> bytes:
        out: List[str] = []
        ekey = self._event_ra
        for ev in decode_events(data):
            if self.splunk_send_raw:
                out.append(_dumps(ev.body))
                continue
            event: Any = ev.body
            if ekey is not None:
                picked = ekey.get(ev.body)
                if picked is not None:
                    event = picked
            entry: Dict[str, Any] = {"time": round(ev.ts_float, 3),
                                     "event": event}
            if self.event_source:
                entry["source"] = self.event_source
            if self.event_sourcetype:
                entry["sourcetype"] = self.event_sourcetype
            if self.event_index:
                entry["index"] = self.event_index
            out.append(_dumps(entry))
        return "\n".join(out).encode()


@registry.register
class DatadogOutput(_HttpDeliveryOutput):
    """plugins/out_datadog: v1 log intake (JSON array)."""

    name = "datadog"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=443),
        ConfigMapEntry("apikey", "str"),
        ConfigMapEntry("dd_service", "str"),
        ConfigMapEntry("dd_source", "str"),
        ConfigMapEntry("dd_tags", "str"),
        ConfigMapEntry("dd_message_key", "str", default="log"),
    ]

    def _uri(self) -> str:
        return f"/v1/input/{self.apikey or ''}"

    def format(self, data: bytes, tag: str) -> bytes:
        out = []
        for ev in decode_events(data):
            entry = dict(ev.body)
            entry["timestamp"] = int(ev.ts_float * 1000)
            msg = entry.pop(self.dd_message_key or "log", None)
            if msg is not None:
                entry["message"] = msg
            entry.setdefault("ddtags", self.dd_tags or "")
            if self.dd_service:
                entry["service"] = self.dd_service
            if self.dd_source:
                entry.setdefault("ddsource", self.dd_source)
            entry.setdefault("ddsource", tag)
            out.append(entry)
        return _dumps(out).encode()


@registry.register
class GelfOutput(_HttpDeliveryOutput):
    """plugins/out_gelf: Graylog GELF 1.1 messages (http mode)."""

    name = "gelf"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=12201),
        ConfigMapEntry("uri", "str", default="/gelf"),
        ConfigMapEntry("gelf_short_message_key", "str", default="log"),
        ConfigMapEntry("gelf_host_key", "str", default="host"),
        ConfigMapEntry("mode", "str", default="http"),
    ]

    def format(self, data: bytes, tag: str) -> bytes:
        return "\n".join(
            m.decode() for m in self.format_messages(data, tag)
        ).encode()

    def format_messages(self, data: bytes, tag: str) -> List[bytes]:
        out = []
        for ev in decode_events(data):
            body = dict(ev.body)
            short = body.pop(self.gelf_short_message_key or "log", "")
            host = body.pop(self.gelf_host_key or "host", tag)
            msg: Dict[str, Any] = {
                "version": "1.1",
                "host": str(host),
                "short_message": str(short),
                "timestamp": round(ev.ts_float, 3),
            }
            for k, v in body.items():
                msg[f"_{k}"] = v  # GELF additional fields
            out.append(_dumps(msg).encode())
        return out

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        # GELF HTTP inputs accept exactly ONE JSON message per request
        for msg in self.format_messages(data, tag):
            res = await self._post(msg)
            if res != FlushResult.OK:
                return res
        return FlushResult.OK


@registry.register
class InfluxdbOutput(_HttpDeliveryOutput):
    """plugins/out_influxdb: line protocol writes."""

    name = "influxdb"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=8086),
        ConfigMapEntry("database", "str", default="fluentbit"),
        ConfigMapEntry("sequence_tag", "str"),
        ConfigMapEntry("tag_keys", "clist"),
    ]

    def _uri(self) -> str:
        return f"/write?db={self.database}"

    def _content_type(self) -> str:
        return "text/plain"

    @staticmethod
    def _escape_tag(v: str) -> str:
        return str(v).replace(" ", "\\ ").replace(",", "\\,") \
            .replace("=", "\\=")

    def format(self, data: bytes, tag: str) -> bytes:
        lines = []
        tag_keys = set(self.tag_keys or [])
        for ev in decode_events(data):
            tags = [self._escape_tag(tag)]
            fields = []
            for k, v in ev.body.items():
                if k in tag_keys:
                    tags.append(f"{self._escape_tag(k)}="
                                f"{self._escape_tag(v)}")
                elif isinstance(v, bool):
                    fields.append(f"{k}={'true' if v else 'false'}")
                elif isinstance(v, (int, float)):
                    fields.append(f"{k}={v}")
                else:
                    s = str(v).replace('"', '\\"')
                    fields.append(f'{k}="{s}"')
            if not fields:
                continue
            ns = int(ev.ts_float * 1e9)
            lines.append(f"{','.join(tags)} {','.join(fields)} {ns}")
        return "\n".join(lines).encode()
