"""node_exporter_metrics + collectd inputs.

Reference: plugins/in_node_exporter_metrics (10160 LoC of /proc &
/sys scrapers emitting prometheus-convention node_* metrics) — this
build covers the core collector set (cpu, meminfo, loadavg,
filesystem, netdev, uname/boot_time); plugins/in_collectd (the
collectd binary "parts" protocol over UDP: typed parts HOST/TIME/
PLUGIN/TYPE/VALUES per the public protocol spec).
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import time
from typing import Dict, List, Optional

from ..codec.chunk import EVENT_TYPE_METRICS
from ..codec.events import encode_event, now_event_time
from ..codec.msgpack import packb
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry

log = logging.getLogger("flb.exporters")


def _gauge(name, desc, samples, label_keys=()):
    return {"name": name, "type": "gauge", "desc": desc,
            "labels": list(label_keys),
            "values": [{"labels": list(l), "value": float(v)}
                       for l, v in samples]}


def _counter(name, desc, samples, label_keys=()):
    e = _gauge(name, desc, samples, label_keys)
    e["type"] = "counter"
    return e


@registry.register
class NodeExporterMetricsInput(InputPlugin):
    name = "node_exporter_metrics"
    description = "host metrics in node_exporter conventions"
    config_map = [
        ConfigMapEntry("scrape_interval", "time", default="5"),
        ConfigMapEntry("path.procfs", "str", default="/proc"),
        ConfigMapEntry("path.sysfs", "str", default="/sys"),
        ConfigMapEntry("collectors", "clist",
                       default="cpu,cpufreq,meminfo,diskstats,filesystem,"
                               "uptime,loadavg,netdev,stat,time,vmstat,"
                               "filefd,uname"),
        ConfigMapEntry("textfile.directory", "str"),
    ]

    def init(self, instance, engine) -> None:
        self.collect_interval = float(self.scrape_interval or 5)
        self._enabled = {c.strip().lower() for c in (self.collectors or [])}
        if self.textfile_directory:
            self._enabled.add("textfile")

    # -- collectors --

    def _cpu(self) -> List[dict]:
        modes = ("user", "nice", "system", "idle", "iowait", "irq",
                 "softirq", "steal")
        samples = []
        with open(os.path.join(self.path_procfs, "stat")) as f:
            for line in f:
                if not line.startswith("cpu") or line.startswith("cpu "):
                    continue
                parts = line.split()
                cpu = parts[0][3:]
                for mode, v in zip(modes, parts[1:9]):
                    samples.append(((cpu, mode), int(v) / 100.0))
        return [_counter("node_cpu_seconds_total",
                         "Seconds the CPUs spent in each mode.",
                         samples, ("cpu", "mode"))]

    def _meminfo(self) -> List[dict]:
        out = []
        with open(os.path.join(self.path_procfs, "meminfo")) as f:
            for line in f:
                key, _, rest = line.partition(":")
                fields = rest.split()
                if not fields:
                    continue
                base = "node_memory_" + key.replace("(", "_").replace(")", "")
                if "kB" in rest:  # unit-less counts (HugePages_*) keep
                    v = int(fields[0]) * 1024  # node_exporter's bare name
                    name = base + "_bytes"
                else:
                    v = int(fields[0])
                    name = base
                out.append(_gauge(name, f"Memory information field {key}.",
                                  [((), v)]))
        return out

    def _loadavg(self) -> List[dict]:
        with open(os.path.join(self.path_procfs, "loadavg")) as f:
            l1, l5, l15 = f.read().split()[:3]
        return [_gauge("node_load1", "1m load average.", [((), float(l1))]),
                _gauge("node_load5", "5m load average.", [((), float(l5))]),
                _gauge("node_load15", "15m load average.",
                       [((), float(l15))])]

    def _filesystem(self) -> List[dict]:
        size, avail = [], []
        seen = set()
        with open(os.path.join(self.path_procfs, "mounts")) as f:
            for line in f:
                dev, mnt, fstype = line.split()[:3]
                if not dev.startswith("/") or mnt in seen:
                    continue
                seen.add(mnt)
                try:
                    st = os.statvfs(mnt)
                except OSError:
                    continue
                labels = (dev, mnt, fstype)
                size.append((labels, st.f_blocks * st.f_frsize))
                avail.append((labels, st.f_bavail * st.f_frsize))
        keys = ("device", "mountpoint", "fstype")
        return [_gauge("node_filesystem_size_bytes",
                       "Filesystem size in bytes.", size, keys),
                _gauge("node_filesystem_avail_bytes",
                       "Filesystem space available to unprivileged users.",
                       avail, keys)]

    def _netdev(self) -> List[dict]:
        rx, tx = [], []
        with open(os.path.join(self.path_procfs, "net/dev")) as f:
            for line in f.readlines()[2:]:
                name, _, rest = line.partition(":")
                parts = rest.split()
                rx.append(((name.strip(),), int(parts[0])))
                tx.append(((name.strip(),), int(parts[8])))
        return [_counter("node_network_receive_bytes_total",
                         "Network device statistic receive_bytes.",
                         rx, ("device",)),
                _counter("node_network_transmit_bytes_total",
                         "Network device statistic transmit_bytes.",
                         tx, ("device",))]

    def _uname(self) -> List[dict]:
        u = os.uname()
        labels = (u.sysname, u.release, u.version, u.machine, u.nodename)
        keys = ("sysname", "release", "version", "machine", "nodename")
        out = [_gauge("node_uname_info", "Labeled system information.",
                      [(labels, 1.0)], keys)]
        try:
            with open(os.path.join(self.path_procfs, "stat")) as f:
                for line in f:
                    if line.startswith("btime "):
                        out.append(_gauge("node_boot_time_seconds",
                                          "Node boot time.",
                                          [((), int(line.split()[1]))]))
        except OSError:
            pass
        return out

    def _diskstats(self) -> List[dict]:
        """/proc/diskstats → the node_exporter core disk series
        (reference in_node_exporter_metrics/ne_diskstats.c; sectors are
        fixed 512-byte units)."""
        reads, read_b, writes, written_b, io_t = [], [], [], [], []
        with open(os.path.join(self.path_procfs, "diskstats")) as f:
            for line in f:
                p = line.split()
                if len(p) < 14:
                    continue
                dev = (p[2],)
                reads.append((dev, int(p[3])))
                read_b.append((dev, int(p[5]) * 512))
                writes.append((dev, int(p[7])))
                written_b.append((dev, int(p[9]) * 512))
                io_t.append((dev, int(p[12]) / 1000.0))
        k = ("device",)
        return [
            _counter("node_disk_reads_completed_total",
                     "The total number of reads completed successfully.",
                     reads, k),
            _counter("node_disk_read_bytes_total",
                     "The total number of bytes read successfully.",
                     read_b, k),
            _counter("node_disk_writes_completed_total",
                     "The total number of writes completed successfully.",
                     writes, k),
            _counter("node_disk_written_bytes_total",
                     "The total number of bytes written successfully.",
                     written_b, k),
            _counter("node_disk_io_time_seconds_total",
                     "Total seconds spent doing I/Os.", io_t, k),
        ]

    def _vmstat(self) -> List[dict]:
        """node_exporter exports the ^(oom_kill|pgpg|pswp|pg.*fault)
        subset of /proc/vmstat (ne_vmstat.c)."""
        import re as _re

        keep = _re.compile(r"^(oom_kill|pgpg|pswp|pg.*fault)")
        out = []
        with open(os.path.join(self.path_procfs, "vmstat")) as f:
            for line in f:
                key, _, val = line.partition(" ")
                if not keep.match(key):
                    continue
                out.append(_counter(f"node_vmstat_{key}",
                                    f"/proc/vmstat information field {key}.",
                                    [((), int(val))]))
        return out

    def _stat(self) -> List[dict]:
        """context switches / interrupts / forks / procs gauges from
        /proc/stat (ne_stat.c)."""
        out = []
        with open(os.path.join(self.path_procfs, "stat")) as f:
            for line in f:
                p = line.split()
                if not p:
                    continue
                if p[0] == "intr":
                    out.append(_counter(
                        "node_intr_total",
                        "Total number of interrupts serviced.",
                        [((), int(p[1]))]))
                elif p[0] == "ctxt":
                    out.append(_counter(
                        "node_context_switches_total",
                        "Total number of context switches.",
                        [((), int(p[1]))]))
                elif p[0] == "processes":
                    out.append(_counter(
                        "node_forks_total", "Total number of forks.",
                        [((), int(p[1]))]))
                elif p[0] == "procs_running":
                    out.append(_gauge(
                        "node_procs_running",
                        "Number of processes in runnable state.",
                        [((), int(p[1]))]))
                elif p[0] == "procs_blocked":
                    out.append(_gauge(
                        "node_procs_blocked",
                        "Number of processes blocked waiting for I/O.",
                        [((), int(p[1]))]))
        return out

    def _filefd(self) -> List[dict]:
        with open(os.path.join(self.path_procfs,
                               "sys/fs/file-nr")) as f:
            alloc, _unused, maximum = f.read().split()[:3]
        return [_gauge("node_filefd_allocated",
                       "File descriptor statistics: allocated.",
                       [((), int(alloc))]),
                _gauge("node_filefd_maximum",
                       "File descriptor statistics: maximum.",
                       [((), int(maximum))])]

    def _cpufreq(self) -> List[dict]:
        """scaling frequencies from sysfs (ne_cpufreq.c); kHz → Hz."""
        import glob as _glob

        cur, mn, mx = [], [], []
        base = os.path.join(self.path_sysfs, "devices/system/cpu")
        for d in sorted(_glob.glob(os.path.join(base, "cpu[0-9]*"))):
            cpu = (os.path.basename(d)[3:],)
            for fname, dest in (("scaling_cur_freq", cur),
                                ("scaling_min_freq", mn),
                                ("scaling_max_freq", mx)):
                try:
                    with open(os.path.join(d, "cpufreq", fname)) as f:
                        dest.append((cpu, int(f.read()) * 1000.0))
                except (OSError, ValueError):
                    continue
        k = ("cpu",)
        out = []
        if cur:
            out.append(_gauge("node_cpu_scaling_frequency_hertz",
                              "Current scaled CPU thread frequency in "
                              "hertz.", cur, k))
        if mn:
            out.append(_gauge("node_cpu_scaling_frequency_min_hertz",
                              "Minimum scaled CPU thread frequency in "
                              "hertz.", mn, k))
        if mx:
            out.append(_gauge("node_cpu_scaling_frequency_max_hertz",
                              "Maximum scaled CPU thread frequency in "
                              "hertz.", mx, k))
        return out

    def _hwmon(self) -> List[dict]:
        """temperature sensors from /sys/class/hwmon (ne_hwmon.c);
        milli-celsius → celsius."""
        import glob as _glob

        temps = []
        for hw in sorted(_glob.glob(
                os.path.join(self.path_sysfs, "class/hwmon/hwmon*"))):
            try:
                with open(os.path.join(hw, "name")) as f:
                    chip = f.read().strip()
            except OSError:
                chip = os.path.basename(hw)
            for t in sorted(_glob.glob(os.path.join(hw, "temp*_input"))):
                sensor = os.path.basename(t)[: -len("_input")]
                try:
                    with open(t) as f:
                        temps.append(((chip, sensor),
                                      int(f.read()) / 1000.0))
                except (OSError, ValueError):
                    continue
        if not temps:
            return []
        return [_gauge("node_hwmon_temp_celsius",
                       "Hardware monitor for temperature.",
                       temps, ("chip", "sensor"))]

    def _time(self) -> List[dict]:
        return [_gauge("node_time_seconds",
                       "System time in seconds since epoch (1970).",
                       [((), time.time())])]

    def _uptime(self) -> List[dict]:
        with open(os.path.join(self.path_procfs, "uptime")) as f:
            up = float(f.read().split()[0])
        return [_counter("node_uptime_seconds_total",
                         "Seconds since the system booted.",
                         [((), up)])]

    def _textfile(self) -> List[dict]:
        """*.prom exposition files (ne_textfile.c / the node_exporter
        textfile collector contract)."""
        import glob as _glob

        from .inputs_net_extra import parse_prometheus_text

        if not self.textfile_directory:
            return []
        out: List[dict] = []
        for path in sorted(_glob.glob(
                os.path.join(self.textfile_directory, "*.prom"))):
            try:
                with open(path, encoding="utf-8") as f:
                    out.extend(parse_prometheus_text(f.read()))
            except OSError as e:
                log.debug("node_exporter textfile %s: %s", path, e)
        return out

    def collect(self, engine) -> None:
        entries: List[dict] = []
        for name, fn in (("cpu", self._cpu), ("meminfo", self._meminfo),
                         ("loadavg", self._loadavg),
                         ("filesystem", self._filesystem),
                         ("netdev", self._netdev), ("uname", self._uname),
                         ("diskstats", self._diskstats),
                         ("vmstat", self._vmstat), ("stat", self._stat),
                         ("filefd", self._filefd),
                         ("cpufreq", self._cpufreq),
                         ("hwmon", self._hwmon), ("time", self._time),
                         ("uptime", self._uptime),
                         ("textfile", self._textfile)):
            if name not in self._enabled:
                continue
            try:
                entries.extend(fn())
            except (OSError, ValueError, UnicodeDecodeError) as e:
                # one broken source (malformed *.prom, short procfs
                # file) must not abort the other collectors' tick
                log.debug("node_exporter: collector %s failed: %s", name, e)
        if not entries:
            return
        payload = {"meta": {"ts": time.time()}, "metrics": entries}
        engine.input_event_append(
            self.instance, self.instance.tag, packb(payload),
            EVENT_TYPE_METRICS, n_records=len(entries),
        )


# ----------------------------------------------------------------- collectd

# part type ids (public collectd binary protocol)
_HOST, _TIME, _PLUGIN, _PLUGIN_INSTANCE, _TYPE, _TYPE_INSTANCE = (
    0x0000, 0x0001, 0x0002, 0x0003, 0x0004, 0x0005)
_VALUES, _INTERVAL, _TIME_HR, _INTERVAL_HR = 0x0006, 0x0007, 0x0008, 0x0009
_DS_COUNTER, _DS_GAUGE, _DS_DERIVE, _DS_ABSOLUTE = 0, 1, 2, 3


def parse_collectd_packet(data: bytes) -> List[dict]:
    """Binary parts → records (one per VALUES part, carrying the
    stateful host/plugin/type context accumulated so far)."""
    out: List[dict] = []
    ctx: Dict[str, object] = {}
    pos = 0
    n = len(data)
    while pos + 4 <= n:
        ptype, plen = struct.unpack_from(">HH", data, pos)
        if plen < 4 or pos + plen > n:
            break
        body = data[pos + 4 : pos + plen]
        pos += plen
        if ptype in (_HOST, _PLUGIN, _PLUGIN_INSTANCE, _TYPE,
                     _TYPE_INSTANCE):
            key = {_HOST: "host", _PLUGIN: "plugin",
                   _PLUGIN_INSTANCE: "plugin_instance", _TYPE: "type",
                   _TYPE_INSTANCE: "type_instance"}[ptype]
            ctx[key] = body.rstrip(b"\x00").decode("utf-8", "replace")
        elif ptype in (_TIME, _TIME_HR, _INTERVAL, _INTERVAL_HR):
            if len(body) != 8:  # malformed part from an untrusted peer:
                continue        # skip it, keep the rest of the packet
            v = struct.unpack(">Q", body)[0]
            if ptype == _TIME:
                ctx["time"] = float(v)
            elif ptype == _TIME_HR:
                ctx["time"] = v / (2 ** 30)
            elif ptype == _INTERVAL:
                ctx["interval"] = float(v)
            else:
                ctx["interval"] = v / (2 ** 30)
        elif ptype == _VALUES:
            if len(body) < 2:
                continue
            count = struct.unpack_from(">H", body, 0)[0]
            if len(body) < 2 + count * 9:
                continue
            kinds = body[2 : 2 + count]
            values = []
            vpos = 2 + count
            for k in kinds:
                raw = body[vpos : vpos + 8]
                vpos += 8
                if k == _DS_GAUGE:
                    values.append(struct.unpack("<d", raw)[0])  # LE!
                elif k == _DS_DERIVE:
                    values.append(struct.unpack(">q", raw)[0])
                else:  # counter/absolute: u64 BE
                    values.append(struct.unpack(">Q", raw)[0])
            rec = dict(ctx)
            rec.pop("interval", None)
            rec["values"] = values
            out.append(rec)
    return out


@registry.register
class CollectdInput(InputPlugin):
    name = "collectd"
    description = "collectd binary protocol over UDP"
    server_task_needed = True
    config_map = [
        ConfigMapEntry("listen", "str", default="0.0.0.0"),
        ConfigMapEntry("port", "int", default=25826),
        ConfigMapEntry("typesdb", "str",
                       desc="accepted for parity; value names default "
                            "to positional 'values'"),
    ]

    def init(self, instance, engine) -> None:
        self.bound_port: Optional[int] = None

    def _emit(self, engine, data: bytes) -> None:
        records = parse_collectd_packet(data)
        if not records:
            return
        out = bytearray()
        for rec in records:
            ts = rec.pop("time", None)
            out += encode_event(rec, ts if ts else now_event_time())
        engine.input_log_append(self.instance, self.instance.tag,
                                bytes(out), len(records))

    async def start_server(self, engine) -> None:
        plugin = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                try:
                    plugin._emit(engine, data)
                except Exception:
                    log.exception("collectd: packet parse failed")

        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=(self.listen, self.port)
        )
        self.bound_port = transport.get_extra_info("sockname")[1]
        try:
            await asyncio.Event().wait()
        finally:
            transport.close()


@registry.register
class ProcessExporterMetricsInput(InputPlugin):
    """Reference: plugins/in_process_exporter_metrics (procfs scraper in
    process_exporter conventions, grouped by comm name)."""

    name = "process_exporter_metrics"
    description = "per-process metrics from procfs (process_exporter)"
    config_map = [
        ConfigMapEntry("scrape_interval", "time", default="5"),
        ConfigMapEntry("path.procfs", "str", default="/proc"),
        ConfigMapEntry("process_include_pattern", "str", default=".*"),
        ConfigMapEntry("process_exclude_pattern", "str"),
    ]

    def init(self, instance, engine) -> None:
        import re

        self.collect_interval = float(self.scrape_interval or 5)
        self._inc = re.compile(self.process_include_pattern or ".*")
        self._exc = (re.compile(self.process_exclude_pattern)
                     if self.process_exclude_pattern else None)
        self._clk = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") \
            else 100
        self._page = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") \
            else 4096

    def _scan(self):
        """Aggregate per-comm: cpu seconds, rss/vsize, threads, fds,
        process count."""
        agg: dict = {}
        for pid in os.listdir(self.path_procfs):
            if not pid.isdigit():
                continue
            base = os.path.join(self.path_procfs, pid)
            try:
                with open(os.path.join(base, "stat")) as f:
                    stat = f.read()
                # comm may contain spaces/parens: fields after rparen
                lp, rp = stat.index("("), stat.rindex(")")
                comm = stat[lp + 1:rp]
                fields = stat[rp + 2:].split()
            except (OSError, ValueError):
                continue  # process exited mid-scan
            if not self._inc.search(comm) or (
                    self._exc is not None and self._exc.search(comm)):
                continue
            utime, stime = int(fields[11]), int(fields[12])
            threads = int(fields[17])
            vsize = int(fields[20])
            rss = int(fields[21]) * self._page
            try:
                fds = len(os.listdir(os.path.join(base, "fd")))
            except OSError:
                fds = 0
            a = agg.setdefault(comm, [0.0, 0, 0, 0, 0, 0])
            a[0] += (utime + stime) / self._clk
            a[1] += rss
            a[2] += vsize
            a[3] += threads
            a[4] += fds
            a[5] += 1
        return agg

    def collect(self, engine) -> None:
        try:
            agg = self._scan()
        except OSError as e:
            log.debug("process_exporter: scan failed: %s", e)
            return
        if not agg:
            return
        keys = ("name",)
        rows = sorted(agg.items())
        entries = [
            _counter("process_cpu_seconds_total",
                     "CPU time per process name.",
                     [((c,), a[0]) for c, a in rows], keys),
            _gauge("process_resident_memory_bytes",
                   "Resident memory per process name.",
                   [((c,), a[1]) for c, a in rows], keys),
            _gauge("process_virtual_memory_bytes",
                   "Virtual memory per process name.",
                   [((c,), a[2]) for c, a in rows], keys),
            _gauge("process_num_threads",
                   "Thread count per process name.",
                   [((c,), a[3]) for c, a in rows], keys),
            _gauge("process_open_fds",
                   "Open file descriptors per process name.",
                   [((c,), a[4]) for c, a in rows], keys),
            _gauge("process_count",
                   "Processes per name.",
                   [((c,), a[5]) for c, a in rows], keys),
        ]
        payload = {"meta": {"ts": time.time()}, "metrics": entries}
        engine.input_event_append(
            self.instance, self.instance.tag, packb(payload),
            EVENT_TYPE_METRICS, n_records=len(entries),
        )
