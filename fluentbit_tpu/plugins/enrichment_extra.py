"""Enrichment extras: filter_aws, filter_ecs, processor
opentelemetry_envelope, processor tda.

Reference: plugins/filter_aws (EC2 instance metadata enrichment via
IMDS), plugins/filter_ecs (ECS task metadata), plugins/
processor_opentelemetry_envelope (attach OTLP resource/scope group
identity), plugins/processor_tda (sliding-window topological anomaly
signal: Betti numbers via the vendored C++ ripser — this build computes
Betti-0 exactly with union-find over the Vietoris–Rips 1-skeleton at a
fixed threshold; Betti-1/2 need full persistent homology and are
reported as unavailable rather than faked).
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional

from ..codec.events import LogEvent
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, ProcessorPlugin, registry
from ..core.record_accessor import RecordAccessor

log = logging.getLogger("flb.enrich")


def _pkg_version() -> str:
    from .. import __version__

    return __version__


class _MetadataHttpFilter(FilterPlugin):
    """Shared one-shot HTTP metadata fetch + per-record merge."""

    def _get(self, host: str, port: int, path: str,
             headers: Optional[Dict[str, str]] = None,
             timeout: float = 2.0) -> Optional[bytes]:
        from ..utils import plain_http_request

        got = plain_http_request(host, port, "GET", path, headers,
                                 timeout=timeout)
        if got is None or got[0] != 200:
            return None
        return got[1]


@registry.register
class AwsFilter(_MetadataHttpFilter):
    """plugins/filter_aws: EC2 instance-metadata enrichment. The IMDS
    endpoint is configurable (``imds_host``) so tests run against a
    stub; fetch happens once at init and failure degrades to
    pass-through with a warning (records still flow)."""

    name = "aws"
    config_map = [
        ConfigMapEntry("imds_host", "str", default="169.254.169.254"),
        ConfigMapEntry("imds_port", "int", default=80),
        ConfigMapEntry("az", "bool", default=True),
        ConfigMapEntry("ec2_instance_id", "bool", default=True),
        ConfigMapEntry("ec2_instance_type", "bool", default=False),
        ConfigMapEntry("private_ip", "bool", default=False),
        ConfigMapEntry("ami_id", "bool", default=False),
        ConfigMapEntry("hostname", "bool", default=False),
    ]

    PATHS = {
        "az": ("/latest/meta-data/placement/availability-zone", "az"),
        "ec2_instance_id": ("/latest/meta-data/instance-id",
                            "ec2_instance_id"),
        "ec2_instance_type": ("/latest/meta-data/instance-type",
                              "ec2_instance_type"),
        "private_ip": ("/latest/meta-data/local-ipv4", "private_ip"),
        "ami_id": ("/latest/meta-data/ami-id", "ami_id"),
        "hostname": ("/latest/meta-data/hostname", "hostname"),
    }

    def init(self, instance, engine) -> None:
        from ..utils import plain_http_request

        self._fields: Dict[str, str] = {}
        # IMDSv2 first: modern instances (HttpTokens=required) reject
        # token-less requests with 401; v1 remains the fallback
        headers = None
        got = plain_http_request(
            self.imds_host, self.imds_port, "PUT", "/latest/api/token",
            {"X-aws-ec2-metadata-token-ttl-seconds": "21600"},
        )
        if got is not None and got[0] == 200 and got[1]:
            headers = {"X-aws-ec2-metadata-token":
                       got[1].decode("ascii", "replace").strip()}
        for opt, (path, key) in self.PATHS.items():
            if not getattr(self, opt):
                continue
            body = self._get(self.imds_host, self.imds_port, path,
                             headers=headers)
            if body is None:
                log.warning("filter_aws: IMDS fetch failed for %s "
                            "(records pass through unenriched)", key)
                continue
            self._fields[key] = body.decode("utf-8", "replace").strip()

    def filter(self, events: list, tag: str, engine) -> tuple:
        from ..core.plugin import FilterResult

        if not self._fields:
            return (FilterResult.NOTOUCH, events)
        out = []
        for ev in events:
            if isinstance(ev.body, dict):
                body = dict(ev.body)
                body.update(self._fields)
                out.append(LogEvent(ev.timestamp, body, ev.metadata,
                                    raw=None))
            else:
                out.append(ev)
        return (FilterResult.MODIFIED, out)


@registry.register
class EcsFilter(_MetadataHttpFilter):
    """plugins/filter_ecs: task metadata from the ECS metadata endpoint
    (ECS_CONTAINER_METADATA_URI_V4 style; endpoint configurable)."""

    name = "ecs"
    config_map = [
        ConfigMapEntry("metadata_host", "str"),
        ConfigMapEntry("metadata_port", "int", default=80),
        ConfigMapEntry("add", "slist", multiple=True, slist_max_split=1,
                       desc="<dest_key> <metadata_key> (cluster/task_arn/"
                            "family/revision...)"),
    ]

    KEYS = {"cluster": "Cluster", "task_arn": "TaskARN",
            "family": "Family", "revision": "Revision"}

    def init(self, instance, engine) -> None:
        import os

        self._fields: Dict[str, str] = {}
        host = self.metadata_host
        base = ""
        if not host:
            uri = os.environ.get("ECS_CONTAINER_METADATA_URI_V4", "")
            if uri.startswith("http://"):
                try:
                    rest = uri[len("http://"):]
                    hostport, _, base_path = rest.partition("/")
                    host, _, p = hostport.partition(":")
                    self.metadata_port = int(p or 80)
                    # the per-container base path (…/v4/<id>) prefixes
                    # the /task endpoint — dropping it 404s on real ECS
                    base = "/" + base_path.rstrip("/") if base_path else ""
                except ValueError:
                    # degrade-to-passthrough contract: a malformed URI
                    # (IPv6 literal etc.) must not fail startup
                    log.warning("filter_ecs: cannot parse metadata URI %r",
                                uri)
                    host = None
        if not host:
            log.warning("filter_ecs: no metadata endpoint (records pass "
                        "through unenriched)")
            return
        body = self._get(host, self.metadata_port, f"{base}/task")
        if body is None:
            log.warning("filter_ecs: metadata fetch failed")
            return
        try:
            task = json.loads(body)
        except ValueError:
            return
        for pair in self.add or []:
            parts = pair if isinstance(pair, list) else pair.split(None, 1)
            if len(parts) != 2:
                continue
            dest, src = parts
            meta_key = self.KEYS.get(src.lower(), src)
            v = task.get(meta_key)
            if v is not None:
                self._fields[dest] = str(v)

    filter = AwsFilter.filter


@registry.register
class OtelEnvelopeProcessor(ProcessorPlugin):
    """plugins/processor_opentelemetry_envelope: stamp records with the
    OTLP resource/scope group identity so out_opentelemetry exports
    them under a proper group (metadata['otlp'], the same shape the
    OTLP input produces)."""

    name = "opentelemetry_envelope"
    description = "attach OTLP resource/scope envelope metadata"
    config_map = []

    def process_logs(self, events: list, tag: str, engine) -> list:
        out = []
        for ev in events:
            meta = dict(ev.metadata) if isinstance(ev.metadata, dict) else {}
            if "otlp" not in meta:
                meta["otlp"] = {
                    "resource": {"service.name": tag},
                    "scope": {"name": "fluentbit_tpu",
                              "version": _pkg_version()},
                }
                out.append(LogEvent(ev.timestamp, ev.body, meta, raw=None))
            else:
                out.append(ev)
        return out


def _gf2_rank(rows: List[int]) -> int:
    """Rank over GF(2) of a bit-matrix (rows as Python ints)."""
    rank = 0
    pivots: List[int] = []
    for row in rows:
        for p in pivots:
            low = p & -p
            if row & low:
                row ^= p
        if row:
            pivots.append(row)
            rank += 1
    return rank


@registry.register
class TdaProcessor(ProcessorPlugin):
    """plugins/processor_tda: sliding-window topological signal. The
    reference computes Betti 0/1/2 with the vendored C++ ripser
    (src/ripser/flb_ripser_wrapper.cpp:39-45; tda.c:735-757). Here the
    Vietoris–Rips complex at ``epsilon`` is built exactly up to its
    3-skeleton: Betti-0 by union-find over the edge set, Betti-1 by the
    identity β1 = E − V + β0 − rank(∂2), Betti-2 by
    β2 = dim ker ∂2 − rank ∂3 = (T − rank ∂2) − rank ∂3 with both
    boundary ranks computed over GF(2) — exact, because Hk depends only
    on the (k+1)-skeleton (tda.c:735-757 emits the same three gauges
    via ripser). Simplex-count guards keep pathological windows from
    stalling ingest — past max_triangles only β0 is stamped; past
    max_tetrahedra β0/β1 are stamped without β2."""

    name = "tda"
    description = "sliding-window Betti-0/1/2 anomaly signal"
    config_map = [
        ConfigMapEntry("fields", "clist",
                       desc="numeric fields forming the point cloud"),
        ConfigMapEntry("window_size", "int", default=32),
        ConfigMapEntry("epsilon", "double", default=1.0),
        ConfigMapEntry("output_key", "str", default="betti_0"),
        ConfigMapEntry("output_key_b1", "str", default="betti_1"),
        ConfigMapEntry("output_key_b2", "str", default="betti_2"),
        ConfigMapEntry("max_triangles", "int", default=20000,
                       desc="β1 guard: beyond this, only β0 is emitted"),
        ConfigMapEntry("max_tetrahedra", "int", default=20000,
                       desc="β2 guard: beyond this, β2 is not emitted"),
    ]

    def init(self, instance, engine) -> None:
        if not self.fields:
            raise ValueError("tda: fields is required")
        self._ras = [RecordAccessor(f if f.startswith("$") else "$" + f)
                     for f in self.fields]
        self._window: List[tuple] = []

    def _betti(self) -> tuple:
        """(β0, β1 | None) of the VR complex at epsilon."""
        pts = self._window
        n = len(pts)
        eps2 = float(self.epsilon) ** 2
        parent = list(range(n))

        def find(a):
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        adj = [[False] * n for _ in range(n)]
        edge_idx: dict = {}
        for i in range(n):
            for j in range(i + 1, n):
                d2 = sum((x - y) ** 2 for x, y in zip(pts[i], pts[j]))
                if d2 <= eps2:
                    adj[i][j] = adj[j][i] = True
                    edge_idx[(i, j)] = len(edge_idx)
                    parent[find(i)] = find(j)
        b0 = len({find(i) for i in range(n)})
        E = len(edge_idx)
        # triangle boundary rows: each triangle flips its 3 edge bits
        rows: List[int] = []
        tri_idx: dict = {}
        for i in range(n):
            for j in range(i + 1, n):
                if not adj[i][j]:
                    continue
                for k in range(j + 1, n):
                    if adj[i][k] and adj[j][k]:
                        tri_idx[(i, j, k)] = len(tri_idx)
                        rows.append((1 << edge_idx[(i, j)])
                                    | (1 << edge_idx[(i, k)])
                                    | (1 << edge_idx[(j, k)]))
                        if len(rows) > self.max_triangles:
                            return b0, None, None  # guard tripped
        r2 = _gf2_rank(rows)
        b1 = E - n + b0 - r2
        # tetrahedra flip their 4 triangle faces (∂3)
        rows3: List[int] = []
        for (i, j, k) in tri_idx:
            for l in range(k + 1, n):
                if adj[i][l] and adj[j][l] and adj[k][l]:
                    rows3.append((1 << tri_idx[(i, j, k)])
                                 | (1 << tri_idx[(i, j, l)])
                                 | (1 << tri_idx[(i, k, l)])
                                 | (1 << tri_idx[(j, k, l)]))
                    if len(rows3) > self.max_tetrahedra:
                        return b0, b1, None
        b2 = (len(tri_idx) - r2) - _gf2_rank(rows3)
        return b0, b1, b2

    def process_logs(self, events: list, tag: str, engine) -> list:
        out = []
        for ev in events:
            if not isinstance(ev.body, dict):
                out.append(ev)
                continue
            point = []
            ok = True
            for ra in self._ras:
                v = ra.get(ev.body)
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    ok = False
                    break
                point.append(float(v))
            if not ok:
                out.append(ev)
                continue
            self._window.append(tuple(point))
            if len(self._window) > self.window_size:
                self._window.pop(0)
            body = dict(ev.body)
            b0, b1, b2 = self._betti()
            body[self.output_key] = b0
            if b1 is not None:
                body[self.output_key_b1] = b1
            if b2 is not None:
                body[self.output_key_b2] = b2
            out.append(LogEvent(ev.timestamp, body, ev.metadata, raw=None))
        return out
