"""in_serial — read records from a serial character device.

Reference: plugins/in_serial/in_serial.c. The device is opened and put
into raw mode at the configured ``bitrate`` with ``VMIN=min_bytes``
(in_serial.c:364-378); here the fd is non-blocking and polled by the
engine's interval collector. Buffer semantics match cb_serial_collect
(in_serial.c:131-270): a leading NUL (FTDI handshake) or bare CR/LF is
consumed; with ``separator`` set, the buffer is split on each
occurrence and every non-empty span becomes ``{"msg": <span>}``; with
``format json``, concatenated JSON values are decoded incrementally
(partial values wait for more bytes, invalid input drops the buffer)
and each value becomes ``{"msg": <value>}``; otherwise every read
drains the whole buffer into a single ``{"msg": <text>}`` record.
"""

from __future__ import annotations

import json
import os

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import InputPlugin, registry

_BUF_MAX = 32 * 1024  # serial line discipline scale, like SERIAL_BUFFER_SIZE


@registry.register
class SerialInput(InputPlugin):
    name = "serial"
    description = "Serial input"
    collect_interval = 0.05
    threaded_capable = True
    config_map = [
        ConfigMapEntry("file", "str"),
        ConfigMapEntry("bitrate", "str"),
        ConfigMapEntry("separator", "str"),
        ConfigMapEntry("format", "str"),
        ConfigMapEntry("min_bytes", "int", default=0),
    ]

    def init(self, instance, engine) -> None:
        if not self.file:
            raise ValueError("serial: 'file' is required")
        if not self.bitrate:
            raise ValueError("serial: 'bitrate' is required")
        fmt = (self.format or "").lower()
        if fmt and fmt not in ("json", "none"):
            raise ValueError(f"serial: unknown format {self.format!r}")
        if fmt == "json" and self.separator:
            # reference: separator wins; format only applies without one
            fmt = ""
        self._json = fmt == "json"
        self._ins = instance
        self._buf = b""
        self._fd = os.open(self.file, os.O_RDWR | os.O_NOCTTY
                           | os.O_NONBLOCK)
        self._tio_orig = None
        try:
            if os.isatty(self._fd):
                self._setup_termios()
        except Exception:
            # a failed instance never gets exit(): close here or leak
            # one fd per rejected hot-reload validation
            os.close(self._fd)
            self._fd = None
            raise

    def _setup_termios(self) -> None:
        import termios

        br = int(self.bitrate)
        speed = getattr(termios, f"B{br}", None)
        if speed is None:
            raise ValueError(f"serial: unsupported bitrate {br}")
        self._tio_orig = termios.tcgetattr(self._fd)
        tio = termios.tcgetattr(self._fd)
        # raw 8N1, like the reference's cfmakeraw-style setup
        tio[0] = 0                      # iflag
        tio[1] = 0                      # oflag
        tio[2] = (termios.CS8 | termios.CREAD | termios.CLOCAL)  # cflag
        tio[3] = 0                      # lflag
        tio[4] = speed                  # ispeed
        tio[5] = speed                  # ospeed
        tio[6][termios.VMIN] = max(0, min(255, self.min_bytes))
        tio[6][termios.VTIME] = 0
        termios.tcsetattr(self._fd, termios.TCSANOW, tio)

    def exit(self) -> None:
        if self._fd is not None:
            if self._tio_orig is not None:
                try:
                    import termios
                    termios.tcsetattr(self._fd, termios.TCSANOW,
                                      self._tio_orig)
                except (OSError, termios.error):
                    pass
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def collect(self, engine) -> None:
        while True:
            try:
                data = os.read(self._fd, _BUF_MAX)
            except BlockingIOError:
                break
            except OSError:
                return
            if not data:
                break
            self._buf += data
            self._drain(engine)
            if len(self._buf) >= _BUF_MAX:
                # no record boundary found and no more space: drop, the
                # reference resets buf_len the same way (in_serial.c:220)
                self._buf = b""
        self._drain(engine)

    def _emit(self, engine, bodies) -> None:
        if not bodies:
            return
        ts = now_event_time()
        buf = b"".join(encode_event({"msg": b}, ts) for b in bodies)
        engine.input_log_append(self._ins, self._ins.tag, buf, len(bodies))

    def _drain(self, engine) -> None:
        # FTDI handshake NUL / stray leading CR-LF removal
        while self._buf[:1] in (b"\x00", b"\r", b"\n"):
            self._buf = self._buf[1:]
        if not self._buf:
            return
        bodies = []
        if self.separator:
            sep = self.separator.encode()
            while True:
                pos = self._buf.find(sep)
                if pos < 0:
                    break
                if pos > 0:
                    bodies.append(
                        self._buf[:pos].decode("utf-8", "replace"))
                self._buf = self._buf[pos + len(sep):]
        elif self._json:
            dec = json.JSONDecoder()
            # decode a strict UTF-8 prefix so a multi-byte character
            # split across reads survives in the byte remainder — text
            # is ALWAYS strictly decoded, keeping the char→byte
            # mapping exact for the consumed-bytes arithmetic below
            while True:
                try:
                    text = self._buf.decode("utf-8")
                    prefix_bytes = len(self._buf)
                    hard_invalid = False
                except UnicodeDecodeError as e:
                    if e.start == 0 and len(self._buf) > 3:
                        # garbage at the buffer head (e.g. a bad byte
                        # retained last round as a possible truncated
                        # tail): skip it and re-sync on what follows
                        self._buf = self._buf[1:]
                        continue
                    text = self._buf[:e.start].decode("utf-8")
                    prefix_bytes = e.start
                    # within the last 3 bytes = possibly a truncated
                    # tail; earlier = hard-invalid (never valid JSON)
                    hard_invalid = e.start < len(self._buf) - 3
                break
            at = 0
            while at < len(text):
                while at < len(text) and text[at] in " \t\r\n":
                    at += 1
                if at >= len(text):
                    break
                try:
                    value, end = dec.raw_decode(text, at)
                except ValueError:
                    head = text[at:].lstrip()
                    if hard_invalid or (
                            head and head[0] not in
                            "{[\"-0123456789tfn"):
                        # cannot ever become valid JSON: drop buffer
                        self._buf = b""
                        self._emit(engine, bodies)
                        return
                    # else: partial value — wait for more bytes
                    break
                bodies.append(value)
                at = end
            if at >= len(text):
                if hard_invalid:
                    # everything up to the bad byte parsed; the bad
                    # byte itself can never become valid JSON
                    self._buf = b""
                else:
                    self._buf = self._buf[prefix_bytes:]
            else:
                self._buf = self._buf[len(text[:at].encode("utf-8")):]
        else:
            bodies.append(self._buf.decode("utf-8", "replace"))
            self._buf = b""
        self._emit(engine, bodies)
