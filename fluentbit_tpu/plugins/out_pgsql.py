"""out_pgsql — insert records into PostgreSQL.

Reference: plugins/out_pgsql (libpq-based; inserts (tag, time, data
jsonb) rows). No libpq in this image, so this speaks the PostgreSQL
frontend/backend protocol v3 directly over asyncio: StartupMessage,
AuthenticationOk / cleartext / MD5 password, then simple-protocol
Query with escaped literals — the same row shape the reference
produces (timestamp, tag varchar, data jsonb).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import struct
from typing import List, Optional, Tuple

from ..codec.events import decode_events
from ..core.config import ConfigMapEntry
from ..core.guard import io_deadline
from ..core.plugin import FlushResult, OutputPlugin, registry
from ..core.upstream import close_quietly
from .outputs_http_based import _json_default

log = logging.getLogger("flb.pgsql")


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


async def _read_msg(reader) -> Tuple[bytes, bytes]:
    tag = await reader.readexactly(1)
    (length,) = struct.unpack("!I", await reader.readexactly(4))
    payload = await reader.readexactly(length - 4)
    return tag, payload


def _quote_literal(s: str) -> str:
    """Single-quoted SQL literal (standard_conforming_strings on)."""
    return "'" + s.replace("'", "''") + "'"


class SqlError(Exception):
    """Backend rejected the statement — the data is the problem, not
    the connection; the chunk must ERROR (drop/DLQ), never retry."""


@registry.register
class PgsqlOutput(OutputPlugin):
    name = "pgsql"
    description = "PostgreSQL insert output (wire protocol v3)"
    config_map = [
        ConfigMapEntry("host", "str", default="127.0.0.1"),
        ConfigMapEntry("port", "int", default=5432),
        ConfigMapEntry("user", "str", default="fluentbit"),
        ConfigMapEntry("password", "str"),
        ConfigMapEntry("database", "str", default="fluentbit"),
        ConfigMapEntry("table", "str", default="fluentbit"),
        ConfigMapEntry("timestamp_key", "str", default="date"),
        ConfigMapEntry("create_table", "bool", default=True),
    ]

    def init(self, instance, engine) -> None:
        self._reader = None
        self._writer = None
        self._created = False

    async def _connect(self) -> None:
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), 10.0)
        params = _cstr("user") + _cstr(self.user) + \
            _cstr("database") + _cstr(self.database) + b"\x00"
        payload = struct.pack("!I", 196608) + params  # protocol 3.0
        self._writer.write(struct.pack("!I", len(payload) + 4) + payload)
        await io_deadline(self._writer.drain(), 10.0)
        while True:
            tag, body = await asyncio.wait_for(
                _read_msg(self._reader), 10.0)
            if tag == b"R":
                (code,) = struct.unpack("!I", body[:4])
                if code == 0:  # AuthenticationOk
                    continue
                if code == 3:  # cleartext password
                    self._writer.write(_msg(
                        b"p", _cstr(self.password or "")))
                    await io_deadline(self._writer.drain(), 10.0)
                    continue
                if code == 5:  # MD5: md5(md5(pw + user) + salt)
                    salt = body[4:8]
                    inner = hashlib.md5(
                        (self.password or "").encode()
                        + self.user.encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._writer.write(_msg(b"p", _cstr("md5" + outer)))
                    await io_deadline(self._writer.drain(), 10.0)
                    continue
                raise ConnectionError(f"unsupported auth method {code}")
            if tag == b"E":
                raise ConnectionError(
                    f"pgsql error during startup: {body!r}")
            if tag == b"Z":  # ReadyForQuery
                return
            # ParameterStatus / BackendKeyData / NoticeResponse: skip

    async def _query(self, sql: str) -> None:
        self._writer.write(_msg(b"Q", _cstr(sql)))
        await io_deadline(self._writer.drain())
        error = None
        while True:
            tag, body = await asyncio.wait_for(
                _read_msg(self._reader), 30.0)
            if tag == b"E":
                error = body
            elif tag == b"Z":
                if error is not None:
                    # the backend answered ReadyForQuery: the
                    # connection is healthy, the STATEMENT failed
                    raise SqlError(f"pgsql error: {error!r}")
                return

    def _rows_sql(self, data: bytes, tag: str) -> Optional[str]:
        values = []
        for ev in decode_events(data):
            doc = json.dumps(ev.body, default=_json_default,
                             separators=(",", ":"))
            # PostgreSQL jsonb cannot store NUL code points
            doc = doc.replace("\\u0000", "")
            values.append(
                f"(to_timestamp({ev.ts_float!r}), "
                f"{_quote_literal(tag)}, "
                f"{_quote_literal(doc)}::jsonb)")
        if not values:
            return None
        table = self.table
        return (f"INSERT INTO {table} (time, tag, data) VALUES "
                + ", ".join(values) + ";")

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        sql = self._rows_sql(data, tag)
        if sql is None:
            return FlushResult.OK  # nothing decodable to insert
        for attempt in (0, 1):  # one reconnect per flush
            try:
                if self._writer is None:
                    await self._connect()
                    if self.create_table and not self._created:
                        await self._query(
                            f"CREATE TABLE IF NOT EXISTS {self.table} "
                            "(time timestamptz, tag varchar, "
                            "data jsonb);")
                        self._created = True
                await self._query(sql)
                return FlushResult.OK
            except SqlError as e:
                # poison data: drop/DLQ the chunk, keep the connection
                log.error("pgsql: statement rejected: %s", e)
                return FlushResult.ERROR
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, struct.error):
                if self._writer is not None:
                    close_quietly(self._writer)
                self._reader = self._writer = None
        return FlushResult.RETRY

    def exit(self) -> None:
        if self._writer is not None:
            try:
                self._writer.write(_msg(b"X", b""))  # Terminate
                self._writer.close()
            except (OSError, RuntimeError):
                pass  # peer gone / loop closed at exit
            self._writer = None
