"""Multiline engine — state-machine line concatenation.

Reference: src/multiline/ (flb_ml.c rule types :88-94, flb_ml_rule.c
state machine, flb_ml_stream.c per-stream buffering, and the built-in
language parsers flb_ml_parser_docker/cri/go/java/python/ruby). Used by
in_tail (``multiline.parser``) and filter_multiline.

Model: a parser is a set of rules ``(from_states, regex, to_state)``.
A stream feeds lines; a group opens when a rule from ``start_state``
matches, continues while a rule from the current state matches, and
closes (concatenated emit) on the first non-matching line — which is
then re-fed as a fresh line. ``flush_ms`` bounds how long a pending
group may wait for its continuation.

Built-ins are re-specified from the well-known public formats (docker
JSON logs, CRI-O, Go panics, Java stack traces, Python tracebacks) —
not copies of the reference's tables.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..regex import FlbRegex

DEFAULT_FLUSH_MS = 2000


class MLRule:
    __slots__ = ("from_states", "regex", "to_state")

    def __init__(self, from_states: Sequence[str], pattern: str,
                 to_state: str):
        self.from_states = tuple(from_states)
        self.regex = FlbRegex(pattern)
        self.to_state = to_state


class MLParser:
    """A named multiline parser (flb_ml_parser)."""

    def __init__(self, name: str, rules: Sequence[MLRule],
                 flush_ms: int = DEFAULT_FLUSH_MS, sep: str = "\n",
                 key_content: str = "log"):
        self.name = name
        self.rules = list(rules)
        self.flush_ms = flush_ms
        self.sep = sep
        self.key_content = key_content

    def rules_from(self, state: str) -> List[MLRule]:
        return [r for r in self.rules if state in r.from_states]

    def matches_start(self, line: str) -> Optional[str]:
        for r in self.rules_from("start_state"):
            if r.regex.match(line):
                return r.to_state
        return None


class MLStream:
    """Per-source concatenation state (flb_ml_stream).

    Accepts several parsers: when no group is open each parser's start
    rules are tried IN ORDER (the reference tries the configured parser
    list per stream); the parser that opened the group owns it until it
    closes.
    """

    __slots__ = ("parsers", "active", "emit", "state", "lines",
                 "opened_at", "meta", "flush_ms")

    def __init__(self, parsers, emit: Callable[[str, object], None],
                 flush_ms: Optional[int] = None):
        if isinstance(parsers, MLParser):
            parsers = [parsers]
        self.parsers = list(parsers)
        self.active: Optional[MLParser] = None
        self.emit = emit  # emit(concatenated_text, context)
        self.state: Optional[str] = None
        self.lines: List[str] = []
        self.opened_at = 0.0
        self.meta = None  # caller context of the group's FIRST line
        self.flush_ms = (flush_ms if flush_ms is not None
                         else self.parsers[0].flush_ms)

    def feed(self, line: str, ctx=None) -> None:
        if self.state is not None:
            for r in self.active.rules_from(self.state):
                if r.regex.match(line):
                    self.lines.append(line)
                    self.state = r.to_state
                    return
            self._close()
        for parser in self.parsers:
            to = parser.matches_start(line)
            if to is not None:
                self.active = parser
                self.state = to
                self.lines = [line]
                self.opened_at = time.monotonic()
                self.meta = ctx
                return
        self.emit(line, ctx)

    def _close(self) -> None:
        if self.lines:
            self.emit(self.active.sep.join(self.lines), self.meta)
        self.state = None
        self.active = None
        self.lines = []
        self.meta = None

    def flush(self) -> None:
        """Force out any pending group (shutdown / timeout)."""
        self._close()

    def timed_out(self) -> bool:
        return (
            self.state is not None
            and (time.monotonic() - self.opened_at) * 1000 >= self.flush_ms
        )


# ------------------------------------------------------------- built-ins

def _builtin_go() -> MLParser:
    return MLParser("go", [
        MLRule(["start_state"], r"^(panic:|fatal error:)", "after_panic"),
        MLRule(["after_panic", "trace"],
               r"^(goroutine \d+|\s|\[signal|created by |exit status "
               r"|runtime\.|.*\.go:\d+|[A-Za-z0-9_.\-/*()]+\()", "trace"),
    ])


def _builtin_java() -> MLParser:
    return MLParser("java", [
        MLRule(["start_state"],
               r"^.+(Exception|Error)(: .*)?$", "after_exc"),
        MLRule(["after_exc", "frames"],
               r"^([\t ]+(at |\.\.\. |Suppressed: )|Caused by: )", "frames"),
    ])


def _builtin_python() -> MLParser:
    return MLParser("python", [
        MLRule(["start_state"],
               r"^Traceback \(most recent call last\):", "frames"),
        MLRule(["frames"], r"^[\t ]+", "frames"),
        # the final "SomeError: message" line completes the group; the
        # closing state has no outgoing rules so the next line closes it
        MLRule(["frames"], r"^\S+(Error|Exception|Interrupt|Exit)", "done"),
    ])


def _builtin_ruby() -> MLParser:
    return MLParser("ruby", [
        MLRule(["start_state"], r"^.+Error \(.+\):", "frames"),
        MLRule(["frames"], r"^[\t ]+(from )?", "frames"),
    ])


#: cri lines: "<time> <stream> <P|F> <content>" — P keeps the group open
CRI_REGEX = (
    r"^(?<time>[^ ]+) (?<stream>stdout|stderr) (?<flag>[FP]) (?<log>.*)$"
)


BUILTINS: Dict[str, Callable[[], MLParser]] = {
    "go": _builtin_go,
    "java": _builtin_java,
    "python": _builtin_python,
    "ruby": _builtin_ruby,
}


def get_builtin(name: str) -> Optional[MLParser]:
    fn = BUILTINS.get(name.lower())
    return fn() if fn else None


class DockerStream:
    """Built-in 'docker' mode: JSON-log fragments concat until the
    content ends with a newline (daemon 16K splits)."""

    __slots__ = ("emit", "parts", "meta", "opened_at", "flush_ms")

    def __init__(self, emit, flush_ms: int = DEFAULT_FLUSH_MS):
        self.emit = emit
        self.parts: List[str] = []
        self.meta = None
        self.opened_at = 0.0
        self.flush_ms = flush_ms

    def feed(self, content: str, ctx=None) -> None:
        if not self.parts:
            self.meta = ctx
            self.opened_at = time.monotonic()
        self.parts.append(content)
        if content.endswith("\n"):
            self.emit("".join(self.parts).rstrip("\n"), self.meta)
            self.parts = []
            self.meta = None

    def flush(self) -> None:
        if self.parts:
            self.emit("".join(self.parts).rstrip("\n"), self.meta)
            self.parts = []
            self.meta = None

    def timed_out(self) -> bool:
        return bool(self.parts) and (
            (time.monotonic() - self.opened_at) * 1000 >= self.flush_ms
        )


class CriStream:
    """Built-in 'cri' mode: the P/F flag drives grouping; the emitted
    context is the parsed (time, stream, log) of the FIRST fragment."""

    __slots__ = ("emit", "parts", "meta", "opened_at", "flush_ms", "_rx")

    def __init__(self, emit, flush_ms: int = DEFAULT_FLUSH_MS):
        self.emit = emit
        self.parts: List[str] = []
        self.meta = None
        self.opened_at = 0.0
        self.flush_ms = flush_ms
        self._rx = FlbRegex(CRI_REGEX)

    def feed(self, line: str, ctx=None) -> None:
        got = self._rx.parse_record(line)
        if got is None:
            self.flush()
            self.emit(line, ctx)
            return
        if not self.parts:
            self.meta = ctx
            self.opened_at = time.monotonic()
        self.parts.append(got.get("log", ""))
        if got.get("flag") == "F":
            self.emit("".join(self.parts), self.meta)
            self.parts = []
            self.meta = None

    def flush(self) -> None:
        if self.parts:
            self.emit("".join(self.parts), self.meta)
            self.parts = []
            self.meta = None

    def timed_out(self) -> bool:
        return bool(self.parts) and (
            (time.monotonic() - self.opened_at) * 1000 >= self.flush_ms
        )


def create_stream(parser_names, resolver, emit,
                  flush_ms: Optional[int] = None):
    """Stream factory. ``parser_names`` is a name or list of names tried
    in order per stream; ``resolver`` maps a name to a user-defined
    MLParser (or None → built-ins). 'docker'/'cri' have dedicated
    stream types and cannot be combined with rule parsers.

    ``flush_ms=None`` defers to the (first) parser's configured
    Flush_Timeout; an explicit value (filter_multiline's flush_ms)
    overrides it."""
    if isinstance(parser_names, str):
        parser_names = [parser_names]
    if resolver is None:
        resolver = lambda name: None  # noqa: E731
    elif isinstance(resolver, dict):
        table = resolver
        resolver = table.get
    lows = [n.lower() for n in parser_names]
    if "docker" in lows or "cri" in lows:
        if len(lows) > 1:
            raise ValueError(
                "multiline: docker/cri cannot combine with other parsers"
            )
        ms = flush_ms if flush_ms is not None else DEFAULT_FLUSH_MS
        return (DockerStream(emit, ms) if lows[0] == "docker"
                else CriStream(emit, ms))
    parsers = []
    for name in parser_names:
        parser = resolver(name) or get_builtin(name.lower())
        if parser is None:
            raise ValueError(f"unknown multiline parser {name!r}")
        parsers.append(parser)
    return MLStream(parsers, emit, flush_ms)
